"""Deterministic fault-tolerance semantics: replay-or-reject, restart
tombstones, per-spec death-retry accounting, transitive lineage
reconstruction, spill-restore composition, and crash-mode storage — the
single-process half of the proof tree (raymc exhausts the
interleavings, the slow chaos suite drives real processes; these pin
the DECISIONS deterministically).

Reference semantics under test: `gcs_actor_manager.h` restart FSM +
max_task_retries (actor calls), `task_manager.h` resubmit accounting
(max_retries covers node death), `object_recovery_manager.h` recursive
reconstruction, and GCS store crash durability.
"""

import os
import time
from types import SimpleNamespace

import cloudpickle
import pytest

from ray_tpu import exceptions as exc
from ray_tpu._private.actor_gate import ActorRestartGate, ActorRestartState
from ray_tpu._private.config import ray_config
from ray_tpu._private.ids import ActorID, TaskID
from ray_tpu._private.memory_store import MemoryStore
from ray_tpu._private.task_spec import TaskKind, TaskSpec
from ray_tpu.cluster_utils import ClusterHead, _NodeRecord


def _make_head():
    """Transport-less head over a stub worker + recording backend."""
    worker = SimpleNamespace(memory_store=MemoryStore(), shm_plane=None,
                             gcs=None, backend=None)
    head = ClusterHead(worker, start_server=False)
    submitted = []
    worker.backend = SimpleNamespace(submit=submitted.append)
    head.nodes["n1"] = _NodeRecord("n1", ("127.0.0.1", 7191), {"CPU": 2})
    return head, worker, submitted


def _creation_spec(max_restarts=0):
    spec = TaskSpec(task_id=TaskID.from_random(),
                    kind=TaskKind.ACTOR_CREATION, func=object,
                    args=(), kwargs={}, name="A.__init__",
                    actor_id=ActorID.from_random(),
                    max_restarts=max_restarts)
    spec.assign_return_ids()
    return spec


def _call_spec(creation, max_task_retries=0, name="A.f"):
    spec = TaskSpec(task_id=TaskID.from_random(),
                    kind=TaskKind.ACTOR_TASK, func="f", args=(),
                    kwargs={}, name=name, actor_id=creation.actor_id,
                    max_retries=max_task_retries)
    spec.assign_return_ids()
    return spec


def _stored_error(worker, spec):
    ready, _value, error = worker.memory_store.peek(spec.return_ids[0])
    assert ready, "no outcome stored for the call"
    return error


# -- gate decision units -----------------------------------------------------


def test_gate_fsm_budget_and_tombstone_cause():
    gate = ActorRestartGate()
    gate.register(b"a", 2)
    assert gate.state(b"a") == ActorRestartState.ALIVE
    assert gate.begin_restart(b"a", "its node n1 died")
    assert gate.state(b"a") == ActorRestartState.RESTARTING
    assert gate.restarts_left(b"a") == 1
    gate.ready(b"a")
    assert gate.state(b"a") == ActorRestartState.ALIVE
    assert gate.begin_restart(b"a", "its node n2 died")
    gate.ready(b"a")
    # Budget drained: the third death tombstones with a cause naming it.
    assert not gate.begin_restart(b"a", "its node n3 died")
    assert gate.state(b"a") == ActorRestartState.DEAD
    assert "max_restarts=2" in gate.death_cause(b"a")
    # register() is idempotent: a resubmitted creation spec must not
    # resurrect or refill the actor.
    gate.register(b"a", 2)
    assert gate.state(b"a") == ActorRestartState.DEAD


def test_gate_rollback_ready_returns_to_restarting():
    """A failed creation send unwinds its location gain: the ALIVE flip
    rolls back to RESTARTING so parked calls keep parking instead of
    falling through to a backend that never heard of the actor."""
    gate = ActorRestartGate()
    gate.register(b"a", 1)
    gate.begin_restart(b"a", "its node n1 died")
    gate.ready(b"a")
    assert gate.state(b"a") == ActorRestartState.ALIVE
    gate.rollback_ready(b"a")
    assert gate.state(b"a") == ActorRestartState.RESTARTING
    # Rollback never resurrects the dead.
    gate.mark_dead(b"a", "gone")
    gate.rollback_ready(b"a")
    assert gate.state(b"a") == ActorRestartState.DEAD


def test_gate_infinite_restarts():
    gate = ActorRestartGate()
    gate.register(b"a", -1)
    for i in range(5):
        assert gate.begin_restart(b"a", f"death {i}")
        gate.ready(b"a")
    assert gate.restarts_left(b"a") == -1


def test_gate_replay_authorized_call_parks_not_rejected():
    """Regression (found by the raymc actor_restart scenario while it
    was being built): recover_call consumes the call's last retry to
    authorize the replay — the resubmitted call re-enters route_call
    with max_retries==0 and must PARK for the replacement, not be
    re-judged against the budget it just spent."""
    gate = ActorRestartGate()
    creation = _creation_spec(max_restarts=1)
    aid = creation.actor_id.binary()
    gate.register(aid, 1)
    gate.begin_restart(aid, "its node n1 died")
    call = _call_spec(creation, max_task_retries=1)
    routed = []
    gate.recover_call(
        call,
        resubmit=lambda s: gate.route_call(
            s, dispatch=None, park=lambda x: routed.append("park"),
            fail=lambda x, m, d: routed.append(("reject", m))),
        fail=lambda s, m, d: routed.append(("fail", m)))
    assert routed == ["park"]
    assert call.max_retries == 0 and call.attempt == 1


def test_gate_route_rejects_zero_budget_mid_restart_naming_budget():
    gate = ActorRestartGate()
    creation = _creation_spec(max_restarts=1)
    aid = creation.actor_id.binary()
    gate.register(aid, 1)
    gate.begin_restart(aid, "its node n1 died")
    call = _call_spec(creation, max_task_retries=0)
    out = []
    gate.route_call(call, dispatch=None,
                    park=lambda s: out.append("park"),
                    fail=lambda s, m, d: out.append((m, d)))
    (msg, dead), = out
    assert not dead
    assert "max_task_retries=0" in msg and "RESTARTING" in msg


# -- head-level replay-or-reject --------------------------------------------


def test_inflight_call_with_retry_budget_replays_on_node_death():
    head, worker, submitted = _make_head()
    creation = _creation_spec(max_restarts=1)
    head.record_lineage(creation)
    head.set_actor_node(creation.actor_id.binary(), "n1")
    call = _call_spec(creation, max_task_retries=1)
    head.record_inflight(call, "n1")

    head.mark_node_dead("n1", reason="test kill")

    # The creation spec was resubmitted (restart) and the call REPLAYED
    # (not failed): both reached the backend.
    kinds = [s.kind for s in submitted]
    assert kinds.count(TaskKind.ACTOR_CREATION) == 1
    assert kinds.count(TaskKind.ACTOR_TASK) == 1
    replayed = next(s for s in submitted
                    if s.kind == TaskKind.ACTOR_TASK)
    assert replayed is call
    assert call.max_retries == 0 and call.attempt == 1
    # No error was stored for the call — its outcome is the replay's.
    ready, _v, _e = worker.memory_store.peek(call.return_ids[0])
    assert not ready


def test_inflight_call_without_budget_rejects_naming_state():
    head, worker, submitted = _make_head()
    creation = _creation_spec(max_restarts=1)
    head.record_lineage(creation)
    head.set_actor_node(creation.actor_id.binary(), "n1")
    call = _call_spec(creation, max_task_retries=0)
    head.record_inflight(call, "n1")

    head.mark_node_dead("n1", reason="test kill")

    error = _stored_error(worker, call)
    assert isinstance(error, exc.ActorUnavailableError)
    msg = str(error)
    assert "max_task_retries" in msg and "RESTARTING" in msg


def test_replayed_call_with_applied_output_dedupes():
    """ROADMAP FT gap (a) regression: the call's output REPORT won the
    race — its return object is already resolved in the caller's store
    when the death sweep decides. The replay must DEDUPE on
    return-object identity (no re-execution, no retry-budget burn, the
    resolved value untouched) instead of double-executing."""
    head, worker, submitted = _make_head()
    creation = _creation_spec(max_restarts=1)
    head.record_lineage(creation)
    head.set_actor_node(creation.actor_id.binary(), "n1")
    call = _call_spec(creation, max_task_retries=1)
    head.record_lineage(call)
    head.record_inflight(call, "n1")
    worker.memory_store.put(call.return_ids[0], 41)

    head.mark_node_dead("n1", reason="test kill")

    kinds = [s.kind for s in submitted]
    assert kinds.count(TaskKind.ACTOR_CREATION) == 1  # restart ran
    assert kinds.count(TaskKind.ACTOR_TASK) == 0      # call did NOT
    assert call.max_retries == 1
    assert getattr(call, "attempt", 0) == 0
    ready, value, error = worker.memory_store.peek(call.return_ids[0])
    assert ready and value == 41 and error is None


def test_replayed_call_with_spilled_output_dedupes():
    """Dedupe evidence #2: a durable spilled copy of the output exists
    — the call executed; restore-from-disk (not re-execution) owns
    serving it."""
    head, worker, submitted = _make_head()
    creation = _creation_spec(max_restarts=1)
    head.record_lineage(creation)
    head.set_actor_node(creation.actor_id.binary(), "n1")
    call = _call_spec(creation, max_task_retries=1)
    head.record_lineage(call)
    head.record_inflight(call, "n1")
    head._report_spilled([call.return_ids[0].binary()],
                         ["file:///tmp/rayspec-dedupe-test"])

    head.mark_node_dead("n1", reason="test kill")

    assert [s.kind for s in submitted].count(TaskKind.ACTOR_TASK) == 0
    assert call.max_retries == 1


def test_late_report_from_dead_node_is_ignored():
    """FT gap (a) companion guard: the dying node's last-gasp output
    REPORT lands after the death sweep replayed the call. Applying it
    would re-point the directory at an unreachable address and pop the
    REPLAY's fresh in-flight record; it must be dropped wholesale. A
    surviving node's report still applies."""
    head, worker, submitted = _make_head()
    dead_addr = head.nodes["n1"].address
    creation = _creation_spec(max_restarts=1)
    head.record_lineage(creation)
    head.set_actor_node(creation.actor_id.binary(), "n1")
    call = _call_spec(creation, max_task_retries=1)
    head.record_lineage(call)
    head.record_inflight(call, "n1")

    head.mark_node_dead("n1", reason="test kill")
    assert [s.kind for s in submitted].count(TaskKind.ACTOR_TASK) == 1

    # The replay dispatched to a replacement node.
    head.nodes["n2"] = _NodeRecord("n2", ("127.0.0.1", 7192),
                                   {"CPU": 2})
    head.record_inflight(call, "n2")
    oid = call.return_ids[0].binary()

    head._report_objects([oid], dead_addr)
    assert call.task_id.binary() in head.inflight
    assert head.object_locations.get(oid) is None

    head._report_objects([oid], head.nodes["n2"].address)
    assert call.task_id.binary() not in head.inflight
    assert head.object_locations.get(oid) == tuple(
        head.nodes["n2"].address)


def test_inflight_call_on_budgetless_actor_gets_actor_died():
    head, worker, submitted = _make_head()
    creation = _creation_spec(max_restarts=0)
    head.record_lineage(creation)
    head.set_actor_node(creation.actor_id.binary(), "n1")
    call = _call_spec(creation, max_task_retries=5)
    head.record_inflight(call, "n1")

    head.mark_node_dead("n1", reason="test kill")

    # Retries cannot help a dead actor: typed death naming the budget.
    error = _stored_error(worker, call)
    assert isinstance(error, exc.ActorDiedError)
    assert "max_restarts=0" in str(error)
    assert not any(s.kind == TaskKind.ACTOR_TASK for s in submitted)


def test_tombstoned_actor_fails_fast_not_local_dispatch():
    """Satellite regression: _restart_actor with no budget used to pop
    the actor_nodes entry, so the next submit took the node_id-is-None
    branch into the LOCAL backend (which has never heard of the actor).
    Tombstones must fail the call fast with the recorded cause."""
    from ray_tpu.cluster_utils import ClusterBackendMixin

    head, worker, _submitted = _make_head()
    creation = _creation_spec(max_restarts=0)
    head.record_lineage(creation)
    head.set_actor_node(creation.actor_id.binary(), "n1")
    head.mark_node_dead("n1", reason="test kill")

    local_calls = []
    worker.backend = SimpleNamespace(
        submit=local_calls.append,
        resources=None)
    backend = ClusterBackendMixin(worker, head)
    call = _call_spec(creation, max_task_retries=3)
    backend.submit(call)

    assert local_calls == [], \
        "tombstoned actor call leaked to the local backend"
    error = _stored_error(worker, call)
    assert isinstance(error, exc.ActorDiedError)
    assert "max_restarts=0" in str(error)


def test_parked_call_dispatches_when_restart_completes(monkeypatch):
    from ray_tpu.cluster_utils import ClusterBackendMixin

    monkeypatch.setattr(ray_config, "actor_restart_timeout_s", 5.0)
    head, worker, _submitted = _make_head()
    creation = _creation_spec(max_restarts=1)
    head.record_lineage(creation)
    head.set_actor_node(creation.actor_id.binary(), "n1")
    head.mark_node_dead("n1", reason="test kill")  # -> RESTARTING
    head.nodes["n2"] = _NodeRecord("n2", ("127.0.0.1", 7192),
                                   {"CPU": 2})

    worker.backend = SimpleNamespace(submit=lambda s: None,
                                     resources=None)
    backend = ClusterBackendMixin(worker, head)
    sent = []
    backend._send = lambda record, spec: sent.append(
        (record.node_id, spec))

    call = _call_spec(creation, max_task_retries=1)
    backend.submit(call)  # parks (no live location, RESTARTING)
    assert sent == []

    # Replacement registers: the parked waiter must dispatch to it.
    head.set_actor_node(creation.actor_id.binary(), "n2")
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not sent:
        time.sleep(0.02)
    assert sent and sent[0][0] == "n2" and sent[0][1] is call


def test_parked_call_times_out_with_unavailable_error(monkeypatch):
    from ray_tpu.cluster_utils import ClusterBackendMixin

    monkeypatch.setattr(ray_config, "actor_restart_timeout_s", 0.2)
    head, worker, _submitted = _make_head()
    creation = _creation_spec(max_restarts=1)
    head.record_lineage(creation)
    head.set_actor_node(creation.actor_id.binary(), "n1")
    head.mark_node_dead("n1", reason="test kill")  # restart never done

    worker.backend = SimpleNamespace(submit=lambda s: None,
                                     resources=None)
    backend = ClusterBackendMixin(worker, head)
    call = _call_spec(creation, max_task_retries=1)
    backend.submit(call)

    deadline = time.monotonic() + 5.0
    error = None
    while time.monotonic() < deadline:
        ready, _v, error = worker.memory_store.peek(call.return_ids[0])
        if ready:
            break
        time.sleep(0.02)
    assert isinstance(error, exc.ActorUnavailableError)
    assert "actor_restart_timeout_s" in str(error)


# -- plain-task death-retry accounting ---------------------------------------


def test_lost_task_resubmits_with_decremented_budget():
    head, worker, submitted = _make_head()
    spec = TaskSpec(task_id=TaskID.from_random(),
                    kind=TaskKind.NORMAL_TASK, func=lambda: 1,
                    args=(), kwargs={}, name="t", max_retries=2)
    spec.assign_return_ids()
    head.record_lineage(spec)
    head.record_inflight(spec, "n1")

    head.mark_node_dead("n1", reason="test kill")

    assert submitted == [spec]
    assert spec.max_retries == 1 and spec.attempt == 1


def test_lost_task_with_exhausted_budget_fails_naming_it():
    head, worker, submitted = _make_head()
    spec = TaskSpec(task_id=TaskID.from_random(),
                    kind=TaskKind.NORMAL_TASK, func=lambda: 1,
                    args=(), kwargs={}, name="t", max_retries=0)
    spec.assign_return_ids()
    head.record_lineage(spec)
    head.record_inflight(spec, "n1")

    head.mark_node_dead("n1", reason="test kill")

    assert submitted == []
    error = _stored_error(worker, spec)
    assert isinstance(error, exc.TaskError)
    assert "retry budget is exhausted" in str(error)


# -- transitive reconstruction + spill compose -------------------------------


def _exec_backend(head, worker, log):
    """A backend that 'executes' specs: runs func, stores + reports the
    output (the node-side effect, condensed)."""

    def execute(spec):
        log.append(spec.name)
        value = spec.func()
        worker.memory_store.put(spec.return_ids[0], value)
        head._report_objects([spec.return_ids[0].binary()],
                             head.server.address)

    return SimpleNamespace(submit=execute)


def test_transitive_reconstruction_charges_per_object():
    from ray_tpu.object_ref import ObjectRef

    head, worker, _ = _make_head()
    log = []
    worker.backend = _exec_backend(head, worker, log)
    node_addr = ("127.0.0.1", 7191)

    def chain_spec(name, func, args=()):
        spec = TaskSpec(task_id=TaskID.from_random(),
                        kind=TaskKind.NORMAL_TASK, func=func,
                        args=args, kwargs={}, name=name)
        spec.assign_return_ids()
        head.record_lineage(spec)
        head._report_objects([spec.return_ids[0].binary()], node_addr)
        return spec

    spec_a = chain_spec("a", lambda: 1)
    ref_a = ObjectRef(spec_a.return_ids[0], _register=False)
    spec_b = chain_spec("b", lambda: 2, args=(ref_a,))
    ref_b = ObjectRef(spec_b.return_ids[0], _register=False)
    spec_c = chain_spec("c", lambda: 3, args=(ref_b,))

    head.mark_node_dead("n1", reason="test kill")  # all three lost
    head._maybe_reconstruct(spec_c.return_ids[0].binary())

    # Recursive re-execution in dependency order, each object charged
    # its OWN attempt (not one per chain).
    assert log == ["a", "b", "c"]
    for spec in (spec_a, spec_b, spec_c):
        ready, value, error = worker.memory_store.peek(
            spec.return_ids[0])
        assert ready and error is None
    # _report_objects resets the attempt charge as each lands; the
    # recursion never exceeded one attempt per object.
    assert all(v <= 1 for v in head._recon_attempts.values())


def test_reconstruction_cycle_guard_terminates():
    from ray_tpu.object_ref import ObjectRef

    head, worker, _ = _make_head()
    log = []
    # A backend that does NOT produce outputs: lineage stays lost, so a
    # cycle would recurse forever without the guard.
    worker.backend = SimpleNamespace(
        submit=lambda spec: log.append(spec.name))
    node_addr = ("127.0.0.1", 7191)

    spec_a = TaskSpec(task_id=TaskID.from_random(),
                      kind=TaskKind.NORMAL_TASK, func=lambda: 1,
                      args=(), kwargs={}, name="a")
    spec_a.assign_return_ids()
    spec_b = TaskSpec(task_id=TaskID.from_random(),
                      kind=TaskKind.NORMAL_TASK, func=lambda: 2,
                      args=(ObjectRef(spec_a.return_ids[0],
                                      _register=False),),
                      kwargs={}, name="b")
    spec_b.assign_return_ids()
    # Forge the cycle: a depends on b, b depends on a.
    spec_a.args = (ObjectRef(spec_b.return_ids[0], _register=False),)
    for spec in (spec_a, spec_b):
        head.record_lineage(spec)
        head._report_objects([spec.return_ids[0].binary()], node_addr)
    head.mark_node_dead("n1", reason="test kill")

    head._maybe_reconstruct(spec_b.return_ids[0].binary())  # returns


def test_lost_object_restores_from_spill_not_reexecution(tmp_path):
    from ray_tpu._private.spilling import FileSystemStorage

    head, worker, _ = _make_head()
    log = []
    worker.backend = _exec_backend(head, worker, log)
    node_addr = ("127.0.0.1", 7191)
    spec = TaskSpec(task_id=TaskID.from_random(),
                    kind=TaskKind.NORMAL_TASK,
                    func=lambda: "recomputed", args=(), kwargs={},
                    name="y")
    spec.assign_return_ids()
    oid = spec.return_ids[0]
    head.record_lineage(spec)
    head._report_objects([oid.binary()], node_addr)

    storage = FileSystemStorage(str(tmp_path))
    url = storage.spill(oid, cloudpickle.dumps("from-disk"))
    head._report_spilled([oid.binary()], [url], node_id="n1")

    head.mark_node_dead("n1", reason="test kill")
    head._maybe_reconstruct(oid.binary())

    assert log == [], "spill-backed object re-executed its task"
    ready, value, error = worker.memory_store.peek(oid)
    assert ready and error is None and value == "from-disk"
    # The restored copy is advertised at the head.
    assert head.object_locations[oid.binary()] == head.server.address


def test_stale_spill_url_falls_back_to_reexecution(tmp_path):
    head, worker, _ = _make_head()
    log = []
    worker.backend = _exec_backend(head, worker, log)
    node_addr = ("127.0.0.1", 7191)
    spec = TaskSpec(task_id=TaskID.from_random(),
                    kind=TaskKind.NORMAL_TASK, func=lambda: "redone",
                    args=(), kwargs={}, name="z")
    spec.assign_return_ids()
    oid = spec.return_ids[0]
    head.record_lineage(spec)
    head._report_objects([oid.binary()], node_addr)
    head._report_spilled([oid.binary()],
                         [f"file://{tmp_path}/gone"], node_id="n1")

    head.mark_node_dead("n1", reason="test kill")
    head._maybe_reconstruct(oid.binary())

    assert log == ["z"]
    assert oid.binary() not in head.object_spill_urls  # dropped stale
    ready, value, _err = worker.memory_store.peek(oid)
    assert ready and value == "redone"


def test_lost_actor_output_with_retries_is_lineage_recoverable():
    """Reference semantics: objects created by actor tasks reconstruct
    when the call carries max_task_retries budget — a completed call
    whose output died with its node re-executes through the gate."""
    head, worker, submitted = _make_head()
    creation = _creation_spec(max_restarts=1)
    head.record_lineage(creation)
    head.set_actor_node(creation.actor_id.binary(), "n1")
    call = _call_spec(creation, max_task_retries=1)
    head.record_lineage(call)
    oid = call.return_ids[0]
    head._report_objects([oid.binary()], ("127.0.0.1", 7191))

    head.mark_node_dead("n1", reason="test kill")  # output lost

    # Not poisoned (it IS recoverable)...
    ready, _v, _e = worker.memory_store.peek(oid)
    assert not ready
    # ...and an on-demand locate re-executes the call.
    head._maybe_reconstruct(oid.binary())
    assert any(s is call for s in submitted)


def test_lost_actor_output_without_retries_poisons_fast():
    """A lost object with NO lineage (zero-retry actor call output) and
    no spilled copy can never come back: waiting gets must get a typed
    ObjectLostError now, not hang out the fetch deadline."""
    head, worker, _submitted = _make_head()
    creation = _creation_spec(max_restarts=1)
    head.record_lineage(creation)
    head.set_actor_node(creation.actor_id.binary(), "n1")
    call = _call_spec(creation, max_task_retries=0)
    head.record_lineage(call)  # no-op: zero budget, no lineage entry
    oid = call.return_ids[0]
    head._report_objects([oid.binary()], ("127.0.0.1", 7191))

    head.mark_node_dead("n1", reason="test kill")

    error = _stored_error(worker, call)
    assert isinstance(error, exc.ObjectLostError)
    assert "no lineage or spilled copy" in str(error)


# -- node spill reporting ----------------------------------------------------


def test_memory_store_notifies_spills(monkeypatch):
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.spilling import SpillManager

    monkeypatch.setattr(ray_config, "min_spilling_size_bytes", 1)
    store = MemoryStore()
    store.spill_manager = SpillManager(store, budget_bytes=1)
    seen = []
    store.on_spilled = lambda oid, url: seen.append((oid, url))
    oid = ObjectID.from_random()
    store.put(oid, b"x" * 4096)
    store.spill_manager.maybe_spill()
    assert seen and seen[0][0] == oid \
        and seen[0][1].startswith("file://")
    store.spill_manager.storage.destroy()


# -- crash-mode storage ------------------------------------------------------


def test_sqlite_crash_loses_window_keeps_acked(tmp_path):
    from ray_tpu._private.gcs_storage import SqliteStoreClient

    path = str(tmp_path / "gcs.sqlite")
    store = SqliteStoreClient(path, commit_interval_s=0)
    store._interval = 3600.0  # committer-driven window
    store.put("t", b"acked", b"1")
    store.flush()
    store.put("t", b"riding-the-window", b"2")
    store.crash()

    survivor = SqliteStoreClient(path, commit_interval_s=0)
    try:
        present = {k for k, _ in survivor.get_all("t")}
    finally:
        survivor.close()
    assert present == {b"acked"}
