"""Regression tests for the round-1 advisor findings (ADVICE.md)."""

import numpy as np
import pytest


def test_checkpoint_dir_roundtrips_through_bytes(tmp_path):
    """A directory checkpoint serialized with to_bytes() must come back as
    a directory checkpoint (ADVICE: '__tar__' was never unpacked)."""
    from ray_tpu.air import Checkpoint

    src = tmp_path / "ckpt"
    src.mkdir()
    (src / "weights.bin").write_bytes(b"\x01\x02\x03")
    sub = src / "sub"
    sub.mkdir()
    (sub / "meta.txt").write_text("hello")

    blob = Checkpoint.from_directory(str(src)).to_bytes()
    restored = Checkpoint.from_bytes(blob)

    out = restored.to_directory()
    with open(f"{out}/weights.bin", "rb") as f:
        assert f.read() == b"\x01\x02\x03"
    with open(f"{out}/sub/meta.txt") as f:
        assert f.read() == "hello"
    # to_dict of a dir checkpoint packs file contents.
    d = restored.to_dict()
    assert d["weights.bin"] == b"\x01\x02\x03"


def test_reservoir_buffer_keeps_transitions_coherent():
    """Each stored transition's fields must come from the same incoming
    row (ADVICE: per-key random draws scattered fields across rows)."""
    from ray_tpu.rl.replay_buffer import ReservoirReplayBuffer
    from ray_tpu.rl.sample_batch import SampleBatch

    buf = ReservoirReplayBuffer(capacity=16, seed=0)
    # obs and actions carry the same payload so coherence is checkable.
    for start in range(0, 200, 10):
        ids = np.arange(start, start + 10)
        buf.add(SampleBatch({"obs": ids.astype(np.float32),
                             "actions": ids.astype(np.int64)}))
    assert buf._size == 16
    np.testing.assert_array_equal(
        buf._storage["obs"].astype(np.int64), buf._storage["actions"])


@pytest.mark.parametrize("sq,sk,causal", [(48, 48, False), (100, 100, True),
                                          (64, 100, False)])
def test_flash_attention_ragged_blocks(sq, sk, causal):
    """Sequence lengths not divisible by the block size must not let
    padded K/V columns feed the online softmax (ADVICE: OOB masking)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.attention import attention_reference, flash_attention

    key = jax.random.PRNGKey(0)
    b, h, d = 2, 2, 32
    q = jax.random.normal(key, (b, sq, h, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, sk, h, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, sk, h, d), jnp.float32)
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                          interpret=True)
    ref = attention_reference(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), causal, d ** -0.5).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_rpc_retry_does_not_reexecute():
    """A retried request (same id) must not run the handler twice
    (ADVICE: blind retry broke actor exactly-once semantics)."""
    from ray_tpu._private.rpc import RpcClient, RpcServer

    calls = []

    def bump(n):
        calls.append(n)
        return len(calls)

    server = RpcServer({"bump": bump},
                       dedupe_methods=frozenset({"bump"}))
    try:
        client = RpcClient(server.address)
        assert client.call("bump", n=1) == 1
        # Simulate a connection drop after a processed request: replay the
        # same request id manually and expect the cached reply.
        from ray_tpu._private.rpc import recv_msg, send_msg
        from ray_tpu._private import wire
        import socket

        rid = f"{client._id_prefix}:{client._seq}"
        with socket.create_connection(server.address) as sock:
            send_msg(sock, wire.Request(method="bump", kwargs={"n": 1},
                                        id=rid))
            reply = recv_msg(sock)
        assert reply.ok and reply.result == 1
        assert calls == [1], "handler re-executed on retry"
        client.close()
    finally:
        server.shutdown()


def test_rpc_reply_retained_until_acked_by_next_request():
    """Round-2 ADVICE: the global 4096-entry FIFO could evict a reply
    inside the retry window. Retention is now per client: a reply stays
    until that client's next request acks it, regardless of how much
    traffic other clients generate — and a retry whose reply truly
    expired gets an error, never a re-execution."""
    import socket

    from ray_tpu._private import wire
    from ray_tpu._private.rpc import (RpcClient, RpcServer, recv_msg,
                                      send_msg)

    calls = []

    def bump(n):
        calls.append(n)
        return len(calls)

    server = RpcServer({"bump": bump},
                       dedupe_methods=frozenset({"bump"}))
    try:
        client = RpcClient(server.address)
        assert client.call("bump", n=1) == 1
        rid = f"{client._id_prefix}:{client._seq}"
        # Heavy traffic from *other* clients must not evict the reply.
        for i in range(50):
            with socket.create_connection(server.address) as sock:
                send_msg(sock, wire.Request(method="bump",
                                            kwargs={"n": 0},
                                            id=f"other{i}:1"))
                recv_msg(sock)
        with socket.create_connection(server.address) as sock:
            send_msg(sock, wire.Request(method="bump", kwargs={"n": 1},
                                        id=rid))
            reply = recv_msg(sock)
        assert reply.ok and reply.result == 1, reply
        assert calls.count(1) == 1, "handler re-executed on delayed retry"
        # The client's next request acks (drops) the old reply; a replay
        # of the acked id then re-executes at most by design choice — but
        # what must NEVER happen is a waiter silently re-running. Verify
        # the ack actually pruned the cache.
        assert client.call("bump", n=2) == 52
        prefix = client._id_prefix
        with server._replies_lock:
            seqs = list(server._replies.get(prefix, {}))
        assert seqs == [client._seq], seqs
        client.close()
    finally:
        server.shutdown()


def test_routable_host_loopback_and_node_advertises_reachable_addr():
    """Round-2 ADVICE: transfer endpoints were hard-coded to 127.0.0.1.
    Nodes now advertise the interface that routes to the head."""
    from ray_tpu._private.rpc import routable_host

    assert routable_host(("127.0.0.1", 80)) == "127.0.0.1"
    # For a non-loopback peer the advertised host must be a real local
    # interface address, not loopback (skip if the sandbox has no route).
    host = routable_host(("192.0.2.1", 80))
    assert isinstance(host, str) and host
