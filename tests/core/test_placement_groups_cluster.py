"""Cross-node placement groups (2PC prepare/commit) + scheduling policies.

Reference models: `gcs_placement_group_scheduler.h` (2PC),
`bundle_scheduling_policy.h:82-109` (PACK/SPREAD/STRICT_*),
`scheduling/policy/spread_scheduling_policy.h:27`,
`node_affinity_scheduling_policy.h:29`, and the repo's TPU extension:
`ici_slice` node labels gating gang placement to one contiguous slice.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.placement_group import (
    placement_group,
    remove_placement_group,
)
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SpreadSchedulingStrategy,
)

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture
def cluster():
    c = Cluster(head_node_args={"num_cpus": 1})
    yield c
    c.shutdown()


def test_strict_spread_across_three_nodes(cluster):
    """Three 2-CPU bundles cannot share nodes: head + 2 nodes each take
    exactly one, and tasks pinned to distinct bundles run in distinct
    processes."""
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    # head has 1 CPU; give it room for one 1-CPU bundle
    pg = placement_group([{"CPU": 1}, {"CPU": 2}, {"CPU": 2}],
                         strategy="STRICT_SPREAD")
    assert pg.wait(timeout=60)
    nodes = pg.bundle_nodes
    assert len(set(nodes)) == 3, f"bundles share nodes: {nodes}"

    @ray_tpu.remote(num_cpus=1)
    def where():
        return os.getpid()

    pids = ray_tpu.get([
        where.options(scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=i)).remote()
        for i in range(3)], timeout=60)
    assert len(set(pids)) == 3
    remove_placement_group(pg)


def test_strict_pack_lands_on_one_node(cluster):
    cluster.add_node(num_cpus=4)
    pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="STRICT_PACK")
    assert pg.wait(timeout=60)
    assert len(set(pg.bundle_nodes)) == 1

    @ray_tpu.remote(num_cpus=2)
    def where():
        return os.getpid()

    pids = ray_tpu.get([
        where.options(scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=i)).remote()
        for i in range(2)], timeout=60)
    assert pids[0] == pids[1]
    remove_placement_group(pg)


def test_strict_pack_infeasible_fails_fast(cluster):
    cluster.add_node(num_cpus=2)
    pg = placement_group([{"CPU": 8}, {"CPU": 8}], strategy="STRICT_PACK")
    with pytest.raises(Exception):
        pg.wait(timeout=30)


def test_pack_reserves_and_frees(cluster):
    """PACK across nodes; removing the group returns capacity."""
    node = cluster.add_node(num_cpus=2)
    pg = placement_group([{"CPU": 2}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout=60)
    remove_placement_group(pg)
    # After release the node's full capacity is available again.
    from ray_tpu._private.rpc import RpcClient

    record = cluster.head.nodes[node]
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        info = RpcClient.to(record.address).call("ping")
        if info["available"].get("CPU", 0) == 2:
            return
        time.sleep(0.1)
    raise AssertionError("bundle resources were not returned")


def test_spread_strategy_round_robins(cluster):
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=1)
    def where():
        time.sleep(0.2)
        return os.getpid()

    refs = [where.options(
        scheduling_strategy=SpreadSchedulingStrategy()).remote()
        for _ in range(4)]
    pids = set(ray_tpu.get(refs, timeout=60))
    assert len(pids) >= 2, f"spread used only one process: {pids}"


def test_node_affinity_strategy(cluster):
    node1 = cluster.add_node(num_cpus=2)
    node2 = cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=1)
    def where():
        return os.getpid()

    pid1 = ray_tpu.get(where.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=node1)).remote(), timeout=60)
    pid2 = ray_tpu.get(where.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=node2)).remote(), timeout=60)
    assert pid1 != pid2
    # Same node again → same process.
    assert pid1 == ray_tpu.get(where.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=node1)).remote(), timeout=60)

    # Hard affinity to a missing node fails; soft falls back.
    with pytest.raises(Exception):
        ray_tpu.get(where.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id="node-999")).remote(), timeout=30)
    assert ray_tpu.get(where.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id="node-999", soft=True)).remote(), timeout=30)


def test_ici_slice_gang_placement(cluster):
    """ici_slice="auto" must place every bundle within ONE slice's nodes
    even when capacity exists across slices — the contiguous-slice gang
    constraint (SURVEY.md §7 step 4)."""
    a1 = cluster.add_node(num_cpus=2, labels={"ici_slice": "slice-a"})
    a2 = cluster.add_node(num_cpus=2, labels={"ici_slice": "slice-a"})
    b1 = cluster.add_node(num_cpus=2, labels={"ici_slice": "slice-b"})
    assert b1

    pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="PACK",
                         ici_slice="auto")
    assert pg.wait(timeout=60)
    assert set(pg.bundle_nodes) <= {a1, a2}, pg.bundle_nodes
    remove_placement_group(pg)

    # Pinning to a named slice that cannot fit the group fails fast.
    pg_bad = placement_group([{"CPU": 2}, {"CPU": 2}],
                             strategy="STRICT_SPREAD", ici_slice="slice-b")
    with pytest.raises(Exception):
        pg_bad.wait(timeout=30)
