"""Critical-path attribution engine + flight recorder (PR 18).

Pure-core coverage for `_private/critical_path.py` (stage folding,
late-arrival ingest, waterfalls, exemplars) and
`_private/flight_recorder.py` (rings, edge-triggered dump, debounce),
plus the dashboard surfaces (`/api/slow_requests`, `/api/debug/dump`)
and the chaos leg: an SLO flood on a 2-node cluster produces exactly
one correlated FLIGHT dump with rings from every live node.
"""

import json

import pytest

import ray_tpu
from ray_tpu._private import critical_path, flight_recorder, perf_stats
from ray_tpu._private.config import ray_config


@pytest.fixture(autouse=True)
def _clean_engines():
    critical_path.reset()
    flight_recorder.reset()
    perf_stats.restore_records(critical_path.STAGE_METRIC, {})
    yield


def test_finish_folds_stages_and_unattributed():
    critical_path.record_stage("t1", "proxy.dispatch", 0.01,
                               route="/r")
    critical_path.record_stage("t1", "replica.execute", 0.05,
                               route="/r")
    critical_path.finish_request("t1", "/r", "200", 0.10)

    vecs = critical_path.attribution_vectors()
    assert set(vecs["/r"]) == {"proxy.dispatch", "replica.execute",
                               "unattributed"}
    # The vector tiles the measured total: 0.01 + 0.05 + 0.04.
    assert vecs["/r"]["unattributed"]["sum"] == pytest.approx(0.04)
    assert vecs["/r"]["replica.execute"]["count"] == 1

    (entry,) = critical_path.finished_waterfalls()
    assert entry["dominant_stage"] == "replica.execute"
    assert entry["unattributed_s"] == pytest.approx(0.04)

    # Exemplars pin the trace id to its (route, stage) bucket.
    exes = critical_path.exemplars()
    assert any(e["trace_id"] == "t1" and e["stage"] == "replica.execute"
               for e in exes)


def test_late_arrival_folds_into_finished_route():
    """Node-born stage records ship seconds after the proxy closed the
    request; they must still land in the route's attribution vector."""
    critical_path.record_stage("t2", "proxy.dispatch", 0.01, route="/r")
    critical_path.finish_request("t2", "/r", "200", 0.02)
    # Arrives via the obs shipper after the finish:
    critical_path.ingest([{"trace_id": "t2", "stage": "llm.prefill",
                           "dur_s": 0.5, "route": ""}])
    vecs = critical_path.attribution_vectors()
    assert vecs["/r"]["llm.prefill"]["sum"] == pytest.approx(0.5)


def test_drain_requeue_roundtrip():
    # Only shipping processes (a NodeObsShipper started) queue records.
    critical_path.set_shipping(True)
    try:
        critical_path.record_stage("t3", "sched.queue", 0.001)
        recs = critical_path.drain_records()
        assert [r["stage"] for r in recs] == ["sched.queue"]
        assert critical_path.drain_records() == []
        critical_path.requeue_records(recs)
        assert critical_path.drain_records() == recs
    finally:
        critical_path.set_shipping(False)


def test_head_process_does_not_queue_for_shipping():
    """The head folds its own records in place; with no shipper
    started, nothing accumulates in the pending queue."""
    critical_path.record_stage("t3b", "sched.queue", 0.001)
    assert critical_path.drain_records() == []
    # ...but the trace still accumulated locally.
    critical_path.finish_request("t3b", "/r", "200", 0.002)
    assert critical_path.attribution_vectors()["/r"]["sched.queue"][
        "count"] == 1


def test_disabled_records_nothing():
    critical_path.set_enabled(False)
    try:
        critical_path.record_stage("t4", "proxy.dispatch", 0.01,
                                   route="/r")
        critical_path.finish_request("t4", "/r", "200", 0.1)
        assert critical_path.finished_waterfalls() == []
        assert critical_path.drain_records() == []
        assert critical_path.attribution_vectors() == {}
    finally:
        critical_path.set_enabled(True)


def test_slow_requests_ranked_with_fracs():
    for i, total in enumerate((0.1, 0.5, 0.3)):
        tid = f"t5-{i}"
        critical_path.record_stage(tid, "replica.execute", total / 2,
                                   route="/r")
        critical_path.finish_request(tid, "/r", "200", total)
    rows = critical_path.slow_requests(n=2)
    assert [r["trace_id"] for r in rows] == ["t5-1", "t5-2"]
    assert rows[0]["stages"][0]["frac"] == pytest.approx(0.5)


def test_stage_metric_p99_exported():
    """runtime_metrics exports the p99 gauge for the attribution
    metric (the per-route p50/p99 vector contract)."""
    from ray_tpu._private.runtime_metrics import _collect_fastpath_stats
    from ray_tpu.util.metrics import snapshot_registry

    critical_path.record_stage("t6", "replica.execute", 0.05,
                               route="/r")
    critical_path.finish_request("t6", "/r", "200", 0.06)
    _collect_fastpath_stats()
    snap = snapshot_registry()
    assert "ray_tpu_request_stage_seconds_p50" in snap
    assert "ray_tpu_request_stage_seconds_p99" in snap


def test_flight_rings_bounded_and_snapshotted(monkeypatch):
    monkeypatch.setattr(ray_config, "flight_ring_size", 8)
    for i in range(32):
        flight_recorder.note_span({"trace_id": f"x{i}",
                                   "stage": "s", "dur_s": 0.0})
        flight_recorder.note_sample("health", {"i": i})
    snap = flight_recorder.local_snapshot()
    assert len(snap["spans"]) == 8
    assert snap["spans"][-1]["trace_id"] == "x31"
    assert len(snap["samples"]) == 8
    assert "slow_requests" in snap


def test_observe_verdict_edge_and_debounce(tmp_path, monkeypatch):
    monkeypatch.setattr(ray_config, "flight_recorder_dir",
                        str(tmp_path))
    monkeypatch.setattr(ray_config, "flight_min_interval_s", 3600.0)
    ok = {"status": "ok", "reasons": []}
    bad = {"status": "degraded", "reasons": ["slo_burn: route /r"]}

    assert flight_recorder.observe_verdict(ok) is None
    payload = flight_recorder.observe_verdict(bad)
    assert payload is not None and "path" in payload
    # Still degraded: no new edge, no new dump.
    assert flight_recorder.observe_verdict(bad) is None
    # Recovered then re-degraded inside the debounce window: edge
    # detected but the dump is suppressed.
    assert flight_recorder.observe_verdict(ok) is None
    assert flight_recorder.observe_verdict(bad) is None
    files = list(tmp_path.glob("FLIGHT_*.json"))
    assert len(files) == 1
    on_disk = json.loads(files[0].read_text())
    assert on_disk["verdict"] == "degraded"
    assert on_disk["reasons"] == bad["reasons"]
    assert on_disk["trigger"] == "degraded"


def test_observe_verdict_no_dir_never_writes(tmp_path, monkeypatch):
    monkeypatch.setattr(ray_config, "flight_recorder_dir", "")
    bad = {"status": "degraded", "reasons": ["r"]}
    flight_recorder.observe_verdict({"status": "ok", "reasons": []})
    assert flight_recorder.observe_verdict(bad) is None
    assert list(tmp_path.glob("FLIGHT_*.json")) == []


def test_api_slow_requests_and_debug_dump(ray_start_2_cpus):
    import urllib.request

    from ray_tpu.dashboard import shutdown_dashboard, start_dashboard

    critical_path.record_stage("t7", "replica.execute", 0.2,
                               route="/demo")
    critical_path.finish_request("t7", "/demo", "200", 0.25)
    server = start_dashboard(port=0)
    base = f"http://{server.host}:{server.port}"
    try:
        with urllib.request.urlopen(base, timeout=10) as resp:
            endpoints = json.loads(resp.read())["endpoints"]
        assert "/api/slow_requests" in endpoints
        assert "/api/debug/dump" in endpoints
        with urllib.request.urlopen(f"{base}/api/slow_requests",
                                    timeout=10) as resp:
            body = json.loads(resp.read())
        rows = body["slow_requests"]
        assert rows and rows[0]["trace_id"] == "t7"
        assert rows[0]["dominant_stage"] == "replica.execute"
        assert body["attribution"]["/demo"]["replica.execute"]["count"] \
            == 1
        assert any(e["trace_id"] == "t7" for e in body["exemplars"])
        with urllib.request.urlopen(f"{base}/api/debug/dump",
                                    timeout=10) as resp:
            dump = json.loads(resp.read())
        assert dump["trigger"] == "api"
        assert dump["nodes"]  # at least this process's rings
        ring = next(iter(dump["nodes"].values()))
        assert "spans" in ring and "samples" in ring
        # No directory configured: inline payload only, nothing on disk.
        assert "path" not in dump
    finally:
        shutdown_dashboard()


def test_cli_slow_prints_waterfalls(ray_start_2_cpus, capsys):
    from ray_tpu.scripts.cli import main as cli_main

    critical_path.record_stage("t8", "llm.prefill", 0.3, route="/llm")
    critical_path.finish_request("t8", "/llm", "200", 0.4)
    cli_main(["slow", "-n", "5"])
    out = capsys.readouterr().out
    assert "t8" in out
    assert "dominant=llm.prefill" in out
    cli_main(["slow", "--json"])
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["slow_requests"][0]["trace_id"] == "t8"
    assert "/llm" in parsed["attribution"]


def test_slo_flood_dumps_once_with_rings_from_every_node(
        tmp_path, monkeypatch):
    """Chaos leg: flood a route past its SLO target on a 2-node
    cluster. The ok→degraded edge must produce EXACTLY one flight dump
    whose verdict names slo_burn and whose rings cover every live
    node; repeated degraded polls must not dump again."""
    from ray_tpu._private.health import evaluate_health
    from ray_tpu.cluster_utils import Cluster

    route = "/flood"
    monkeypatch.setattr(ray_config, "serve_slo_targets",
                        f"{route}=0.05:0.9")
    monkeypatch.setattr(ray_config, "flight_recorder_dir",
                        str(tmp_path))
    monkeypatch.setattr(ray_config, "flight_min_interval_s", 3600.0)
    # Only the SLO signal may trip on a loaded CI box: park the other
    # thresholds out of reach so the baseline verdict is "ok".
    monkeypatch.setattr(ray_config, "health_memory_pressure_threshold",
                        1.1)
    monkeypatch.setattr(ray_config, "health_loop_lag_threshold_s", 60.0)
    monkeypatch.setattr(ray_config, "health_backlog_threshold",
                        10 ** 6)

    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=1)
        v0 = evaluate_health()
        assert v0["status"] == "ok", v0["reasons"]

        # The flood: 50 requests at 10x the 50ms target burn the whole
        # error budget (objective 0.9 -> any >10% bad is >1x burn).
        dist = perf_stats.dist(
            "serve_request_seconds",
            tags={"route": route, "status": "200"},
            bounds=perf_stats.SERVE_LATENCY_BOUNDS)
        for _ in range(50):
            dist.record(0.5)

        v1 = evaluate_health()
        assert v1["status"] == "degraded"
        assert any(r.startswith("slo_burn:") for r in v1["reasons"]), \
            v1["reasons"]
        # Still degraded on later polls: the edge fired once.
        evaluate_health()
        evaluate_health()

        files = list(tmp_path.glob("FLIGHT_*.json"))
        assert len(files) == 1, [f.name for f in files]
        payload = json.loads(files[0].read_text())
        assert payload["verdict"] == "degraded"
        assert any("slo_burn:" in r for r in payload["reasons"])
        # Rings from every live node: the head's own plus a
        # flight_snapshot RPC answer from the added worker node.
        rings = payload["nodes"]
        assert len(rings) >= 2, list(rings)
        for node_id, ring in rings.items():
            assert "error" not in ring, (node_id, ring)
            assert "spans" in ring and "samples" in ring, node_id
    finally:
        cluster.shutdown()
