"""End-to-end autoscaler: pending cluster demands launch REAL node
processes (ClusterNodeProvider), tasks run there, idle nodes scale back
down (reference: fake_multi_node provider e2e tests)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    AutoscalerConfig,
    ClusterNodeProvider,
    NodeType,
    StandardAutoscaler,
    cluster_demand_fn,
)
from ray_tpu.cluster_utils import Cluster

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    yield c
    c.shutdown()


def test_autoscaler_launches_real_nodes_for_demand(cluster):
    provider = ClusterNodeProvider(cluster, {"cpu4": {"CPU": 4}})
    autoscaler = StandardAutoscaler(
        provider,
        AutoscalerConfig(
            node_types=[NodeType("cpu4", {"CPU": 4}, min_workers=0,
                                 max_workers=2)],
            interval_s=0.2, idle_timeout_s=2.0),
        demand_fn=cluster_demand_fn(cluster.head))
    autoscaler.start()
    try:
        # A 4-CPU task cannot fit anywhere (head has 1): with the
        # autoscaler running it must get capacity and complete.
        @ray_tpu.remote(num_cpus=4)
        def big():
            import os

            return os.getpid()

        ref = big.remote()
        pid = ray_tpu.get(ref, timeout=90)
        assert isinstance(pid, int)
        assert autoscaler.launches >= 1
        assert len(provider.non_terminated_nodes({})) >= 1

        # Demand drained -> pending_demands empty.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                cluster.head.pending_demands:
            time.sleep(0.1)
        assert not cluster.head.pending_demands

        # Idle nodes terminate back to min_workers=0 (the termination
        # counter bumps after the graceful RPC shutdown returns, a beat
        # after the node table empties).
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and (
                provider.non_terminated_nodes({})
                or autoscaler.terminations < 1):
            time.sleep(0.3)
        assert not provider.non_terminated_nodes({})
        assert autoscaler.terminations >= 1
    finally:
        autoscaler.stop()


def test_infeasible_still_fails_fast_without_autoscaler(cluster):
    @ray_tpu.remote(num_cpus=64)
    def huge():
        return 1

    with pytest.raises(Exception, match="no live cluster node"):
        ray_tpu.get(huge.remote(), timeout=30)
