"""Aux subsystem tests: jobs, autoscaler, runtime env, CLI, dashboard,
multiprocessing shim, accelerators, check_serialize."""

import json
import os
import sys
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    AutoscalerConfig,
    FakeNodeProvider,
    NodeType,
    StandardAutoscaler,
)
from ray_tpu.autoscaler.autoscaler import bin_pack_demands
from ray_tpu.job_submission import JobStatus, JobSubmissionClient


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_job_submission_lifecycle():
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('job output 42')\"")
    info = client.wait_until_finish(job_id, timeout=60)
    assert info.status == JobStatus.SUCCEEDED
    assert "job output 42" in client.get_job_logs(job_id)


def test_job_failure_and_env():
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"import os,sys; "
                   f"sys.exit(0 if os.environ.get('MY_FLAG')=='1' else 3)\"",
        runtime_env={"env_vars": {"MY_FLAG": "1"}})
    assert client.wait_until_finish(job_id).status == JobStatus.SUCCEEDED
    job2 = client.submit_job(entrypoint=f"{sys.executable} -c 'exit(5)'")
    info = client.wait_until_finish(job2)
    assert info.status == JobStatus.FAILED
    assert info.return_code == 5


def test_bin_pack_demands():
    types = [NodeType("small", {"CPU": 4}, max_workers=10),
             NodeType("tpu", {"CPU": 8, "TPU": 8}, max_workers=4)]
    plan = bin_pack_demands(
        [{"CPU": 2}] * 4 + [{"TPU": 8}], types, existing={})
    # TPU demand forces the slice type; its spare CPU absorbs the rest.
    assert plan == {"tpu": 1}
    plan2 = bin_pack_demands([{"CPU": 2}] * 10, types, existing={})
    assert plan2.get("small", 0) >= 5  # pure-CPU load uses the small type
    plan3 = bin_pack_demands([{"TPU": 8}] * 9, types, existing={})
    assert plan3 == {"tpu": 4}  # capped at max_workers


def test_autoscaler_scales_up_for_pending_tasks():
    provider = FakeNodeProvider({"worker": {"CPU": 4}})
    cfg = AutoscalerConfig(node_types=[NodeType("worker", {"CPU": 4},
                                                max_workers=5)],
                           interval_s=0.05)
    scaler = StandardAutoscaler(provider, cfg)

    @ray_tpu.remote
    def hog():
        time.sleep(0.8)
        return 1

    # 8 tasks × 2 CPU on a 4-CPU node → demand backlog.
    refs = [hog.options(num_cpus=2).remote() for _ in range(8)]
    time.sleep(0.1)  # let the backlog form
    scaler.update()
    assert scaler.launches > 0
    assert len(provider.non_terminated_nodes({})) > 0
    ray_tpu.get(refs)


def test_runtime_env_applied_to_task():
    @ray_tpu.remote(runtime_env={"env_vars": {"TASK_ENV_X": "hello"}})
    def read_env():
        return os.environ.get("TASK_ENV_X")

    assert ray_tpu.get(read_env.remote()) == "hello"
    assert os.environ.get("TASK_ENV_X") is None


def test_runtime_env_validation():
    from ray_tpu._private.runtime_env import validate_runtime_env

    with pytest.raises(ValueError):
        validate_runtime_env({"bogus_field": 1})
    with pytest.raises(TypeError):
        validate_runtime_env({"env_vars": "notadict"})
    validate_runtime_env({"env_vars": {"A": "B"}, "pip": ["numpy"]})


def test_cli_status_and_summary(capsys):
    from ray_tpu.scripts.cli import main

    main(["status"])
    out = json.loads(capsys.readouterr().out)
    assert "cluster_resources" in out

    @ray_tpu.remote
    def noop():
        return 1

    ray_tpu.get(noop.remote())
    main(["summary", "tasks"])
    out = json.loads(capsys.readouterr().out)
    assert any("noop" in k for k in out)


def test_dashboard_endpoints():
    from ray_tpu.dashboard import shutdown_dashboard, start_dashboard

    @ray_tpu.remote
    def marker_task():
        return 1

    ray_tpu.get(marker_task.remote())
    server = start_dashboard(port=0)
    try:
        base = f"http://{server.host}:{server.port}"
        with urllib.request.urlopen(f"{base}/api/cluster_status",
                                    timeout=10) as r:
            status = json.loads(r.read())
        assert "cluster_resources" in status
        with urllib.request.urlopen(f"{base}/api/tasks", timeout=10) as r:
            tasks = json.loads(r.read())
        assert any("marker_task" in t["name"] for t in tasks)
        with urllib.request.urlopen(f"{base}/api/metrics", timeout=10) as r:
            assert r.status == 200
    finally:
        shutdown_dashboard()


def test_multiprocessing_pool():
    from ray_tpu.util.multiprocessing import Pool

    with Pool() as pool:
        assert pool.map(lambda x: x * x, range(6)) == [0, 1, 4, 9, 16, 25]
        assert pool.apply(lambda a, b: a + b, (2, 3)) == 5
        r = pool.apply_async(lambda: 7)
        assert r.get(timeout=10) == 7
        assert sorted(pool.imap_unordered(lambda x: x + 1, [1, 2, 3])) == \
            [2, 3, 4]


def test_accelerators():
    from ray_tpu.util import accelerators

    spec = accelerators.chip_spec(accelerators.TPU_V5E)
    assert spec.hbm_bytes == 16 * 2**30
    assert accelerators.detect_tpu_type() in accelerators.TPU_SPECS


def test_check_serialize():
    from ray_tpu.util.check_serialize import inspect_serializability

    ok, _ = inspect_serializability({"a": 1})
    assert ok
    import threading

    lock = threading.Lock()

    def closure():
        return lock

    ok, failures = inspect_serializability(closure)
    assert not ok
    assert any("lock" in f for f in failures)
