"""Multi-process head: shard routing, fan-out isolation, folds, lease
authority, and crash failover (PR 19 tentpole)."""

import subprocess
import sys

import pytest

from ray_tpu._private.head_shards import (DURABLE_TABLES, HeadShardState,
                                          InprocRouter, ShardRouter,
                                          shard_of)
from ray_tpu._private.sched_state import stable_shard_of


def _k(i: int) -> bytes:
    return b"key-%06d" % i


# -- routing stability -------------------------------------------------------


def test_shard_of_stable_across_interpreter_restarts():
    """The key->shard map must survive a coordinator restart: a
    restarted head has to find durable rows where its predecessor left
    them. The salted builtin hash() breaks this (PYTHONHASHSEED); the
    crc-based map must agree with a FRESH interpreter."""
    keys = [_k(i) for i in range(64)]
    local = [shard_of(k, 4) for k in keys]
    script = (
        "import sys\n"
        "from ray_tpu._private.head_shards import shard_of\n"
        "keys = [b'key-%06d' % i for i in range(64)]\n"
        "print(','.join(str(shard_of(k, 4)) for k in keys))\n")
    out = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, check=True)
    remote = [int(x) for x in out.stdout.strip().split(",")]
    assert remote == local


def test_shard_of_spreads_and_degenerates_to_zero():
    assert all(stable_shard_of(_k(i), 1) == 0 for i in range(32))
    assert stable_shard_of(_k(1), 0) == 0
    hits = {shard_of(_k(i), 4) for i in range(256)}
    assert hits == {0, 1, 2, 3}  # every shard takes some of the range
    # Non-bytes keys route via repr (lease keys are tuples).
    assert 0 <= stable_shard_of(("job", ((("CPU", 1),), 0)), 4) < 4


# -- in-process shard state --------------------------------------------------


def test_apply_fold_and_ownership(tmp_path):
    router = InprocRouter(2, states=[
        HeadShardState(i, 2, db_path=str(tmp_path / f"s{i}.db"),
                       commit_interval_s=0) for i in range(2)])
    try:
        for i in range(40):
            router.put("objects", _k(i), ("10.0.0.1", 7000 + i))
        router.delete("objects", _k(0))
        # Single ownership: every row lives ONLY on its owning shard.
        for state in router.shards:
            for key, _ in state.items("objects"):
                assert state.owns(key)
        folded = dict(router.fold_items("objects"))
        assert len(folded) == 39
        assert folded[_k(7)] == ("10.0.0.1", 7007)
        # Both shards took a share (not all keys on one).
        assert all(len(s.tables["objects"]) > 0 for s in router.shards)
    finally:
        router.close()


def test_durable_rows_reload_after_restart(tmp_path):
    db = str(tmp_path / "s0.db")
    state = HeadShardState(0, 1, db_path=db, commit_interval_s=0)
    state.apply([("put", "lineage", _k(1), b"task-1"),
                 ("put", "sizes", _k(1), 4096)])
    state.flush()
    state.close()
    reborn = HeadShardState(0, 1, db_path=db, commit_interval_s=0)
    assert reborn.get("lineage", _k(1)) == b"task-1"
    assert reborn.get("sizes", _k(1)) == 4096
    reborn.close()


def test_lease_cap_is_shard_side_authority():
    state = HeadShardState(0, 1)
    key = repr(("job", "shape")).encode()
    assert state.lease_register(key, "node-a", cap=1)
    # The cap lives on the shard, not in the caller's memory: a second
    # grant for a cap-1 key is refused even from a "different" caller.
    assert not state.lease_register(key, "node-b", cap=1)
    assert state.lease_grants(key) == ["node-a"]
    assert state.lease_retire(key, "node-a")
    assert state.lease_register(key, "node-b", cap=1)
    assert not state.lease_retire(key, "node-zzz")  # unknown grant


# -- subprocess router -------------------------------------------------------


@pytest.fixture
def router(tmp_path):
    r = ShardRouter(2, str(tmp_path / "shards"), commit_interval_s=0.01)
    yield r
    r.close()


def test_fanout_frame_isolation_and_fold(router):
    """Streamed mutations coalesce PER SHARD: each shard process sees
    only frames for its own key range, and the whole-table fold stitches
    the ranges back together."""
    n = 60
    for i in range(n):
        router.put("objects", _k(i), ("127.0.0.1", i))
    assert router.flush()
    for chan in router.channels:
        rows = chan.call("shard_items", table="objects")
        assert rows, f"shard {chan.index} got no share of the range"
        for key, _ in rows:
            assert router.shard_of(key) == chan.index
    folded = dict(router.fold_items("objects"))
    assert len(folded) == n
    assert folded[_k(3)] == ("127.0.0.1", 3)
    # Point reads route to the owning shard.
    assert router.get("objects", _k(5)) == ("127.0.0.1", 5)
    # Per-shard stats carry the group-commit counters.
    for row in router.stats():
        assert row["alive"] and row["applied"] > 0
        assert row["commits"] >= 1


def test_lease_register_over_rpc(router):
    key = repr(("job-1", ((("CPU", 1),), 0))).encode()
    assert router.lease_register(key, "node-a", cap=1)
    assert not router.lease_register(key, "node-b", cap=1)
    assert router.lease_retire(key, "node-a")
    assert router.lease_register(key, "node-b", cap=1)


def test_shard_crash_failover_and_loss_bound(router):
    """Hard-kill one shard mid-flood: the survivor keeps granting, the
    supervisor restarts the victim from its db, acked (flushed) rows
    survive, and everything lost is inside the victim's unflushed
    window."""
    acked = {_k(i): ("10.0.0.2", i) for i in range(40)}
    for key, value in acked.items():
        router.put("objects", key, value)
    assert router.flush()  # acked boundary: durable on both shards

    victim = 0
    router.kill_shard(victim)
    # Post-kill window: these rows race the death; the victim's share
    # may be lost (bounded loss), the survivor's share must not be.
    window = {_k(100 + i): ("10.0.0.3", i) for i in range(20)}
    for key, value in window.items():
        router.put("objects", key, value)

    # Survivor keeps granting while the victim's key range refuses.
    grants = {0: None, 1: None}
    for i in range(200):
        key = repr(("job", i)).encode()
        grants[router.shard_of(key)] = router.lease_register(
            key, "node-a", cap=1)
        if grants[0] is not None and grants[1] is not None:
            break
    assert grants[victim] is False, "dead shard granted a lease"
    assert grants[1 - victim] is True, "survivor stopped granting"

    restarted = router.poll()
    assert restarted == [victim]
    assert router.restarts == 1

    # Every acked row survived the crash — on BOTH shards.
    folded = dict(router.fold_items("objects"))
    for key, value in acked.items():
        assert folded.get(key) == value, f"acked row {key!r} lost"
    # Loss bound: anything missing is from the victim's open window.
    for key, value in window.items():
        if folded.get(key) != value:
            assert router.shard_of(key) == victim
    # The restarted shard serves decisions again.
    key = repr(("job-after", 1)).encode()
    assert router.lease_register(key, "node-a", cap=1) or \
        router.shard_of(key) != victim


def test_poll_does_not_restart_healthy_shard_on_frame_error(router):
    chan = router.channels[0]
    chan.alive = False  # simulate a single frame error, process alive
    assert router.poll() == []  # ping probe revives it instead
    assert chan.alive
    assert router.restarts == 0


# -- head_shards=1 control ---------------------------------------------------


def test_single_shard_config_spawns_no_router(monkeypatch):
    """Default config (head_shards=1) must keep today's single-process
    head byte-for-byte: no router, no shard subprocesses, tasks run."""
    from ray_tpu._private.config import ray_config

    assert ray_config.head_shards == 1  # the documented default
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    try:
        assert c.head.shard_router is None
        assert c.driver_worker.gcs.head_shard_state() == {}

        @ray_tpu.remote
        def f(x):
            return x + 1

        assert ray_tpu.get(f.remote(1), timeout=60) == 2
    finally:
        c.shutdown()


@pytest.mark.slow
def test_cluster_with_sharded_head_end_to_end(monkeypatch, tmp_path):
    """head_shards=2 on a real cluster: tasks run, directory rows land
    on the shards, healthz carries per-shard verdicts, and the fold
    surfaces through ray_tpu.state."""
    from ray_tpu._private.config import ray_config

    monkeypatch.setattr(ray_config, "head_shards", 2)
    monkeypatch.setattr(ray_config, "head_shard_db_dir",
                        str(tmp_path / "shards"))
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    # Zero-CPU head: tasks must execute on the worker node, so their
    # outputs travel the report_objects path that feeds the shards.
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 0})
    try:
        c.add_node(num_cpus=2)
        head = c.head
        assert head.shard_router is not None
        assert head.shard_router.n_shards == 2

        @ray_tpu.remote(num_cpus=1)
        def f(x):
            return x * 2

        assert ray_tpu.get([f.remote(i) for i in range(8)],
                           timeout=60) == [i * 2 for i in range(8)]
        assert head.shard_router.flush()
        folded = dict(head.shard_router.fold_items("objects"))
        assert folded, "no directory rows reached the shards"
        state = c.driver_worker.gcs.head_shard_state()
        assert state["shards"] == 2
        assert state["tables"]["objects"] >= 1
        verdicts = head.shard_health()
        assert len(verdicts) == 2
        assert all(v["verdict"] == "ok" for v in verdicts)
    finally:
        c.shutdown()
