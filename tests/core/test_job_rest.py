"""Job submission REST surface on the dashboard."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.dashboard import shutdown_dashboard, start_dashboard

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    yield
    shutdown_dashboard()
    ray_tpu.shutdown()


def _post(url, payload):
    req = urllib.request.Request(
        url, method="POST", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def test_job_submit_status_logs_over_rest():
    server = start_dashboard(port=0)
    base = f"http://{server.host}:{server.port}"

    out = _post(f"{base}/api/jobs/", {
        "entrypoint": "python -c \"print('hello from job')\""})
    job_id = out["job_id"]

    deadline = time.monotonic() + 30
    status = None
    while time.monotonic() < deadline:
        with urllib.request.urlopen(f"{base}/api/jobs/{job_id}",
                                    timeout=10) as resp:
            info = json.loads(resp.read())
        status = info["status"]
        if status in ("SUCCEEDED", "FAILED", "STOPPED"):
            break
        time.sleep(0.2)
    assert status == "SUCCEEDED", info

    with urllib.request.urlopen(f"{base}/api/jobs/{job_id}/logs",
                                timeout=10) as resp:
        logs = json.loads(resp.read())["logs"]
    assert "hello from job" in logs

    with urllib.request.urlopen(f"{base}/api/jobs/", timeout=10) as resp:
        listing = json.loads(resp.read())
    assert any(j["job_id"] == job_id for j in listing)


def test_job_stop_and_bad_spec():
    server = start_dashboard(port=0)
    base = f"http://{server.host}:{server.port}"

    out = _post(f"{base}/api/jobs/", {
        "entrypoint": "python -c \"import time; time.sleep(60)\""})
    job_id = out["job_id"]
    time.sleep(0.5)
    stopped = _post(f"{base}/api/jobs/{job_id}/stop", {})
    assert stopped["stopped"] is True

    req = urllib.request.Request(
        f"{base}/api/jobs/", method="POST", data=b"{}")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400
