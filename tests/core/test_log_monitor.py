"""Driver log mirroring (reference `_private/log_monitor.py` role):
print() inside a task on a cluster node shows up at the driver with a
node prefix."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


def test_node_prints_mirror_to_driver():
    lines = []
    cluster = Cluster(head_node_args={"num_cpus": 1})
    # swap the sink so the test can assert instead of reading stdout
    cluster._log_monitor._sink = lines.append
    try:
        cluster.add_node(num_cpus=2)

        @ray_tpu.remote(num_cpus=2)
        def chatty():
            print("hello-from-the-node")
            return 1

        assert ray_tpu.get(chatty.remote()) == 1
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if any("hello-from-the-node" in l for l in lines):
                break
            time.sleep(0.1)
        matching = [l for l in lines if "hello-from-the-node" in l]
        assert matching, lines[-5:]
        assert matching[0].startswith("(node-1) "), matching[0]
    finally:
        cluster.shutdown()


def test_monitor_handles_partial_lines_and_truncation(tmp_path):
    from ray_tpu._private.log_monitor import LogMonitor

    out = []
    mon = LogMonitor(poll_interval_s=0.05, sink=out.append)
    p = tmp_path / "node.log"
    p.write_bytes(b"")
    mon.add_file("n", str(p))
    mon.start()
    try:
        with open(p, "ab", buffering=0) as f:
            f.write(b"part")        # no newline yet: must be held back
            time.sleep(0.2)
            assert out == []
            f.write(b"ial line\nsecond\n")
        deadline = time.monotonic() + 5
        while len(out) < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert out == ["(n) partial line", "(n) second"]
        # truncation: monitor re-reads from the top
        p.write_bytes(b"fresh\n")
        deadline = time.monotonic() + 5
        while len(out) < 3 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert out[-1] == "(n) fresh"
    finally:
        mon.stop(drain=False)
