"""Control-plane fast path: spec-template interning, event-driven
wait/get, coalesced submit frames, and deferred durable writes.

Covers the contracts the hot path relies on:
- intern cache identity: same content dedupes, redefinition invalidates;
- wait/get correctness under concurrent completion + cancellation;
- batched-frame flush under backpressure (order, coalescing, errors);
- SQLite group commit: visibility boundary is flush(), not put().
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.memory_store import MemoryStore
from ray_tpu._private.rpc import CoalescingBatcher
from ray_tpu._private.task_spec import TaskKind, intern_template


# ---------------------------------------------------------------------------
# Spec-template interning
# ---------------------------------------------------------------------------


def test_template_interned_once_per_function(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x + 1

    r1 = f.remote(1)
    r2 = f.remote(2)
    assert ray_tpu.get([r1, r2]) == [2, 3]
    # Both submissions share ONE interned template.
    assert f._template is not None
    assert f._template.template_id
    assert f._template.milli == {"CPU": 1000}


def test_template_options_get_distinct_templates(ray_start_regular):
    @ray_tpu.remote
    def g(x):
        return x

    g_half = g.options(num_cpus=0.5)
    assert ray_tpu.get(g.remote(1)) == 1
    assert ray_tpu.get(g_half.remote(2)) == 2
    assert g._template.template_id != g_half._template.template_id
    assert g_half._template.resources == {"CPU": 0.5}


def test_template_cache_invalidated_on_redefinition(ray_start_regular):
    """A redefined function body (same name) must produce a different
    template id — the intern cache keys on content, so the new
    definition can never hit the stale entry."""

    def make(version):
        @ray_tpu.remote
        def worker():
            return version

        return worker

    w1 = make(1)
    w2 = make(2)
    assert ray_tpu.get(w1.remote()) == 1
    assert ray_tpu.get(w2.remote()) == 2  # new body executes, not cached
    assert w1._template.template_id != w2._template.template_id


def test_equal_content_dedupes_to_one_template():
    tpl_a = intern_template(
        kind=TaskKind.ACTOR_TASK, func="ping", name="A.ping",
        num_returns=1, resources={}, max_retries=0)
    tpl_b = intern_template(
        kind=TaskKind.ACTOR_TASK, func="ping", name="A.ping",
        num_returns=1, resources={}, max_retries=0)
    assert tpl_a.template_id == tpl_b.template_id
    tpl_c = intern_template(
        kind=TaskKind.ACTOR_TASK, func="ping", name="A.ping",
        num_returns=1, resources={}, max_retries=2)
    assert tpl_c.template_id != tpl_a.template_id


def test_spec_from_template_carries_invariants(ray_start_regular):
    @ray_tpu.remote(num_cpus=0.25, max_retries=7, name="custom-name")
    def h():
        return 1

    assert ray_tpu.get(h.remote()) == 1
    tpl = h._template
    spec = tpl.make_spec(TaskID.from_random(), (), {})
    assert spec.name == "custom-name"
    assert spec.resources == {"CPU": 0.25}
    assert spec.max_retries == 7
    assert spec.template_id == tpl.template_id
    assert spec._milli_cache == {"CPU": 250}


# ---------------------------------------------------------------------------
# Event-driven wait / get
# ---------------------------------------------------------------------------


def test_wait_all_ready_zero_timeout():
    store = MemoryStore()
    oids = [ObjectID.for_task_return(TaskID.from_random(), 0)
            for _ in range(50)]
    for i, oid in enumerate(oids):
        store.put(oid, i)
    ready, not_ready = store.wait(oids, 50, timeout=0)
    assert ready == oids and not_ready == []
    # num_returns trims even when more are resolved.
    ready, not_ready = store.wait(oids, 10, timeout=0)
    assert ready == oids[:10] and not_ready == oids[10:]


def test_wait_wakes_on_concurrent_completion():
    store = MemoryStore()
    oids = [ObjectID.for_task_return(TaskID.from_random(), 0)
            for _ in range(20)]
    for oid in oids[:5]:
        store.put(oid, 1)

    def complete_rest():
        time.sleep(0.05)
        for oid in oids[5:]:
            store.put(oid, 2)

    t = threading.Thread(target=complete_rest)
    t.start()
    ready, not_ready = store.wait(oids, 20, timeout=5)
    t.join()
    assert len(ready) == 20 and not not_ready


def test_wait_timeout_returns_partial():
    store = MemoryStore()
    oids = [ObjectID.for_task_return(TaskID.from_random(), 0)
            for _ in range(4)]
    store.put(oids[0], "x")
    t0 = time.monotonic()
    ready, not_ready = store.wait(oids, 4, timeout=0.2)
    assert time.monotonic() - t0 < 2.0
    assert ready == [oids[0]]
    assert not_ready == oids[1:]


def test_wait_under_concurrent_completion_and_cancellation(
        ray_start_regular):
    """wait/get stay correct when some tasks complete while others are
    cancelled mid-flight: every ref resolves (value or typed error) and
    wait() accounts for all of them."""

    @ray_tpu.remote(num_cpus=0.01)
    def slow(i):
        time.sleep(0.05)
        return i

    refs = [slow.remote(i) for i in range(40)]
    # Cancel a slice concurrently with execution.
    for r in refs[::4]:
        ray_tpu.cancel(r)
    ready, not_ready = ray_tpu.wait(refs, num_returns=len(refs),
                                    timeout=30)
    assert len(ready) + len(not_ready) == len(refs)
    ok, cancelled = 0, 0
    for r in refs:
        try:
            val = ray_tpu.get(r, timeout=30)
            assert isinstance(val, int)
            ok += 1
        except Exception:
            cancelled += 1
    # Cancellation is racy by contract; completed + cancelled must
    # cover everything, and nothing may hang.
    assert ok + cancelled == len(refs)
    assert ok >= len(refs) - len(refs[::4])


def test_get_many_mixed_ready_and_pending(ray_start_regular):
    @ray_tpu.remote(num_cpus=0.01)
    def quick(i):
        return i

    @ray_tpu.remote(num_cpus=0.01)
    def slow(i):
        time.sleep(0.2)
        return i

    refs = [quick.remote(0), slow.remote(1), quick.remote(2)]
    assert ray_tpu.get(refs, timeout=30) == [0, 1, 2]


def test_get_many_raises_task_error(ray_start_regular):
    @ray_tpu.remote(num_cpus=0.01)
    def boom():
        raise ValueError("expected-boom")

    @ray_tpu.remote(num_cpus=0.01)
    def fine():
        return 1

    refs = [fine.remote(), boom.remote(), fine.remote()]
    with pytest.raises(ValueError, match="expected-boom"):
        ray_tpu.get(refs, timeout=30)


# ---------------------------------------------------------------------------
# Batched-frame flush under backpressure
# ---------------------------------------------------------------------------


def test_batcher_coalesces_under_backpressure():
    """While one frame is in flight (a slow channel = backpressure),
    items pile up and ride the NEXT frame: total frames sent is far
    below items added, order is preserved, nothing is lost."""
    frames = []
    gate = threading.Event()

    def send_frame(items):
        if not gate.is_set():
            gate.wait(5)  # first frame stalls: the backpressure window
        frames.append(list(items))

    b = CoalescingBatcher(send_frame, name="test")
    b.add(0)
    time.sleep(0.1)          # flusher is now stalled inside send_frame
    for i in range(1, 200):
        b.add(i)
    gate.set()
    assert b.flush(timeout=10)
    sent = [i for frame in frames for i in frame]
    assert sent == list(range(200))          # order preserved, no loss
    assert len(frames) <= 3                  # coalesced, not 200 frames
    assert len(frames[1]) >= 150             # the pile-up rode one frame
    b.close()


def test_batcher_error_isolated_to_frame():
    seen_errors = []
    ok_frames = []

    def send_frame(items):
        if "bad" in items:
            raise RuntimeError("frame failed")
        ok_frames.append(list(items))

    b = CoalescingBatcher(send_frame, name="test-err",
                          on_error=lambda items, e: seen_errors.append(
                              (list(items), str(e))))
    b.add("bad")
    assert b.flush(timeout=5)
    b.add("good")
    assert b.flush(timeout=5)
    assert seen_errors and seen_errors[0][0] == ["bad"]
    assert ok_frames == [["good"]]           # flusher survived the error
    b.close()


def test_batcher_flush_empty_is_immediate():
    b = CoalescingBatcher(lambda items: None, name="test-empty")
    t0 = time.monotonic()
    assert b.flush(timeout=5)
    assert time.monotonic() - t0 < 1.0
    b.close()


# ---------------------------------------------------------------------------
# Deferred durable writes (SQLite group commit)
# ---------------------------------------------------------------------------


def test_sqlite_group_commit_flush_boundary(tmp_path):
    """put() defers the disk transaction; flush() is the durability
    boundary a SECOND connection observes."""
    import sqlite3

    from ray_tpu._private.gcs_storage import SqliteStoreClient

    path = str(tmp_path / "gcs.db")
    # Huge interval: the background flusher never fires during the test.
    store = SqliteStoreClient(path, commit_interval_s=300.0)
    store.put("t", b"k", b"v")
    # Same connection reads its own uncommitted write immediately.
    assert store.get("t", b"k") == b"v"
    other = sqlite3.connect(path)
    row = other.execute(
        "SELECT value FROM kv WHERE tbl='t' AND key=?", (b"k",)).fetchone()
    assert row is None, "write visible across connections before flush"
    store.flush()
    row = other.execute(
        "SELECT value FROM kv WHERE tbl='t' AND key=?", (b"k",)).fetchone()
    assert row == (b"v",)
    other.close()
    store.close()


def test_sqlite_close_commits_pending(tmp_path):
    from ray_tpu._private.gcs_storage import SqliteStoreClient

    path = str(tmp_path / "gcs2.db")
    store = SqliteStoreClient(path, commit_interval_s=300.0)
    store.put("t", b"a", b"1")
    store.delete("t", b"a")
    store.put("t", b"b", b"2")
    store.close()
    reopened = SqliteStoreClient(path, commit_interval_s=0)
    assert reopened.get("t", b"a") is None
    assert reopened.get("t", b"b") == b"2"
    reopened.close()


def test_sqlite_background_flusher_commits(tmp_path):
    import sqlite3

    from ray_tpu._private.gcs_storage import SqliteStoreClient

    path = str(tmp_path / "gcs3.db")
    store = SqliteStoreClient(path, commit_interval_s=0.01)
    store.put("t", b"k", b"v")
    other = sqlite3.connect(path)
    deadline = time.monotonic() + 5
    row = None
    while time.monotonic() < deadline and row is None:
        row = other.execute(
            "SELECT value FROM kv WHERE tbl='t' AND key=?",
            (b"k",)).fetchone()
        time.sleep(0.02)
    assert row == (b"v",), "background group commit never landed"
    other.close()
    store.close()


# ---------------------------------------------------------------------------
# Submit-side dispatch bypass
# ---------------------------------------------------------------------------


def test_fast_dispatch_falls_back_when_busy(ray_start_2_cpus):
    """Tasks outnumbering free resources take the parked/dispatcher
    path; everything still completes exactly once."""

    @ray_tpu.remote(num_cpus=2)
    def heavy(i):
        time.sleep(0.05)
        return i

    refs = [heavy.remote(i) for i in range(6)]
    assert ray_tpu.get(refs, timeout=60) == list(range(6))


def test_fast_dispatch_nested_submission(ray_start_regular):
    @ray_tpu.remote(num_cpus=0.5)
    def inner(x):
        return x * 2

    @ray_tpu.remote(num_cpus=0.5)
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 1

    assert ray_tpu.get(outer.remote(10), timeout=60) == 21


def test_fast_dispatch_infeasible_request_errors(ray_start_2_cpus):
    @ray_tpu.remote(num_cpus=64)
    def impossible():
        return 1

    with pytest.raises(Exception):
        ray_tpu.get(impossible.remote(), timeout=30)


# ---------------------------------------------------------------------------
# Cluster wire path (interned templates + batched frames end to end)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_cluster_template_stripped_submissions():
    """Forced-remote tasks ride TaskCall headers after the first
    shipment: the head records the node as knowing the template, and a
    stream of submissions with args still yields correct results."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        node_id = cluster.add_node(num_cpus=4)

        @ray_tpu.remote(num_cpus=1)
        def mul(x, y):
            return x * y

        assert ray_tpu.get(mul.remote(6, 7), timeout=60) == 42
        refs = [mul.remote(i, 2) for i in range(200)]
        assert ray_tpu.get(refs, timeout=120) == [i * 2 for i in range(200)]
        record = cluster.head.nodes[node_id]
        assert mul._template.template_id in record.known_templates
    finally:
        cluster.shutdown()


@pytest.mark.slow
def test_cluster_batched_arg_fetch():
    """A forced-remote task whose args all live on the driver resolves
    them through the batched locate/pull path."""
    import numpy as np

    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=2)
        arg_refs = [ray_tpu.put(np.full(1000, i)) for i in range(8)]

        @ray_tpu.remote(num_cpus=2)
        def total(*arrs):
            return int(sum(a.sum() for a in arrs))

        expect = sum(i * 1000 for i in range(8))
        assert ray_tpu.get(total.remote(*arg_refs), timeout=120) == expect
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# Bench hygiene: schema-versioned perf envelope
# ---------------------------------------------------------------------------


def test_perf_bench_envelope_schema():
    """The perf emitter's calibration is cheap and its schema stable:
    cross-host comparisons rely on these exact keys existing."""
    import benchmarks.perf_bench as pb

    assert isinstance(pb.SCHEMA_VERSION, int) and pb.SCHEMA_VERSION >= 2
    cal = pb.host_calibration(seconds=0.02)
    assert set(cal) >= {"cpu_count", "python_spin_mops_per_s",
                        "lock_roundtrip_mops_per_s"}
    assert cal["python_spin_mops_per_s"] > 0
    assert cal["lock_roundtrip_mops_per_s"] > 0
