"""Dashboard UI page + node stats agent plumbing."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu.dashboard import shutdown_dashboard, start_dashboard


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    yield
    shutdown_dashboard()
    ray_tpu.shutdown()


def test_ui_page_served():
    server = start_dashboard(port=0)
    base = f"http://{server.host}:{server.port}"
    with urllib.request.urlopen(f"{base}/ui", timeout=10) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"] == "text/html"
        body = resp.read().decode()
    assert "ray_tpu dashboard" in body
    assert "/api/nodes" in body
    # advertised from the index
    with urllib.request.urlopen(base, timeout=10) as resp:
        assert "/ui" in json.loads(resp.read())["endpoints"]


def test_nodes_carry_stats():
    server = start_dashboard(port=0)
    base = f"http://{server.host}:{server.port}"
    with urllib.request.urlopen(f"{base}/api/nodes", timeout=10) as resp:
        nodes = json.loads(resp.read())
    assert len(nodes) == 1
    stats = nodes[0]["Stats"]
    assert stats["mem_total"] > 0
    assert stats["cpu_count"] >= 1
    assert "cpu_percent" in stats


def test_cluster_nodes_carry_stats():
    import time

    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster()
    try:
        cluster.add_node(num_cpus=1)
        from ray_tpu.experimental import state

        deadline = time.monotonic() + 15
        ok = False
        while time.monotonic() < deadline and not ok:
            nodes = state.list_nodes()
            remote = [n for n in nodes if n.get("Stats")]
            ok = bool(remote) and any(
                n["Stats"].get("mem_total", 0) > 0 for n in remote)
            if not ok:
                time.sleep(0.3)
        assert ok, nodes
    finally:
        cluster.shutdown()


def test_grafana_dashboard_factory(tmp_path):
    """Reference grafana_dashboard_factory.py role: valid importable
    dashboard JSON over the canonical metrics."""
    import json

    from ray_tpu.dashboard.grafana import (generate_default_dashboard,
                                           write_dashboards)

    dash = generate_default_dashboard()
    assert dash["uid"] == "ray-tpu-core"
    assert len(dash["panels"]) == 6
    for p in dash["panels"]:
        assert p["type"] == "timeseries"
        assert p["targets"] and all("expr" in t for t in p["targets"])
        assert p["datasource"]["uid"] == "${datasource}"
    # grid positions don't overlap
    pos = {(p["gridPos"]["x"], p["gridPos"]["y"])
           for p in dash["panels"]}
    assert len(pos) == 6

    paths = write_dashboards(str(tmp_path))
    # core, serve, observability, jobs, object-plane, tenancy
    assert len(paths) == 6
    tenancy = next(p for p in paths if "tenancy" in p)
    with open(tenancy) as f:
        tenancy_exprs = " ".join(t["expr"]
                                 for p in json.load(f)["panels"]
                                 for t in p["targets"])
    assert "ray_tpu_job_quota_rejections_total" in tenancy_exprs
    assert "ray_tpu_job_arena_spill_bytes_total" in tenancy_exprs
    serve = next(p for p in paths if "serve" in p)
    with open(serve) as f:
        serve_exprs = " ".join(t["expr"]
                               for p in json.load(f)["panels"]
                               for t in p["targets"])
    # LLM serving row (PR 16): TTFT + prefix/KV-cache series.
    assert "ray_tpu_serve_ttft_seconds_p50" in serve_exprs
    assert "ray_tpu_serve_ttft_seconds_p99" in serve_exprs
    assert "ray_tpu_llm_kv_cache_hits" in serve_exprs
    assert "ray_tpu_llm_kv_cache_bytes" in serve_exprs
    assert "ray_tpu_llm_model_swaps" in serve_exprs
    # Request-anatomy row (PR 18): stage attribution + affinity rate.
    assert "ray_tpu_request_stage_seconds_p50" in serve_exprs
    assert "ray_tpu_request_stage_seconds_p99" in serve_exprs
    assert "ray_tpu_serve_affinity_hits_total" in serve_exprs
    assert "ray_tpu_serve_affinity_misses_total" in serve_exprs
    obj = next(p for p in paths if "object-plane" in p)
    with open(obj) as f:
        obj_exprs = " ".join(t["expr"]
                             for p in json.load(f)["panels"]
                             for t in p["targets"])
    assert "ray_tpu_object_pull_bytes_total" in obj_exprs
    assert "ray_tpu_object_spill_bytes_total" in obj_exprs
    # Fault-tolerance row (PR 11): recovery work is graphable.
    assert "ray_tpu_node_deaths_total" in obj_exprs
    assert "ray_tpu_reconstructions_total" in obj_exprs
    assert "ray_tpu_actor_restarts_total" in obj_exprs
    for p in paths:
        with open(p) as f:
            loaded = json.load(f)
        assert loaded["schemaVersion"] >= 30

    from ray_tpu.dashboard.grafana import (
        generate_observability_dashboard,
    )

    obs = generate_observability_dashboard()
    assert obs["uid"] == "ray-tpu-observability"
    exprs = " ".join(t["expr"] for p in obs["panels"]
                     for t in p["targets"])
    assert "ray_tpu_batcher_queue_delay_seconds_p95" in exprs
    assert "ray_tpu_sched_submit_to_start_seconds_p95" in exprs

    from ray_tpu.dashboard.grafana import generate_jobs_dashboard

    jobs = generate_jobs_dashboard()
    assert jobs["uid"] == "ray-tpu-jobs"
    exprs = " ".join(t["expr"] for p in jobs["panels"]
                     for t in p["targets"])
    # Per-job attribution panels read the job-tagged series, the SLO
    # burn panel the health plane's gauge.
    assert "ray_tpu_job_cpu_seconds" in exprs
    assert "ray_tpu_job_tasks" in exprs
    assert "ray_tpu_serve_slo_burn_rate" in exprs
    assert "ray_tpu_memory_pressure" in exprs
