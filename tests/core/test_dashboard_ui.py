"""Dashboard UI page + node stats agent plumbing."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu.dashboard import shutdown_dashboard, start_dashboard


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    yield
    shutdown_dashboard()
    ray_tpu.shutdown()


def test_ui_page_served():
    server = start_dashboard(port=0)
    base = f"http://{server.host}:{server.port}"
    with urllib.request.urlopen(f"{base}/ui", timeout=10) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"] == "text/html"
        body = resp.read().decode()
    assert "ray_tpu dashboard" in body
    assert "/api/nodes" in body
    # advertised from the index
    with urllib.request.urlopen(base, timeout=10) as resp:
        assert "/ui" in json.loads(resp.read())["endpoints"]


def test_nodes_carry_stats():
    server = start_dashboard(port=0)
    base = f"http://{server.host}:{server.port}"
    with urllib.request.urlopen(f"{base}/api/nodes", timeout=10) as resp:
        nodes = json.loads(resp.read())
    assert len(nodes) == 1
    stats = nodes[0]["Stats"]
    assert stats["mem_total"] > 0
    assert stats["cpu_count"] >= 1
    assert "cpu_percent" in stats


def test_cluster_nodes_carry_stats():
    import time

    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster()
    try:
        cluster.add_node(num_cpus=1)
        from ray_tpu.experimental import state

        deadline = time.monotonic() + 15
        ok = False
        while time.monotonic() < deadline and not ok:
            nodes = state.list_nodes()
            remote = [n for n in nodes if n.get("Stats")]
            ok = bool(remote) and any(
                n["Stats"].get("mem_total", 0) > 0 for n in remote)
            if not ok:
                time.sleep(0.3)
        assert ok, nodes
    finally:
        cluster.shutdown()
