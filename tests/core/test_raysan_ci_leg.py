"""Tier-1 bounded sanitizer leg: leaks+ambient over the concurrency
regression suites, via the real CLI.

This is the CI integration the ISSUE's acceptance criteria pin: the
concurrency-fix regression tests (``tests/core/test_concurrency*`` and
``tests/serve/test_concurrency_fixes.py``) run under
``--sanitize=leaks,ambient`` with ZERO unsuppressed findings, inside a
hard wall-clock budget, and the JSON report lands as an artifact
(``RAYSAN_REPORT.json`` at the repo root, next to the bench JSONs).
An A/B against the unsanitized run bounds the sanitizer tax at <2x.

One subprocess each way keeps this honest end-to-end (CLI arg parsing,
plugin wiring, report writing) without doubling the whole suite.
"""

import json
import os
import re
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_LEG_BUDGET_S = 60.0
_ARTIFACT = os.path.join(REPO_ROOT, "RAYSAN_REPORT.json")


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def test_sanitizer_leg_clean_bounded_and_under_2x():
    t0 = time.monotonic()
    out = subprocess.run(
        [sys.executable, "-m", "tools.raysan",
         "--sanitize", "leaks,ambient",
         "--report", "json", "--report-file", _ARTIFACT],
        cwd=REPO_ROOT, env=_env(), capture_output=True, text=True,
        timeout=_LEG_BUDGET_S + 30)
    sanitized_wall = time.monotonic() - t0
    assert out.returncode == 0, (
        f"sanitizer leg failed (rc={out.returncode}):\n"
        f"{out.stdout[-4000:]}\n{out.stderr[-2000:]}")
    assert sanitized_wall < _LEG_BUDGET_S, (
        f"sanitizer leg took {sanitized_wall:.1f}s — over the "
        f"{_LEG_BUDGET_S:.0f}s budget; the leg must stay cheap enough "
        f"to run in tier-1 forever")

    # The artifact CI archives.
    with open(_ARTIFACT, "r", encoding="utf-8") as f:
        report = json.load(f)
    assert report["sanitizers"] == ["leaks", "ambient"]
    assert report["findings"] == [], (
        "unsuppressed sanitizer findings on the concurrency leg:\n"
        + "\n".join(f"[{x['sanitizer']}] {x['test']}: {x['message']}"
                    for x in report["findings"]))
    assert report["tests_checked"] >= 14, (
        f"suspiciously few tests ({report['tests_checked']}) — the "
        f"leg's default paths no longer cover the regression suites")

    # A/B: the same paths unsanitized; compare pytest SESSION time (the
    # interpreter+jax startup is identical on both sides and would
    # otherwise mask the thing being measured).
    from tools.raysan.__main__ import DEFAULT_PATHS

    out_base = subprocess.run(
        [sys.executable, "-m", "pytest", *DEFAULT_PATHS, "-q",
         "-p", "no:cacheprovider"],
        cwd=REPO_ROOT, env=_env(), capture_output=True, text=True,
        timeout=_LEG_BUDGET_S + 30)
    assert out_base.returncode == 0, out_base.stdout[-2000:]
    m = re.search(r"in ([0-9.]+)s", out_base.stdout)
    assert m, out_base.stdout[-500:]
    base_s = float(m.group(1))
    # The committed artifact is deterministic: timings are normalized
    # out of it and live in the (gitignored) .timing.json sidecar.
    assert report["elapsed_s"] == 0
    with open(_ARTIFACT + ".timing.json", "r", encoding="utf-8") as f:
        sanitized_s = json.load(f)["elapsed_s"]
    assert sanitized_s < 2.0 * base_s + 3.0, (
        f"sanitizer overhead {sanitized_s:.1f}s vs {base_s:.1f}s "
        f"unsanitized — over the 2x budget (+3s noise floor); profile "
        f"the snapshot/diff path before widening the budget")
