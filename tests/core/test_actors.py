"""Actor API tests (modeled on reference ``python/ray/tests/test_actor.py``)."""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import ActorDiedError


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.value = start

    def increment(self, by=1):
        self.value += by
        return self.value

    def get_value(self):
        return self.value

    def boom(self):
        raise RuntimeError("method failure")


def test_actor_basic(ray_start_regular):
    c = Counter.remote()
    assert ray_tpu.get(c.increment.remote()) == 1
    assert ray_tpu.get(c.increment.remote(5)) == 6


def test_actor_constructor_args(ray_start_regular):
    c = Counter.remote(start=10)
    assert ray_tpu.get(c.get_value.remote()) == 10


def test_actor_method_ordering(ray_start_regular):
    c = Counter.remote()
    refs = [c.increment.remote() for _ in range(50)]
    assert ray_tpu.get(refs) == list(range(1, 51))


def test_actor_method_error_does_not_kill_actor(ray_start_regular):
    c = Counter.remote()
    with pytest.raises(RuntimeError, match="method failure"):
        ray_tpu.get(c.boom.remote())
    assert ray_tpu.get(c.increment.remote()) == 1


def test_actor_constructor_error(ray_start_regular):
    @ray_tpu.remote
    class Bad:
        def __init__(self):
            raise ValueError("ctor fail")

        def f(self):
            return 1

    b = Bad.remote()
    with pytest.raises(Exception):
        ray_tpu.get(b.f.remote())


def test_named_actor(ray_start_regular):
    Counter.options(name="global_counter").remote(start=3)
    handle = ray_tpu.get_actor("global_counter")
    assert ray_tpu.get(handle.get_value.remote()) == 3


def test_named_actor_duplicate_rejected(ray_start_regular):
    Counter.options(name="dup").remote()
    with pytest.raises(ValueError):
        Counter.options(name="dup").remote()


def test_get_if_exists(ray_start_regular):
    h1 = Counter.options(name="gie", get_if_exists=True).remote(start=1)
    h2 = Counter.options(name="gie", get_if_exists=True).remote(start=99)
    assert h1._actor_id == h2._actor_id
    assert ray_tpu.get(h2.get_value.remote()) == 1


def test_kill_actor(ray_start_regular):
    c = Counter.remote()
    ray_tpu.get(c.increment.remote())
    ray_tpu.kill(c)
    time.sleep(0.05)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(c.increment.remote(), timeout=5)


def test_pass_actor_handle(ray_start_regular):
    c = Counter.remote()

    @ray_tpu.remote
    def use(handle):
        return ray_tpu.get(handle.increment.remote(100))

    assert ray_tpu.get(use.remote(c)) == 100


def test_actor_concurrency(ray_start_regular):
    @ray_tpu.remote(max_concurrency=4)
    class Parallel:
        def slow(self):
            time.sleep(0.25)
            return 1

    p = Parallel.remote()
    start = time.monotonic()
    assert sum(ray_tpu.get([p.slow.remote() for _ in range(4)])) == 4
    assert time.monotonic() - start < 0.9


def test_async_actor(ray_start_regular):
    @ray_tpu.remote
    class AsyncActor:
        async def f(self, x):
            import asyncio

            await asyncio.sleep(0.01)
            return x * 2

    a = AsyncActor.remote()
    assert ray_tpu.get(a.f.remote(21)) == 42


def test_actor_resources_held(ray_start_2_cpus):
    @ray_tpu.remote(num_cpus=1)
    class Holder:
        def ping(self):
            return 1

    h1 = Holder.remote()
    h2 = Holder.remote()
    assert ray_tpu.get([h1.ping.remote(), h2.ping.remote()]) == [1, 1]
    # both CPUs held by actors now
    avail = ray_tpu.available_resources()
    assert avail.get("CPU", 0) == 0


def test_actor_handle_in_actor(ray_start_regular):
    c = Counter.remote()

    @ray_tpu.remote
    class Caller:
        def __init__(self, other):
            self.other = other

        def bump(self):
            return ray_tpu.get(self.other.increment.remote())

    caller = Caller.remote(c)
    assert ray_tpu.get(caller.bump.remote()) == 1


def test_list_named_actors(ray_start_regular):
    Counter.options(name="lna1").remote()
    Counter.options(name="lna2").remote()
    from ray_tpu._private.worker import global_worker

    names = set(global_worker().gcs.list_named_actors())
    assert {"lna1", "lna2"} <= names
