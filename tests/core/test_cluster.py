"""Multiprocess cluster-mode tests (reference model:
`ray.cluster_utils.Cluster`-based multi-node tests, SURVEY.md §4)."""

import os
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    yield c
    c.shutdown()


def test_actor_burst_after_flood_constructs_everywhere(cluster):
    """Creation burst right after a saturating flood: the worker nodes'
    pushed availability is stale (reads full) at burst time, so the
    head must NOT park overflow creations in its own backlog behind
    lifetime-pinned actor CPUs — they queue cluster-wide and land on a
    node once its fresh report shows the freed capacity (regression:
    2 of 12 creations hung forever on the head while a node idled)."""
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=1)
    def slow():
        time.sleep(0.15)
        return os.getpid()

    ray_tpu.get([slow.remote() for _ in range(60)], timeout=180)

    @ray_tpu.remote(num_cpus=0.4)
    class A:
        def __init__(self):
            self.pid = os.getpid()

        def ping(self):
            return self.pid

    # 12 x 0.4 CPU = 4.8 over 6 total: every creation must construct
    # and answer, wherever it lands.
    actors = [A.remote() for _ in range(12)]
    pids = ray_tpu.get([a.ping.remote() for a in actors], timeout=120)
    assert len(pids) == 12
    assert len(set(pids)) >= 2, "burst packed onto one process"


def test_remote_node_executes_spillover(cluster):
    cluster.add_node(num_cpus=4)

    @ray_tpu.remote(num_cpus=2)
    def where():
        time.sleep(1.0)  # hold the CPUs so later submits must spill
        return os.getpid()

    # 4 concurrent 2-CPU tasks > head's 2 CPUs → some must spill to the
    # worker node (different pid).
    refs = [where.remote() for _ in range(4)]
    pids = set(ray_tpu.get(refs, timeout=60))
    assert len(pids) >= 2, pids


def test_cross_node_object_transfer(cluster):
    cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=2)
    def produce():
        import numpy as np

        return np.arange(1000)

    @ray_tpu.remote(num_cpus=2)
    def consume(arr):
        return int(arr.sum())

    # Force both tasks off-head by saturating head CPUs.
    @ray_tpu.remote(num_cpus=2)
    def hog():
        time.sleep(1.0)
        return 1

    h = hog.remote()
    data = produce.remote()
    total = consume.remote(data)
    assert ray_tpu.get(total, timeout=60) == 999 * 500
    ray_tpu.get(h)


def test_driver_arg_shipped_to_node(cluster):
    cluster.add_node(num_cpus=2)
    big = list(range(5000))
    ref = ray_tpu.put(big)

    @ray_tpu.remote(num_cpus=2)
    def length(x):
        return len(x)

    @ray_tpu.remote(num_cpus=2)
    def hog():
        time.sleep(0.8)
        return 1

    h = hog.remote()
    assert ray_tpu.get(length.remote(ref), timeout=60) == 5000
    ray_tpu.get(h)


def test_actor_on_remote_node(cluster):
    cluster.add_node(num_cpus=4)

    @ray_tpu.remote(num_cpus=3)  # cannot fit on the 2-CPU head
    class Counter:
        def __init__(self):
            self.pid = os.getpid()
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def whoami(self):
            return self.pid

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 2
    assert ray_tpu.get(c.whoami.remote(), timeout=60) != os.getpid()


def test_node_removal(cluster):
    nid = cluster.add_node(num_cpus=2)
    assert len(cluster.nodes()) == 1
    cluster.remove_node(nid)
    assert len(cluster.nodes()) == 0

    # Cluster still works locally after the node left.
    @ray_tpu.remote
    def f():
        return 42

    assert ray_tpu.get(f.remote(), timeout=30) == 42


def test_creation_burst_respects_capacity_across_nodes(cluster):
    """A burst of actor creations placed within ONE resource-report
    period must spread by true capacity, not pile onto the first node
    whose pushed view still looks free: creations pin CPUs for life,
    so over-placement queues actors that can never start while other
    nodes idle (head-side reservation, _NodeRecord.reserved_milli)."""
    cluster.add_node(num_cpus=4)
    cluster.add_node(num_cpus=4)

    @ray_tpu.remote(num_cpus=1)
    class Holder:
        def pid(self):
            return os.getpid()

    # 2 (head) + 4 + 4 CPUs: eight 1-CPU actors fit exactly — but only
    # if no node is over-committed by the burst.
    actors = [Holder.remote() for _ in range(8)]
    refs = [a.pid.remote() for a in actors]
    ready, pending = ray_tpu.wait(refs, num_returns=len(refs),
                                  timeout=90)
    assert not pending, (
        f"{len(pending)} creations never constructed — burst "
        f"over-placement regressed")
    pids = ray_tpu.get(refs, timeout=30)
    assert len(set(pids)) >= 3  # all three processes actually used
    # Reservations are transient: all released once constructed.
    head = ray_tpu._private.worker.global_worker().backend.head
    assert all(not rec.reserved_milli for rec in head.nodes.values())
    for a in actors:
        ray_tpu.kill(a)
