"""Multiprocess cluster-mode tests (reference model:
`ray.cluster_utils.Cluster`-based multi-node tests, SURVEY.md §4)."""

import os
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    yield c
    c.shutdown()


def test_remote_node_executes_spillover(cluster):
    cluster.add_node(num_cpus=4)

    @ray_tpu.remote(num_cpus=2)
    def where():
        time.sleep(1.0)  # hold the CPUs so later submits must spill
        return os.getpid()

    # 4 concurrent 2-CPU tasks > head's 2 CPUs → some must spill to the
    # worker node (different pid).
    refs = [where.remote() for _ in range(4)]
    pids = set(ray_tpu.get(refs, timeout=60))
    assert len(pids) >= 2, pids


def test_cross_node_object_transfer(cluster):
    cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=2)
    def produce():
        import numpy as np

        return np.arange(1000)

    @ray_tpu.remote(num_cpus=2)
    def consume(arr):
        return int(arr.sum())

    # Force both tasks off-head by saturating head CPUs.
    @ray_tpu.remote(num_cpus=2)
    def hog():
        time.sleep(1.0)
        return 1

    h = hog.remote()
    data = produce.remote()
    total = consume.remote(data)
    assert ray_tpu.get(total, timeout=60) == 999 * 500
    ray_tpu.get(h)


def test_driver_arg_shipped_to_node(cluster):
    cluster.add_node(num_cpus=2)
    big = list(range(5000))
    ref = ray_tpu.put(big)

    @ray_tpu.remote(num_cpus=2)
    def length(x):
        return len(x)

    @ray_tpu.remote(num_cpus=2)
    def hog():
        time.sleep(0.8)
        return 1

    h = hog.remote()
    assert ray_tpu.get(length.remote(ref), timeout=60) == 5000
    ray_tpu.get(h)


def test_actor_on_remote_node(cluster):
    cluster.add_node(num_cpus=4)

    @ray_tpu.remote(num_cpus=3)  # cannot fit on the 2-CPU head
    class Counter:
        def __init__(self):
            self.pid = os.getpid()
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def whoami(self):
            return self.pid

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 2
    assert ray_tpu.get(c.whoami.remote(), timeout=60) != os.getpid()


def test_node_removal(cluster):
    nid = cluster.add_node(num_cpus=2)
    assert len(cluster.nodes()) == 1
    cluster.remove_node(nid)
    assert len(cluster.nodes()) == 0

    # Cluster still works locally after the node left.
    @ray_tpu.remote
    def f():
        return 42

    assert ray_tpu.get(f.remote(), timeout=30) == 42
