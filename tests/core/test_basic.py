"""Core task/object API tests.

Modeled on the reference's ``python/ray/tests/test_basic.py`` coverage:
remote invocation, multiple returns, nested tasks, ref passing, put/get,
wait semantics, error propagation, options validation.
"""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError, TaskError


@ray_tpu.remote
def add(a, b):
    return a + b


@ray_tpu.remote(num_returns=2)
def two_returns(x):
    return x, x + 1


@ray_tpu.remote
def fail():
    raise ValueError("boom")


def test_simple_task(ray_start_regular):
    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_task_kwargs(ray_start_regular):
    assert ray_tpu.get(add.remote(a=5, b=7)) == 12


def test_multiple_returns(ray_start_regular):
    r1, r2 = two_returns.remote(10)
    assert ray_tpu.get([r1, r2]) == [10, 11]


def test_put_get(ray_start_regular):
    ref = ray_tpu.put({"x": [1, 2, 3]})
    assert ray_tpu.get(ref) == {"x": [1, 2, 3]}


def test_pass_object_ref_as_arg(ray_start_regular):
    ref = ray_tpu.put(4)
    # top-level refs are resolved to values before execution
    assert ray_tpu.get(add.remote(ref, 1)) == 5


def test_chained_tasks(ray_start_regular):
    ref = add.remote(1, 1)
    for _ in range(10):
        ref = add.remote(ref, 1)
    assert ray_tpu.get(ref) == 12


def test_nested_submission(ray_start_regular):
    @ray_tpu.remote
    def outer():
        return ray_tpu.get(add.remote(20, 22))

    assert ray_tpu.get(outer.remote()) == 42


def test_deeply_nested_get_no_deadlock(ray_start_2_cpus):
    @ray_tpu.remote
    def rec(n):
        if n == 0:
            return 0
        return ray_tpu.get(rec.remote(n - 1)) + 1

    # depth > num_cpus: requires blocked-worker CPU release
    assert ray_tpu.get(rec.remote(8)) == 8


def test_error_propagation(ray_start_regular):
    with pytest.raises(ValueError, match="boom"):
        ray_tpu.get(fail.remote())


def test_error_is_task_error_too(ray_start_regular):
    with pytest.raises(TaskError):
        ray_tpu.get(fail.remote())


def test_error_propagates_through_dependency(ray_start_regular):
    bad = fail.remote()
    with pytest.raises(ValueError, match="boom"):
        ray_tpu.get(add.remote(bad, 1))


def test_get_timeout(ray_start_regular):
    @ray_tpu.remote
    def slow():
        # Long enough to outlive the 0.1s get-timeout by orders of
        # magnitude, short enough that shutdown's bounded thread join
        # reclaims the executor (threads can't preempt a sleep).
        time.sleep(1.5)

    with pytest.raises(GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.1)


def test_wait(ray_start_regular):
    @ray_tpu.remote
    def sleepy(t):
        time.sleep(t)
        return t

    refs = [sleepy.remote(0.01), sleepy.remote(1.5)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=1, timeout=2)
    assert ready == [refs[0]] and not_ready == [refs[1]]


def test_wait_timeout(ray_start_regular):
    @ray_tpu.remote
    def sleepy():
        time.sleep(1.5)

    refs = [sleepy.remote()]
    ready, not_ready = ray_tpu.wait(refs, num_returns=1, timeout=0.05)
    assert ready == [] and not_ready == refs


def test_wait_rejects_duplicates(ray_start_regular):
    ref = ray_tpu.put(1)
    with pytest.raises(ValueError):
        ray_tpu.wait([ref, ref])


def test_options_override(ray_start_regular):
    assert ray_tpu.get(add.options(name="custom").remote(2, 2)) == 4


def test_invalid_option_rejected(ray_start_regular):
    with pytest.raises(ValueError):
        add.options(nonsense=1)


def test_direct_call_rejected(ray_start_regular):
    with pytest.raises(TypeError):
        add(1, 2)


def test_num_returns_mismatch(ray_start_regular):
    @ray_tpu.remote(num_returns=3)
    def wrong():
        return 1, 2

    with pytest.raises(ValueError):
        ray_tpu.get(wrong.remote()[0])


def test_parallel_execution(ray_start_regular):
    # 4 cpus, 4 sleeps of 0.3s should overlap
    @ray_tpu.remote
    def sleepy():
        time.sleep(0.3)
        return 1

    start = time.monotonic()
    assert sum(ray_tpu.get([sleepy.remote() for _ in range(4)])) == 4
    assert time.monotonic() - start < 1.0


def test_resource_limit_respected(ray_start_2_cpus):
    @ray_tpu.remote(num_cpus=2)
    def heavy():
        time.sleep(0.2)
        return 1

    start = time.monotonic()
    assert sum(ray_tpu.get([heavy.remote() for _ in range(3)])) == 3
    # three 2-cpu tasks on 2 cpus must serialize: >= 0.6s
    assert time.monotonic() - start >= 0.55


def test_infeasible_task_errors(ray_start_2_cpus):
    @ray_tpu.remote(num_cpus=64)
    def big():
        return 1

    with pytest.raises(Exception, match="never be satisfied"):
        ray_tpu.get(big.remote(), timeout=5)


def test_retry_exceptions(ray_start_regular):
    attempts = {"n": 0}

    @ray_tpu.remote(max_retries=3, retry_exceptions=[RuntimeError])
    def flaky():
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise RuntimeError("transient")
        return attempts["n"]

    assert ray_tpu.get(flaky.remote()) == 3


def test_cluster_resources(ray_start_regular):
    assert ray_tpu.cluster_resources()["CPU"] == 4.0


def test_nested_refs_are_borrowed(ray_start_regular):
    inner = ray_tpu.put(7)

    @ray_tpu.remote
    def read_container(container):
        # nested refs arrive as refs, not values
        (ref,) = container
        assert isinstance(ref, ray_tpu.ObjectRef)
        return ray_tpu.get(ref)

    assert ray_tpu.get(read_container.remote([inner])) == 7


def test_cancel_pending_task(ray_start_2_cpus):
    @ray_tpu.remote(num_cpus=2)
    def blocker():
        time.sleep(1.0)

    @ray_tpu.remote(num_cpus=2)
    def victim():
        return 1

    b = blocker.remote()
    v = victim.remote()
    ray_tpu.cancel(v)
    with pytest.raises(Exception):
        ray_tpu.get(v, timeout=5)
    ray_tpu.get(b)
