"""Mutual-TLS on the control-plane RPC (reference: RAY_USE_TLS)."""

import subprocess

import pytest

from ray_tpu._private.config import ray_config
from ray_tpu._private.rpc import RemoteCallError, RpcClient, RpcServer


def _make_certs(d):
    """Self-signed CA + a node cert signed by it (openssl CLI)."""
    ca_key, ca_crt = d / "ca.key", d / "ca.crt"
    key, csr, crt = d / "node.key", d / "node.csr", d / "node.crt"
    run = lambda *a: subprocess.run(a, check=True, capture_output=True)
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
        "-subj", "/CN=test-ca")
    run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(key), "-out", str(csr), "-subj", "/CN=node")
    run("openssl", "x509", "-req", "-in", str(csr), "-CA", str(ca_crt),
        "-CAkey", str(ca_key), "-CAcreateserial", "-out", str(crt),
        "-days", "1")
    return str(ca_crt), str(crt), str(key)


@pytest.fixture
def tls_env(tmp_path):
    ca, crt, key = _make_certs(tmp_path)
    ray_config.use_tls = True
    ray_config.tls_ca_cert = ca
    ray_config.tls_server_cert = crt
    ray_config.tls_server_key = key
    yield tmp_path
    ray_config.use_tls = False
    ray_config.tls_ca_cert = ""
    ray_config.tls_server_cert = ""
    ray_config.tls_server_key = ""


def test_tls_rpc_roundtrip(tls_env):
    server = RpcServer({"mul": lambda a, b: a * b})
    try:
        client = RpcClient.dedicated(server.address)
        assert client.call("mul", a=6, b=7) == 42
        with pytest.raises(RemoteCallError):
            client.call("nope")
        client.close()
    finally:
        server.shutdown()


def test_tls_rejects_untrusted_peer(tls_env, tmp_path):
    server = RpcServer({"f": lambda: 1})
    try:
        # A client presenting a cert from a DIFFERENT CA must be refused
        # during the handshake.
        other = tmp_path / "other"
        other.mkdir()
        ca2, crt2, key2 = _make_certs(other)
        ray_config.tls_ca_cert = ca2
        ray_config.tls_server_cert = crt2
        ray_config.tls_server_key = key2
        client = RpcClient.dedicated(server.address)
        with pytest.raises(Exception):
            client.call("f")
        client.close()
    finally:
        server.shutdown()


def test_tls_requires_all_paths():
    ray_config.use_tls = True
    try:
        with pytest.raises(ValueError, match="requires"):
            RpcServer({"f": lambda: 1})
    finally:
        ray_config.use_tls = False


def test_stalled_handshake_does_not_block_accept_loop(tls_env):
    """A half-open TCP peer that never speaks TLS must not wedge the
    accept loop for well-behaved clients (ADVICE r3: the handshake ran
    inside get_request on the server's single accept thread)."""
    import socket
    import time

    server = RpcServer({"f": lambda: 1})
    try:
        # Raw TCP connect, then silence: if the server handshook in the
        # accept thread this would block every later connection.
        stall = socket.create_connection(server.address)
        time.sleep(0.2)
        t0 = time.monotonic()
        client = RpcClient.dedicated(server.address)
        assert client.call("f") == 1
        assert time.monotonic() - t0 < 5.0
        client.close()
        stall.close()
    finally:
        server.shutdown()
