"""num_returns='dynamic' generator tasks (reference dynamic generators)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_dynamic_generator_basic():
    @ray_tpu.remote(num_returns="dynamic")
    def gen(n):
        for i in range(n):
            yield i * i

    gen_ref = gen.remote(5)
    assert isinstance(gen_ref, ray_tpu.ObjectRef)
    refs = ray_tpu.get(gen_ref)
    assert isinstance(refs, ray_tpu.ObjectRefGenerator)
    assert len(refs) == 5
    assert ray_tpu.get(list(refs)) == [0, 1, 4, 9, 16]
    assert ray_tpu.get(refs[2]) == 4


def test_dynamic_generator_empty_and_list():
    @ray_tpu.remote(num_returns="dynamic")
    def empty():
        return iter(())

    assert len(ray_tpu.get(empty.remote())) == 0

    @ray_tpu.remote(num_returns="dynamic")
    def from_list():
        return [np.arange(3), np.arange(4)]

    refs = ray_tpu.get(from_list.remote())
    arrs = ray_tpu.get(list(refs))
    assert [len(a) for a in arrs] == [3, 4]


def test_dynamic_non_iterable_errors():
    @ray_tpu.remote(num_returns="dynamic")
    def bad():
        return 7

    with pytest.raises(Exception, match="non-iterable"):
        ray_tpu.get(bad.remote())


def test_dynamic_refs_flow_into_downstream_tasks():
    @ray_tpu.remote(num_returns="dynamic")
    def produce():
        for i in range(3):
            yield i + 10

    @ray_tpu.remote
    def total(xs):
        return sum(xs)

    refs = ray_tpu.get(produce.remote())
    assert ray_tpu.get(total.remote(list(ray_tpu.get(list(refs))))) == 33


def test_dynamic_generator_cluster_mode():
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=2)

        @ray_tpu.remote(num_cpus=2, num_returns="dynamic")
        def gen(n):
            for i in range(n):
                yield np.full(100, i)

        refs = ray_tpu.get(gen.remote(4), timeout=60)
        assert len(refs) == 4
        vals = ray_tpu.get(list(refs), timeout=60)
        assert [int(v[0]) for v in vals] == [0, 1, 2, 3]
    finally:
        cluster.shutdown()


def test_dynamic_generator_midstream_failure_frees_partials():
    import ray_tpu._private.worker as wm

    @ray_tpu.remote(num_returns="dynamic", max_retries=0)
    def flaky():
        yield 1
        yield 2
        raise RuntimeError("mid-stream")

    with pytest.raises(Exception, match="mid-stream"):
        ray_tpu.get(flaky.remote())
    # The two yielded objects must not linger in the store.
    w = wm.global_worker()
    import gc

    gc.collect()
    leftovers = [e for e in w.memory_store._entries.values()
                 if e.ready and e.value in (1, 2)]
    assert not leftovers


def test_dynamic_generator_actor_method():
    @ray_tpu.remote
    class Gen:
        def stream(self, n):
            for i in range(n):
                yield i * 3

    g = Gen.remote()
    refs = ray_tpu.get(g.stream.options(num_returns="dynamic").remote(4))
    assert isinstance(refs, ray_tpu.ObjectRefGenerator)
    assert ray_tpu.get(list(refs)) == [0, 3, 6, 9]
