"""Deterministic-schedule replay of the repo's own fixed races.

The two historical concurrency bugs this repo fixed by review
(CHANGES.md PR 4 "review hardening") are re-validated here the way a
sanitizer codebase validates TSAN: each test REVERTS the fix under
monkeypatch to the documented pre-fix form, then drives the exact racy
interleaving through ``raysan.sched.Schedule`` yield-point gates and
asserts the bug manifests — deterministically, in well under 5 seconds,
with no sleeps-and-hope. The unreverted twin runs the same adversarial
schedule against the real code and asserts the invariant holds.

Race 1 — router reserved→in-flight handoff (pre-fix: the decrement of
``_reserved`` and the append to ``_in_flight`` were separate lock
holds; in the gap a dispatched request was counted by neither, so a
concurrent dispatcher could oversubscribe the per-replica cap).

Race 2 — ``PipelinedClient.close`` ordering (pre-fix: ``_closed`` was
set BEFORE the flush; the reader thread exits its drain loop once
``_closed`` is visible, sweeping still-pending, about-to-be-acked
requests into the orphan path — a spurious failure-resubmit at every
clean shutdown that lost the race).

Plus the lock-order witness cross-check: the runtime held-before graph
and raylint R2's static SCC must name the same cycle on the same
fixture code (and agree on the inverted, cycle-free twin).
"""

import threading
import time

import ray_tpu
from ray_tpu._private import sanitize_hooks
from ray_tpu._private.rpc import PipelinedClient, RpcServer
from ray_tpu.serve._private.router import Router
from tools.raysan.sched import Schedule, find_race


class _FakeMethod:
    def __init__(self, fn):
        self._fn = fn

    def remote(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


class _FakeController:
    def __init__(self):
        self.reports = []
        self.listen = _FakeMethod(self._listen)
        self.record_handle_metrics = _FakeMethod(
            lambda dep, total: self.reports.append((dep, total)))

    def _listen(self, *a, **k):
        raise RuntimeError("no controller in this test")


class _Replica:
    def __init__(self, fn):
        self.handle_request = _FakeMethod(fn)


def _make_router(replica, max_concurrent):
    router = Router(_FakeController(), "dep",
                    max_concurrent_queries=max_concurrent)
    router._update_replicas([replica])
    return router


def _pending_ref():
    """An ObjectRef that never resolves: dispatched requests stay
    in-flight for the whole test, so ``_prune`` cannot quietly free a
    slot and mask the oversubscription under scrutiny."""
    from ray_tpu._private.ids import ObjectID
    from ray_tpu.object_ref import ObjectRef

    return ObjectRef(ObjectID.from_random(), _register=False)


# -- race 1: router reserved-slot handoff -----------------------------------


def _buggy_try_assign(self, method, args, kwargs, trace=None, job=None):
    """The PR 4 pre-fix handoff, verbatim in shape: reserved decrement
    and in-flight append under SEPARATE lock holds, with the schedule's
    yield point marking the gap between them."""
    with self._lock:
        replicas = list(self._replicas)
    if not replicas:
        return None
    n = len(replicas)
    start = next(self._rr)
    for i in range(n):
        replica = replicas[(start + i) % n]
        with self._lock:
            load = self._prune(replica) + self._reserved.get(replica, 0)
            if load >= self._max_concurrent:
                continue
            self._reserved[replica] = self._reserved.get(replica, 0) + 1
        ref = replica.handle_request.remote(method, args, kwargs)
        with self._lock:
            self._reserved[replica] -= 1
        # <-- the bug: the request is now counted by NEITHER _reserved
        # nor _in_flight; a concurrent dispatcher sees a free slot.
        sanitize_hooks.sched_point("router.buggy_gap")
        with self._lock:
            self._in_flight.setdefault(replica, []).append(ref)
            self._waiting -= 1
            total = self._pending_report_locked()
        self._send_report(total)
        return ref
    return None


def _drive_router_interleaving(router, sched):
    """Thread A dispatches and (per the schedule) parks in the handoff
    window; the main thread (B) then attempts a second dispatch against
    cap=1 and signals A to resume. Returns (ref_a, ref_b)."""
    refs_a = []
    a = threading.Thread(
        target=lambda: refs_a.append(
            router.try_assign_request("__call__", (), {})),
        name="dispatcher-a")
    with sched:
        a.start()
        # B must not probe before A has entered the window; the gate on
        # A's yield point cannot order B's *lock-free* cap check, so
        # wait for A to park (bounded).
        deadline = time.monotonic() + 3.0
        while not sched.parked_at("router.buggy_gap") \
                and not sched.parked_at("router.handoff"):
            if time.monotonic() > deadline:
                raise AssertionError(
                    "dispatcher A never reached the handoff window")
            time.sleep(0.002)
        ref_b = router.try_assign_request("__call__", (), {})
        sched.cross("test.b_done")
        a.join(3.0)
    assert not a.is_alive(), "dispatcher A wedged in the schedule"
    return (refs_a[0] if refs_a else None), ref_b


def test_router_handoff_race_reproduces_when_fix_reverted(
        ray_start_regular, monkeypatch):
    """Fix reverted: B dispatches into A's handoff gap and the cap-1
    replica ends up with TWO in-flight requests — the historical
    oversubscription, reproduced on demand."""
    monkeypatch.setattr(Router, "_try_assign", _buggy_try_assign)
    replica = _Replica(lambda m, a, k: _pending_ref())
    router = _make_router(replica, max_concurrent=1)
    try:
        sched = Schedule(order=["test.b_done", "router.buggy_gap"],
                         timeout_s=3.0)
        ref_a, ref_b = _drive_router_interleaving(router, sched)
        assert ref_a is not None
        assert ref_b is not None, (
            "expected the reverted handoff to oversubscribe the cap — "
            "the race fixture no longer reproduces the historical bug")
        assert sched.completed
    finally:
        router.shutdown()


def test_router_handoff_clean_with_fix(ray_start_regular):
    """Same adversarial schedule against the REAL handoff: while A is
    parked at the (now atomic) handoff boundary its slot is still
    reserved, so B is refused — the cap holds."""
    replica = _Replica(lambda m, a, k: _pending_ref())
    router = _make_router(replica, max_concurrent=1)
    try:
        sched = Schedule(order=["test.b_done", "router.handoff"],
                         timeout_s=3.0)
        ref_a, ref_b = _drive_router_interleaving(router, sched)
        assert ref_a is not None
        assert ref_b is None, (
            "cap-1 replica accepted a second dispatch mid-handoff: the "
            "reserved-slot invariant regressed")
        assert sched.completed
    finally:
        router.shutdown()


def test_router_handoff_race_found_by_seeded_exploration(
        ray_start_regular, monkeypatch):
    """The exploration half: a small seed sweep over the buggy code
    finds the interleaving without a hand-written script, and the
    recorded trace replays it deterministically."""
    monkeypatch.setattr(Router, "_try_assign", _buggy_try_assign)

    def attempt(sched):
        replica = _Replica(lambda m, a, k: _pending_ref())
        router = _make_router(replica, max_concurrent=1)
        try:
            refs = []
            a = threading.Thread(
                target=lambda: refs.append(
                    router.try_assign_request("__call__", (), {})))
            a.start()
            time.sleep(0.01)  # let A reach (and maybe pause in) the gap
            ref_b = router.try_assign_request("__call__", (), {})
            a.join(3.0)
            return refs and refs[0] is not None and ref_b is not None
        finally:
            router.shutdown()

    found = find_race(attempt, seeds=range(8), pause_max_s=0.5)
    assert found is not None, (
        "no seed in 0..7 reproduced the reverted router race")
    seed, trace = found
    assert any(k.startswith("router.buggy_gap") for k in trace), (
        f"seed {seed} trace never crossed the gap: {trace}")
    # Replay: the race the sweep found means B's lock-free cap check
    # ran inside A's handoff window. Global occurrence keys cannot
    # always express that (when A's paused crossing RECORDS first the
    # trace reads [#1, #2] even though B overtook), so the replay
    # script pins each dispatcher by thread role: B (the main thread)
    # crosses the gap first, then A — the role-qualified form raymc
    # emits for exactly this reason.
    script = ["router.buggy_gap@MainThread",
              "router.buggy_gap@dispatcher-a"]
    replica = _Replica(lambda m, a, k: _pending_ref())
    router = _make_router(replica, max_concurrent=1)
    try:
        sched = Schedule(order=script, timeout_s=3.0)
        ref_a, ref_b = _drive_router_interleaving(router, sched)
        assert ref_a is not None and ref_b is not None, (
            f"replay of seed {seed}'s trace did not reproduce the race")
    finally:
        router.shutdown()


# -- race 2: PipelinedClient close/flush ordering ----------------------------


def _buggy_close(self, flush_timeout=0.0):
    """The PR 4 pre-fix close: ``_closed`` set BEFORE the flush, so a
    reader at its loop edge exits and orphan-sweeps pending requests
    the peer was about to acknowledge."""
    self._closed.set()
    sanitize_hooks.sched_point("rpc.pipeline.closed_set")
    if flush_timeout > 0:
        self.flush(flush_timeout)
    with self._send_lock:
        self._teardown()


class _PipeHarness:
    """An RpcServer whose ``slow`` method parks until released, plus a
    PipelinedClient recording every on_error callback."""

    def __init__(self):
        self.release = threading.Event()
        self.errors = []

        def fast(**kwargs):
            return "ok"

        def slow(**kwargs):
            assert self.release.wait(5.0)
            return "ok"

        self.server = RpcServer({"fast": fast, "slow": slow})
        self.client = PipelinedClient(
            self.server.address,
            on_error=lambda tag, msg, rid, lost: self.errors.append(
                (tag, lost)))

    def shutdown(self):
        self.release.set()
        try:
            self.client.close()
        except Exception:
            pass
        self.server.shutdown()


def test_pipelined_close_race_reproduces_when_fix_reverted(
        ray_start_regular, monkeypatch):
    """Fix reverted: the reader, parked at its loop edge, observes
    ``_closed`` the moment the buggy close sets it and sweeps the
    still-pending (about-to-be-acked) request into the orphan path —
    the spurious failure-resubmit, reproduced deterministically."""
    monkeypatch.setattr(PipelinedClient, "close", _buggy_close)
    h = _PipeHarness()
    try:
        sched = Schedule(
            order=["rpc.pipeline.closed_set",
                   "rpc.pipeline.reader_edge#2"],
            timeout_s=3.0)
        with sched:
            h.client.send("fast", tag="req1")
            assert h.client.flush(3.0), "first request never acked"
            # Reader is now parked at its loop edge (gated). Enqueue
            # the request the server is still working on.
            h.client.send("slow", tag="req2")
            h.client.close(flush_timeout=2.0)
        assert sched.completed
        assert ("req2", True) in h.errors, (
            "expected the reverted close to orphan-sweep req2 — the "
            "race fixture no longer reproduces the historical bug")
    finally:
        h.shutdown()


def test_pipelined_close_clean_with_fix(ray_start_regular):
    """Unreverted: the real close flushes BEFORE setting ``_closed``,
    so the closed flag provably cannot become visible to the reader
    until every pending request was acknowledged — asserted by gating
    ``closed_set`` on the ack of the in-flight request."""
    h = _PipeHarness()
    try:
        sched = Schedule(
            order=["rpc.pipeline.reply_handled#2",
                   "rpc.pipeline.closed_set"],
            timeout_s=3.0)
        with sched:
            h.client.send("fast", tag="req1")
            assert h.client.flush(3.0)
            h.client.send("slow", tag="req2")
            h.release.set()  # the peer acks while close() is flushing
            h.client.close(flush_timeout=3.0)
        assert sched.completed, (
            "close set _closed before the pending ack was handled")
        assert h.errors == [], (
            f"clean shutdown produced spurious orphan errors: "
            f"{h.errors}")
        assert h.client._acked == 2
    finally:
        h.shutdown()


# -- lock-order witness vs raylint R2 static SCC -----------------------------

_CYCLE_SRC = '''\
import threading

lock_a = threading.Lock()
lock_b = threading.Lock()


def ab():
    with lock_a:
        with lock_b:
            pass


def ba():
    with lock_b:
        with lock_a:
            pass
'''

_NO_CYCLE_SRC = _CYCLE_SRC.replace(
    "with lock_b:\n        with lock_a:",
    "with lock_a:\n        with lock_b:")


def _static_scc(source):
    """raylint R2's lock-order SCC over the fixture source: the set of
    lock attribute names in any reported cycle."""
    from tools.raylint.core import analyze_source
    from tools.raylint.rules.r2_lock_discipline import LockDisciplineRule

    cycles = [v for v in analyze_source(source, [LockDisciplineRule()],
                                        module="fixture_mod")
              if "lock-order cycle" in v.message]
    names = set()
    for v in cycles:
        for name in ("lock_a", "lock_b"):
            if name in v.message:
                names.add(name)
    return names


def _runtime_scc(source, tmp_path, fname):
    """The lock witness's SCC over the SAME fixture, executed: the set
    of lock variable names in any runtime cycle (mapped back through
    each lock's creation line)."""
    from tools.raysan.lock_witness import LockOrderSanitizer

    path = tmp_path / fname
    path.write_text(source)
    san = LockOrderSanitizer()
    san.start_session()
    try:
        san.before_test("fixture")
        namespace = {}
        exec(compile(source, str(path), "exec"), namespace)
        namespace["ab"]()
        namespace["ba"]()
        findings = san.after_test("fixture")
    finally:
        san.stop_session()
    lines = source.splitlines()
    names = set()
    for f in findings:
        if "lock-order cycle" not in f.message:
            continue
        for site in f.message.split("{", 1)[1].split("}")[0].split(", "):
            lineno = int(site.rsplit(":", 1)[1])
            names.add(lines[lineno - 1].split("=")[0].strip())
    return names


def test_lock_witness_agrees_with_raylint_r2(ray_start_regular,
                                             tmp_path):
    """Positive/negative pair: on the AB/BA fixture both the runtime
    witness and R2's static SCC report the {lock_a, lock_b} cycle; on
    the consistently-ordered twin both report nothing."""
    assert _static_scc(_CYCLE_SRC) == {"lock_a", "lock_b"}
    assert _runtime_scc(_CYCLE_SRC, tmp_path, "cycle_fix.py") \
        == {"lock_a", "lock_b"}

    assert _static_scc(_NO_CYCLE_SRC) == set()
    assert _runtime_scc(_NO_CYCLE_SRC, tmp_path, "no_cycle_fix.py") \
        == set()
