"""Object-plane bandwidth overhaul: descriptor handoff, shm-backed
entries, arena spill→restore, and locality-aware placement scoring.

Reference roles: plasma store provider promotion of task outputs,
LocalObjectManager spill pipeline (`local_object_manager.h:41`), and
the locality-aware lease policy (`lease_policy.h:56`).
"""

import gc
import os
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from ray_tpu._private.config import ray_config
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.memory_store import MemoryStore
from ray_tpu._private.shm_plane import (SharedPlane, decode_payload,
                                        publish_task_output)
from ray_tpu.object_ref import ObjectRef


@pytest.fixture
def worker_with_plane():
    """A real in-process Worker with a small private arena installed —
    the cheapest honest setup for swap/spill paths (no subprocesses)."""
    import ray_tpu
    from ray_tpu._private import worker as worker_mod

    ray_tpu.shutdown()
    w = worker_mod.init(num_cpus=2)
    plane = SharedPlane(f"/rt_objplane_{os.getpid()}", create=True,
                        capacity=32 * 2**20)
    plane.install(w)
    yield w, plane
    plane.destroy()
    ray_tpu.shutdown()


def _publish(w, value):
    oid = ObjectID.from_random()
    w.memory_store.put(oid, value)
    assert publish_task_output(w, oid, value)
    return oid


def test_output_swap_moves_value_out_of_heap(worker_with_plane):
    """publish_task_output leaves ONE copy — in the arena: the store
    entry becomes a zero-copy view and stops counting against the heap
    spill budget."""
    w, plane = worker_with_plane
    manager = w.memory_store.spill_manager
    before = manager.stats()["in_memory_bytes"]
    value = np.full(1_000_000, 3.25)  # 8 MB
    oid = _publish(w, value)
    assert plane.contains(oid)
    # Heap accounting dropped back: the 8 MB live in the arena now.
    assert manager.stats()["in_memory_bytes"] <= before + 4096
    out = w.memory_store.get(oid)
    np.testing.assert_array_equal(out, value)
    assert not out.flags["OWNDATA"] and not out.flags["WRITEABLE"]


def test_spill_restore_roundtrip_under_forced_eviction(worker_with_plane):
    """Creates that outgrow the arena spill the owner's cold swapped
    objects to disk (URL on the entry) instead of failing; every value
    reads back intact through the transparent restore."""
    w, plane = worker_with_plane
    oids = [_publish(w, np.full(1_000_000, float(i)))  # 8 MB each
            for i in range(6)]  # 48 MB through a 32 MB arena
    stats = w.memory_store.spill_manager.stats()
    assert stats["num_spilled"] >= 2, stats
    spilled = [oid for oid in oids
               if w.memory_store._entries[oid].spilled_url is not None]
    assert spilled, "forced eviction spilled nothing"
    for i, oid in enumerate(oids):
        out = w.memory_store.get(oid)
        assert float(out[0]) == float(i)
    assert w.memory_store.spill_manager.stats()["num_restored"] >= 1


def test_spill_skips_entries_with_live_readers(worker_with_plane):
    """The sole-holder guard: a materialized value still referenced by
    an in-process reader must never leave the arena under it (its
    zero-copy arrays would dangle on block reuse)."""
    w, plane = worker_with_plane
    first = _publish(w, np.full(1_500_000, 1.0))  # 12 MB
    held = w.memory_store.get(first)  # live reader holds the view
    for i in range(3):
        _publish(w, np.full(1_500_000, 2.0 + i))
    entry = w.memory_store._entries[first]
    assert entry.spilled_url is None and entry.shm_backed
    assert float(held[0]) == 1.0  # view still valid
    del held


def test_spill_skips_entries_read_since_swap(worker_with_plane):
    """A reader that extracted an INNER array and dropped the container
    is invisible to any refcount check on the container — read-since-
    swap tracking must still keep the entry out of the arena sweep."""
    w, plane = worker_with_plane
    first = _publish(w, {"w": np.full(1_500_000, 5.0), "tag": "x"})
    inner = w.memory_store.get(first)["w"]  # container dropped, view kept
    for i in range(3):
        _publish(w, np.full(1_500_000, 6.0 + i))
    entry = w.memory_store._entries[first]
    assert entry.spilled_url is None and entry.shm_backed, \
        "read-since-swap entry must never be arena-spilled"
    assert float(inner[0]) == 5.0  # the retained inner view stays valid
    del inner


def test_pin_release_lifecycle_spilled_then_restored(worker_with_plane):
    """Spill → restore → last handle drop: the spill file is deleted,
    the entry is gone, and the arena holds no pin for the object."""
    w, plane = worker_with_plane
    manager = w.memory_store.spill_manager
    oid = _publish(w, np.full(1_000_000, 7.0))
    ref = ObjectRef(oid)  # the driver's handle
    # Force it out: fill the arena so the sweep picks the cold object.
    for i in range(4):
        _publish(w, np.full(1_000_000, 10.0 + i))
    entry = w.memory_store._entries[oid]
    assert entry.spilled_url is not None, "object did not spill"
    path = entry.spilled_url[len("file://"):]
    assert os.path.exists(path)
    assert plane.store.refcount(oid.binary()) == -1, \
        "spilled object still holds an arena block"
    # Transparent restore on get.
    out = w.memory_store.get(oid)
    assert float(out[0]) == 7.0
    # Last handle drop deletes the file and the entry.
    del ref, out, entry
    gc.collect()
    assert oid not in w.memory_store._entries
    assert not os.path.exists(path)
    assert manager.stats()["num_restored"] >= 1


def test_decode_payload_roundtrip():
    """A spilled arena payload (RTS1 layout) reconstructs the value
    with buffers viewing the loaded copy — no arena required."""
    plane = SharedPlane(f"/rt_payload_{os.getpid()}", create=True,
                        capacity=16 * 2**20)
    try:
        oid = ObjectID.from_random()
        value = {"w": np.arange(100_000, dtype=np.float64), "step": 9}
        assert plane.maybe_put(oid, value)
        raw = plane.payload_bytes(oid.binary())
        assert raw is not None and raw[:4] == b"RTS1"
        out = decode_payload(raw)
        np.testing.assert_array_equal(out["w"], value["w"])
        assert out["step"] == 9
    finally:
        plane.destroy()


# -- locality scoring (pure unit: fake head, no subprocesses) ---------------


class _FakeBackendForLocality:
    _arg_bytes_by_addr = None  # bound below

    def __init__(self, head):
        self.head = head


# Borrow the real methods: the scoring logic under test must be the
# production code, not a re-implementation.
from ray_tpu.cluster_utils import ClusterBackendMixin, _NodeRecord  # noqa: E402

_FakeBackendForLocality._arg_bytes_by_addr = \
    ClusterBackendMixin._arg_bytes_by_addr
_FakeBackendForLocality._locality_target = \
    ClusterBackendMixin._locality_target
_FakeBackendForLocality._locality_prefers_remote = \
    ClusterBackendMixin._locality_prefers_remote


def _mk_head(nodes, locations, sizes):
    return SimpleNamespace(nodes=nodes, object_locations=locations,
                           object_sizes=sizes,
                           server=SimpleNamespace(
                               address=("127.0.0.1", 7000)))


def _ref():
    return ObjectRef(ObjectID.from_random(), _register=False)


def _spec(args, cpus=1.0):
    return SimpleNamespace(args=tuple(args), kwargs={},
                           resources={"CPU": cpus})


def _node(node_id, port, cpus=4.0, backlog=0):
    rec = _NodeRecord(node_id, ("127.0.0.1", port), {"CPU": cpus})
    rec.backlog = backlog
    return rec


def test_locality_large_arg_lands_on_owner_node():
    a, b = _node("node-a", 7001), _node("node-b", 7002)
    big = _ref()
    head = _mk_head({"node-a": a, "node-b": b},
                    {big.id.binary(): ("127.0.0.1", 7001)},
                    {big.id.binary(): 64 * 2**20})
    backend = _FakeBackendForLocality(head)
    target = backend._locality_target(_spec([big]))
    assert target is a, "64MB-arg task must follow its bytes"
    assert backend._locality_prefers_remote(_spec([big]))


def test_locality_scores_by_total_resident_bytes():
    """Two args on B outweigh one bigger arg on A."""
    a, b = _node("node-a", 7001), _node("node-b", 7002)
    r1, r2, r3 = _ref(), _ref(), _ref()
    head = _mk_head(
        {"node-a": a, "node-b": b},
        {r1.id.binary(): ("127.0.0.1", 7001),
         r2.id.binary(): ("127.0.0.1", 7002),
         r3.id.binary(): ("127.0.0.1", 7002)},
        {r1.id.binary(): 40 * 2**20,
         r2.id.binary(): 32 * 2**20,
         r3.id.binary(): 32 * 2**20})
    backend = _FakeBackendForLocality(head)
    target = backend._locality_target(_spec([r1, r2, r3]))
    assert target is b


def test_locality_tie_falls_back_to_least_loaded():
    a = _node("node-a", 7001, backlog=500)
    b = _node("node-b", 7002, backlog=0)
    r1, r2 = _ref(), _ref()
    head = _mk_head(
        {"node-a": a, "node-b": b},
        {r1.id.binary(): ("127.0.0.1", 7001),
         r2.id.binary(): ("127.0.0.1", 7002)},
        {r1.id.binary(): 8 * 2**20, r2.id.binary(): 8 * 2**20})
    backend = _FakeBackendForLocality(head)
    target = backend._locality_target(_spec([r1, r2]))
    assert target is b, "equal bytes: the shallower queue wins"


def test_locality_small_args_never_override_pack(monkeypatch):
    a = _node("node-a", 7001)
    small = _ref()
    head = _mk_head({"node-a": a},
                    {small.id.binary(): ("127.0.0.1", 7001)},
                    {small.id.binary(): 4096})
    backend = _FakeBackendForLocality(head)
    assert backend._locality_target(_spec([small])) is None
    assert not backend._locality_prefers_remote(_spec([small]))
    # And the knob turns the whole policy off.
    monkeypatch.setattr(ray_config, "locality_aware_scheduling", False)
    big = _ref()
    head.object_locations[big.id.binary()] = ("127.0.0.1", 7001)
    head.object_sizes[big.id.binary()] = 64 * 2**20
    assert backend._locality_target(_spec([big])) is None


def test_locality_local_bytes_keep_task_local():
    """Args resident on the HEAD outweighing remote args: no override."""
    a = _node("node-a", 7001)
    local_ref, remote_ref = _ref(), _ref()
    head = _mk_head(
        {"node-a": a},
        {local_ref.id.binary(): ("127.0.0.1", 7000),   # head itself
         remote_ref.id.binary(): ("127.0.0.1", 7001)},
        {local_ref.id.binary(): 64 * 2**20,
         remote_ref.id.binary(): 8 * 2**20})
    backend = _FakeBackendForLocality(head)
    assert not backend._locality_prefers_remote(
        _spec([local_ref, remote_ref]))


# -- descriptor read path (two segments, one process) ------------------------


def test_descriptor_reply_and_cross_segment_resolution():
    """Owner answers a batched read with a descriptor; a plane-holding
    requester resolves it by native pull + zero-copy read; a plane-less
    requester still gets values."""
    from ray_tpu._private import wire
    from ray_tpu.cluster_utils import (descriptor_object_read,
                                       resolve_descriptor)

    pid = os.getpid()
    owner_plane = SharedPlane(f"/rt_desc_own_{pid}", create=True,
                              capacity=64 * 2**20)
    reader_plane = SharedPlane(f"/rt_desc_rd_{pid}", create=True,
                               capacity=64 * 2**20)
    reader_plane.allow_local_pull = False  # force the wire
    try:
        port = owner_plane.store.start_transfer_server()
        owner = SimpleNamespace(shm_plane=owner_plane,
                                memory_store=MemoryStore())
        reader = SimpleNamespace(shm_plane=reader_plane,
                                 memory_store=MemoryStore())
        value = np.arange(2_000_000, dtype=np.float64)  # 16 MB
        oid = ObjectID.from_random()
        owner.memory_store.put(oid, value)
        assert owner_plane.maybe_put(oid, value)

        def get_object(ob, t):
            ready, v, err = owner.memory_store.peek(ObjectID(ob))
            return ready, v, err

        # Plane-holding requester on a DIFFERENT segment → descriptor
        # with the transfer endpoint.
        out = descriptor_object_read(
            owner, ("127.0.0.1", port), get_object, [oid.binary()],
            shm=reader_plane.name, can_pull=True)
        ok, desc, err = out[0]
        assert ok and err is None
        assert isinstance(desc, wire.ObjectDescriptor)
        assert desc.shm == owner_plane.name and desc.port == port
        assert desc.size >= value.nbytes
        # The requester materializes it via striped pull + shm read.
        assert resolve_descriptor(reader, oid, desc)
        got = reader.memory_store.get(oid)
        np.testing.assert_array_equal(got, value)
        assert not got.flags["OWNDATA"]

        # Same segment → descriptor without a transfer endpoint.
        out = descriptor_object_read(
            owner, ("127.0.0.1", port), get_object, [oid.binary()],
            shm=owner_plane.name, can_pull=True)
        _, desc2, _ = out[0]
        assert isinstance(desc2, wire.ObjectDescriptor)
        assert desc2.host == "" and desc2.port == 0

        # Plane-less requester → framed value, never a descriptor.
        out = descriptor_object_read(
            owner, ("127.0.0.1", port), get_object, [oid.binary()],
            shm=None, can_pull=False)
        ok, v, err = out[0]
        assert ok and not isinstance(v, wire.ObjectDescriptor)
        np.testing.assert_array_equal(v, value)
    finally:
        owner_plane.destroy()
        reader_plane.destroy()


@pytest.mark.slow
def test_descriptor_pull_source_death_64mb():
    """The striped source-death degradation at product level and ≥64MB:
    a descriptor pull whose source dies MID-STRIPE fails cleanly (no
    partial object), and the same descriptor re-resolved against a
    surviving holder completes with correct bytes."""
    from ray_tpu._private import wire
    from ray_tpu.cluster_utils import resolve_descriptor

    pid = os.getpid()
    src = SharedPlane(f"/rt_sd_src_{pid}", create=True,
                      capacity=192 * 2**20)
    alt = SharedPlane(f"/rt_sd_alt_{pid}", create=True,
                      capacity=192 * 2**20)
    dst = SharedPlane(f"/rt_sd_dst_{pid}", create=True,
                      capacity=192 * 2**20)
    dst.allow_local_pull = False
    try:
        oid = ObjectID.from_random()
        value = np.arange(8_388_608, dtype=np.float64)  # 64 MB
        assert src.maybe_put(oid, value)
        assert alt.maybe_put(oid, value)
        src_port = src.store.start_transfer_server()
        alt_port = alt.store.start_transfer_server()
        reader = SimpleNamespace(shm_plane=dst,
                                 memory_store=MemoryStore())
        size = src.store.object_size(oid.binary())

        def kill_src_mid_transfer():
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if src.store.transfer_stats().get("bytes_sent", 0) > 0:
                    break
                time.sleep(0.0005)
            src.store.stop_transfer_server()

        killer = threading.Thread(target=kill_src_mid_transfer)
        killer.start()
        desc = wire.ObjectDescriptor(oid=oid.binary(), shm=src.name,
                                     host="127.0.0.1", port=src_port,
                                     size=int(size))
        ok = resolve_descriptor(reader, oid, desc)
        killer.join(timeout=30)
        if ok:
            # The 64MB raced past the kill on this host: force the
            # degradation by re-pulling from the now-dead source.
            reader.memory_store.evict([oid])
            dst.evict_object(oid)
            ok = resolve_descriptor(reader, oid, desc)
        assert not ok, "pull from a dead source must fail cleanly"
        assert not dst.contains(oid), "partial object left behind"

        # The surviving holder serves the same object.
        desc_alt = wire.ObjectDescriptor(oid=oid.binary(), shm=alt.name,
                                         host="127.0.0.1",
                                         port=alt_port, size=int(size))
        assert resolve_descriptor(reader, oid, desc_alt)
        got = reader.memory_store.get(oid)
        np.testing.assert_array_equal(got, value)
    finally:
        src.destroy()
        alt.destroy()
        dst.destroy()


def test_pull_slot_config_and_backoff_curve(monkeypatch):
    """The pull-bounding + backoff constants are config knobs."""
    import ray_tpu.cluster_utils as cu

    monkeypatch.setattr(ray_config, "object_pull_max_concurrent", 3)
    slots = cu._wire_pull_slots()
    acquired = [slots.acquire(blocking=False) for _ in range(4)]
    assert acquired == [True, True, True, False]
    for _ in range(3):
        slots.release()
    # Cap change rebuilds the semaphore.
    monkeypatch.setattr(ray_config, "object_pull_max_concurrent", 1)
    slots2 = cu._wire_pull_slots()
    assert slots2 is not slots
    assert slots2.acquire(blocking=False)
    slots2.release()

    monkeypatch.setattr(ray_config, "object_fetch_backoff_base_s", 0.0)
    monkeypatch.setattr(ray_config, "object_fetch_backoff_cap_s", 0.0)
    t0 = time.perf_counter()
    for attempt in range(50):
        cu.fetch_backoff(attempt)
    assert time.perf_counter() - t0 < 0.25, \
        "zeroed backoff knobs must zero the sleeps"
