"""Point-to-point collective send/recv (reference:
`util/collective/collective.py:541-615`): two-actor roundtrip, in-place
fill, ordering, and misuse errors."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class P2PWorker:
    def __init__(self, rank: int, world: int, group: str):
        from ray_tpu.util import collective

        self.rank = rank
        collective.init_collective_group(world, rank, group_name=group)
        self.group = group

    def roundtrip_a(self, payload):
        """Rank 0 half: send, then recv the peer's transform back."""
        from ray_tpu.util import collective

        collective.send(payload, dst_rank=1, group_name=self.group)
        out = np.zeros_like(np.asarray(payload))
        got = collective.recv(out, src_rank=1, group_name=self.group)
        # in-place contract: the passed buffer holds the result too
        assert np.array_equal(out, got)
        return got

    def roundtrip_b(self):
        """Rank 1 half: recv, double, send back."""
        from ray_tpu.util import collective

        got = collective.recv(np.empty(0), src_rank=0,
                              group_name=self.group)
        collective.send(got * 2, dst_rank=0, group_name=self.group)
        return got

    def send_many(self, values, dst):
        from ray_tpu.util import collective

        for v in values:
            collective.send(np.asarray(v), dst_rank=dst,
                            group_name=self.group)
        return True

    def recv_many(self, n, src):
        from ray_tpu.util import collective

        return [int(collective.recv(np.empty(0), src_rank=src,
                                    group_name=self.group))
                for _ in range(n)]


def test_two_actor_roundtrip():
    a = P2PWorker.remote(0, 2, "p2p_rt")
    b = P2PWorker.remote(1, 2, "p2p_rt")
    payload = np.arange(8, dtype=np.float32)
    ref_a = a.roundtrip_a.remote(payload)
    ref_b = b.roundtrip_b.remote()
    got_back, got_at_b = ray_tpu.get([ref_a, ref_b], timeout=60)
    assert np.array_equal(np.asarray(got_at_b), payload)
    assert np.array_equal(np.asarray(got_back), payload * 2)


def test_p2p_ordering_many_messages():
    """Messages between one (src, dst) pair arrive in program order —
    the per-pair sequence numbers, not arrival races, pair sends with
    recvs."""
    a = P2PWorker.remote(0, 2, "p2p_ord")
    b = P2PWorker.remote(1, 2, "p2p_ord")
    sent = list(range(20))
    ref_a = a.send_many.remote(sent, 1)
    ref_b = b.recv_many.remote(len(sent), 0)
    _, received = ray_tpu.get([ref_a, ref_b], timeout=60)
    assert received == sent


def test_send_recv_misuse():
    from ray_tpu.util import collective

    collective.init_collective_group(1, 0, group_name="p2p_self")
    with pytest.raises(ValueError, match="send to self"):
        collective.send(np.ones(2), dst_rank=0, group_name="p2p_self")
    with pytest.raises(ValueError, match="recv from self"):
        collective.recv(np.ones(2), src_rank=0, group_name="p2p_self")
    with pytest.raises(RuntimeError, match="not initialized"):
        collective.send(np.ones(2), dst_rank=1, group_name="nope")
    collective.destroy_collective_group("p2p_self")
