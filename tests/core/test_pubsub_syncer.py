"""Pubsub (long-poll) + pushed resource view (syncer role).

Reference: `src/ray/pubsub/publisher.h:302` (buffer + long-poll),
`src/ray/common/ray_syncer/ray_syncer.h:86` (RESOURCE_VIEW deltas).
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu._private.pubsub import Publisher, Subscriber
from ray_tpu.cluster_utils import Cluster


def test_publisher_long_poll_basics():
    pub = Publisher()
    # Poll with nothing published: times out empty.
    reply = pub.poll("ch", "s1", cursor=0, timeout=0.05)
    assert reply["messages"] == [] and reply["cursor"] == 0

    pub.publish("ch", {"a": 1})
    pub.publish("ch", {"a": 2})
    reply = pub.poll("ch", "s1", cursor=0, timeout=0.5)
    assert [m["a"] for m in reply["messages"]] == [1, 2]
    cursor = reply["cursor"]
    # Nothing new past the cursor.
    assert pub.poll("ch", "s1", cursor=cursor,
                    timeout=0.05)["messages"] == []

    # A blocked poll wakes on publish.
    out = {}

    def poll():
        out["reply"] = pub.poll("ch", "s1", cursor=cursor, timeout=5)

    t = threading.Thread(target=poll)
    t.start()
    time.sleep(0.1)
    pub.publish("ch", {"a": 3})
    t.join(timeout=5)
    assert [m["a"] for m in out["reply"]["messages"]] == [3]


def test_subscriber_delivers_messages():
    pub = Publisher()
    got = []
    sub = Subscriber(
        lambda **kw: pub.poll(kw["channel"], kw["subscriber_id"],
                              kw["cursor"], 0.2),
        "sub-1")
    sub.subscribe("events", got.append)
    for i in range(3):
        pub.publish("events", i)
    deadline = time.monotonic() + 5
    while len(got) < 3 and time.monotonic() < deadline:
        time.sleep(0.02)
    sub.close()
    assert got == [0, 1, 2]


@pytest.fixture
def cluster():
    c = Cluster(head_node_args={"num_cpus": 1})
    yield c
    c.shutdown()


def test_node_lifecycle_events_published(cluster):
    from ray_tpu._private.config import ray_config

    node = cluster.add_node(num_cpus=1)
    reply = cluster.head.publisher.poll("node_events", "t", 0, timeout=1)
    events = {(m["event"], m["node_id"]) for m in reply["messages"]}
    assert ("NODE_ADDED", node) in events

    cluster.kill_node(node)
    deadline = time.monotonic() + \
        ray_config.health_check_period_s * 30 + 10
    cursor = reply["cursor"]
    seen_dead = False
    while time.monotonic() < deadline and not seen_dead:
        reply = cluster.head.publisher.poll("node_events", "t", cursor,
                                            timeout=1)
        cursor = reply["cursor"]
        seen_dead = any(m["event"] == "NODE_DEAD" and m["node_id"] == node
                        for m in reply["messages"])
    assert seen_dead


def test_resource_view_pushed_and_scheduling_uses_it(cluster):
    from ray_tpu._private.config import ray_config

    node = cluster.add_node(num_cpus=2)
    record = cluster.head.nodes[node]
    t0 = record.last_report

    # Reports arrive without the head asking. Generous deadline: the
    # loop exits on the first report, but a saturated single-core CI
    # host can hold the node's report thread past 10s.
    deadline = time.monotonic() + 30
    while record.last_report == t0 and time.monotonic() < deadline:
        time.sleep(ray_config.resource_report_period_s)
    assert record.last_report > t0
    assert record.available.get("CPU") == 2.0

    # Scheduling via the cached view still lands work on the node.
    import os

    @ray_tpu.remote(num_cpus=2)
    def where():
        return os.getpid()

    assert ray_tpu.get(where.remote(), timeout=60) != os.getpid()
    # While the task runs... (it already finished) — after completion the
    # next report restores availability.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if record.available.get("CPU") == 2.0:
            break
        time.sleep(0.05)
    assert record.available.get("CPU") == 2.0
