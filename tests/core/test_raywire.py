"""raywire unit contracts: extraction, compat classification, the
version-bump + migration-note gate, skew simulation, fuzz drivers, and
the minimized fixture corpus replay.

The CI leg (``test_raywire_ci_leg.py``) proves the rung runs green
end-to-end; these tests prove each stage would actually catch the
defect class it exists for — a gate that passes everything is
indistinguishable from a gate that works, until someone reorders a
frame's fields.
"""

import copy
import os
import random
import struct
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:  # `tools` must resolve from the repo root
    sys.path.insert(0, REPO_ROOT)

import pytest  # noqa: E402

from ray_tpu._private import wire  # noqa: E402
from tools.raywire import compat, extract, fixtures, fuzz, gen  # noqa: E402

_U32 = struct.Struct("!I")


@pytest.fixture(scope="module")
def extraction():
    return extract.extract(REPO_ROOT)


@pytest.fixture(scope="module")
def schema(extraction):
    return extraction.schema


# -- extraction -------------------------------------------------------------


def test_extraction_clean_and_complete(extraction):
    assert extraction.problems == []
    assert set(extraction.schema["messages"]) == set(wire._REGISTRY)
    frame = extraction.schema["frame"]
    assert frame["max_depth"] == wire._MAX_DEPTH
    # Encoder and decoder agree on the tag alphabet, and every tag is
    # in the rendered grammar.
    assert set("NTFiIdsbltmMO") <= set(frame["tags"])


def test_extraction_catches_ast_live_drift(schema, monkeypatch):
    # A message registered behind the AST's back (monkeypatched into
    # the live registry) must surface as an extraction problem.
    monkeypatch.setitem(wire._REGISTRY, "ghost.Message",
                        (wire.Reply, 1))
    ex = extract.extract(REPO_ROOT)
    assert any("ghost.Message" in p and "dynamic registration" in p
               for p in ex.problems)


def test_migration_note_grammar():
    m = extract.MIGRATION_RE.search(
        "# raywire: migration=rpc.Request -- method retired, "
        "see head_shards rollout notes")
    assert m and m.group(1) == "rpc.Request"
    assert m.group("why").startswith("method retired")
    assert extract.MIGRATION_RE.search("# raywire: migration=x") is None


def test_render_schema_is_canonical(schema):
    assert extract.render_schema(schema) \
        == extract.render_schema(copy.deepcopy(schema))
    assert extract.render_schema(schema).endswith("\n")


def test_committed_baseline_matches_live_code(schema):
    # The gate is only as good as the baseline's freshness: the
    # committed RAYWIRE_SCHEMA.json must equal what extraction produces
    # from the checked-out wire.py (regenerate with --write-baseline
    # after any sanctioned schema change).
    baseline = extract.load_baseline(
        os.path.join(REPO_ROOT, "RAYWIRE_SCHEMA.json"))
    assert baseline is not None, "RAYWIRE_SCHEMA.json missing"
    assert baseline == schema, (
        "committed baseline drifted from wire.py — run "
        "`python -m tools.raywire --write-baseline` (the gate must "
        "approve the diff first)")


# -- compat classification + gate -------------------------------------------


def _mutated(schema, message, fn):
    new = copy.deepcopy(schema)
    fn(new["messages"][message])
    return new


def test_identical_schemas_gate_clean(schema, extraction):
    gate = compat.run_gate(schema, schema, extraction.migration_notes)
    assert gate.ok and not gate.changes
    for result in gate.skew.values():
        assert result["classified"] == "compatible"
        assert result["old_to_new"]["ok"]
        assert result["new_to_old"]["ok"]
        assert result["byte_identity"]


def test_field_append_with_default_is_compatible(schema):
    new = _mutated(schema, "rpc.Reply", lambda m: m["fields"].append(
        {"name": "trace", "type": "str", "has_default": True}))
    gate = compat.run_gate(schema, new, {})
    assert gate.ok
    kinds = {c.kind for c in gate.changes}
    assert kinds == {"field_appended"}
    # Old receivers drop the appended field — visible, not fatal.
    assert gate.skew["rpc.Reply"]["new_to_old"]["skipped"] == ["trace"]


def test_new_message_is_compatible(schema):
    new = copy.deepcopy(schema)
    new["messages"]["task.Cancel"] = {
        "version": 1, "class": "TaskCancel",
        "fields": [{"name": "task_id", "type": "bytes",
                    "has_default": False}]}
    gate = compat.run_gate(schema, new, {})
    assert gate.ok
    assert {c.kind for c in gate.changes} == {"message_added"}


@pytest.mark.parametrize("kind,mutate", [
    ("field_removed", lambda m: m["fields"].pop(1)),
    ("field_type_changed",
     lambda m: m["fields"][0].__setitem__("type", "bytes")),
    ("field_appended_no_default", lambda m: m["fields"].append(
        {"name": "extra", "type": "int", "has_default": False})),
    ("field_reordered",
     lambda m: m["fields"].reverse()),
])
def test_breaking_changes_fail_without_bump(schema, kind, mutate):
    new = _mutated(schema, "rpc.Request", mutate)
    gate = compat.run_gate(schema, new, {})
    assert not gate.ok
    assert kind in {c.kind for c in gate.changes if c.breaking}
    assert any("version bump" in f for f in gate.failures)


def test_rename_reported_as_one_breaking_change(schema):
    def mutate(m):
        m["fields"][0]["name"] = "request_id"
    new = _mutated(schema, "rpc.Request", mutate)
    gate = compat.run_gate(schema, new, {})
    assert not gate.ok
    assert "field_renamed" in {c.kind for c in gate.changes}


def test_message_removed_is_breaking(schema):
    new = copy.deepcopy(schema)
    del new["messages"]["task.Call"]
    gate = compat.run_gate(schema, new, {})
    assert not gate.ok
    assert {c.kind for c in gate.changes} == {"message_removed"}


def test_version_bump_plus_migration_note_passes(schema):
    def mutate(m):
        m["fields"].pop(1)
        m["version"] += 1
    new = _mutated(schema, "rpc.Request", mutate)
    # Bump without the note: still fails, naming what's missing.
    gate = compat.run_gate(schema, new, {})
    assert not gate.ok
    assert any("no justified migration note" in f
               for f in gate.failures)
    # Bump + note: sanctioned.
    gate = compat.run_gate(
        schema, new,
        {"rpc.Request": "field retired with the v2 envelope"})
    assert gate.ok
    assert any(c.kind == "version_changed" for c in gate.changes)


def test_skew_simulator_proves_type_change_empirically(schema):
    new = _mutated(
        schema, "node.ResourceReport",
        lambda m: m["fields"][0].__setitem__("type", "int"))
    gate = compat.run_gate(schema, new, {})
    skew = gate.skew["node.ResourceReport"]
    assert skew["classified"] == "breaking"
    assert not skew["new_to_old"]["ok"]
    assert "expected" in skew["new_to_old"]["error"]


def test_skew_simulator_detects_reorder_byte_divergence(schema):
    new = _mutated(schema, "task.Template",
                   lambda m: m["fields"].reverse())
    gate = compat.run_gate(schema, new, {})
    assert gate.skew["task.Template"]["byte_identity"] is False


def test_compatible_classification_with_observed_failure_fails_gate(
        schema, monkeypatch):
    # Defense in depth: even if the diff logic mislabels a change as
    # compatible, an observed skew decode failure still fails the
    # gate. Force the blind spot by neutering BREAKING classification.
    new = _mutated(schema, "rpc.Reply", lambda m: m["fields"].append(
        {"name": "extra", "type": "int", "has_default": False}))
    monkeypatch.setattr(
        compat, "diff_schemas", lambda old, new_: [])
    gate = compat.run_gate(schema, new, {})
    assert not gate.ok
    assert any("classified compatible but the skew simulator"
               in f for f in gate.failures)


# -- fuzz drivers + minimization --------------------------------------------


def test_fuzz_clean_small_campaign(schema):
    report = fuzz.run_fuzz(schema, n_inputs=1500, seed=7)
    assert report["findings"] == []
    assert report["slow"] == []
    assert all(p["ok"] for p in report["alloc_probes"])
    # Every target and mutator actually participated.
    assert all(n > 0 for n in report["per_target"].values())
    assert all(n > 0 for n in report["per_mutator"].values())


def test_fuzz_campaign_is_deterministic(schema):
    a = fuzz.run_fuzz(schema, n_inputs=300, seed=3)
    b = fuzz.run_fuzz(schema, n_inputs=300, seed=3)
    assert a["per_mutator"] == b["per_mutator"]
    assert a["findings"] == b["findings"]


def test_alloc_probes_bound_peak_memory():
    for probe in fuzz.run_alloc_probes():
        assert probe["ok"], (
            f"{probe['probe']} peaked at {probe['peak_bytes']}B — a "
            f"4-byte header bought a real allocation")


def test_fuzzer_catches_a_seeded_decoder_regression(schema,
                                                    monkeypatch):
    # The campaign must actually be able to see a crash: re-open the
    # historical utf-8 hole and the same seeds must surface it.
    def leaky(self):
        n, = wire._U32.unpack_from(self.raw, self.pos)
        self.pos += 4
        return self._take(n).decode()    # undoes the WireError wrap

    monkeypatch.setattr(wire._Decoder, "_str", leaky)
    report = fuzz.run_fuzz(schema, n_inputs=2000, seed=11)
    assert any(f["exc_type"] == "UnicodeDecodeError"
               for f in report["findings"])


def test_minimizer_shrinks_reproducer():
    # A bad tag buried in a long valid prefix minimizes to (nearly)
    # just the crashing byte.
    from ray_tpu._private import wire as w

    def drive(data):
        w.decode(data)

    payload = w.encode([1, 2, 3]) + b"\xff" * 40
    with pytest.raises(w.WireError):
        drive(payload)
    minimized = fuzz._minimize(payload, drive, w.WireError)
    assert len(minimized) < len(payload)
    with pytest.raises(w.WireError):
        drive(minimized)


def test_proxy_driver_handles_dribble_identically():
    data = (b"POST /v1 HTTP/1.1\r\nHost: a\r\n"
            b"Content-Length: 5\r\n\r\nhello")
    conn = fuzz._fresh_conn()
    conn.buf = data
    conn._parse()
    assert len(conn.backlog) == 1
    assert conn.backlog[0].body == b"hello"
    fuzz.drive_proxy(data)   # must not raise


# -- fixture corpus ---------------------------------------------------------


def test_fixture_corpus_present_and_replays_clean():
    results = fixtures.replay_all(
        os.path.join(REPO_ROOT, fixtures.FIXTURE_DIR))
    assert len(results) >= 15, (
        "the minimized fixture corpus shrank — fixtures are the "
        "regression tests for every defect the fuzzer ever found")
    failures = [r for r in results if not r["ok"]]
    assert failures == [], failures


def test_fixture_corpus_covers_every_target():
    fxs = fixtures.load_fixtures(
        os.path.join(REPO_ROOT, fixtures.FIXTURE_DIR))
    assert {fx["target"] for fx in fxs} \
        == {"wire", "rpc", "shard", "proxy"}
    # Both polarity classes are pinned: typed rejections AND nominal
    # accepts (guards must not over-reject).
    assert {fx["expect"] for fx in fxs} == {"accept", "reject"}


def test_fixture_replay_fails_loudly_on_untyped_escape(monkeypatch):
    # If a fixed defect regresses (typed WireError back to a raw
    # crash), replay must propagate the raw exception, not record a
    # polite mismatch.
    def exploding_decode(data, allow_opaque=True):
        raise UnicodeDecodeError("utf-8", b"", 0, 1, "regressed")

    monkeypatch.setattr(wire, "decode", exploding_decode)
    fx = {"name": "wire-bad-utf8-str", "target": "wire",
          "input_hex": "73000000002ff", "expect": "reject",
          "exc_type": "WireError"}
    fx["input_hex"] = (b"s" + _U32.pack(2) + b"\xff\xfe").hex()
    with pytest.raises(UnicodeDecodeError):
        fixtures.replay(fx)


# -- shard apply hardening (the fuzz-found defect, pinned directly) ---------


def test_shard_apply_rejects_non_row_items_typed():
    from ray_tpu._private.head_shards import HeadShardState

    state = HeadShardState(0, 1)
    with pytest.raises(wire.WireError, match="neither a ShardRow"):
        state.apply([wire.Request(id="r1", method="x", kwargs={})])
    # Rows before the bad item stay applied (idempotent retry model).
    with pytest.raises(wire.WireError):
        state.apply([("put", "objects", b"k", 1), object()])
    assert state.tables["objects"][b"k"] == 1
