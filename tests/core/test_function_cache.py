"""Function-distribution export cache (reference: function_manager
export via GCS KV + worker import thread): repeat submissions of the
same function travel without the function body."""

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

pytestmark = pytest.mark.slow


def test_repeat_submissions_strip_function_bodies():
    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=4)

        payload = list(range(2000))  # make the closure visibly heavy

        @ray_tpu.remote(num_cpus=2)
        def heavy(i):
            return payload[i] + 1

        assert ray_tpu.get([heavy.remote(i) for i in range(20)],
                           timeout=120) == [i + 1 for i in range(20)]
        head = cluster.head
        # exactly one export for the function, not 20
        assert len(head.exported_fns) >= 1
        node = next(iter(head.nodes.values()))
        assert node.known_fns & head.exported_fns
        # the definition is durably in the head KV
        fid = next(iter(head.exported_fns))
        assert head.worker.gcs.kv_get(fid, namespace=b"__fn__")

        # a SECOND node gets the body on ITS first shipment and caches
        cluster.add_node(num_cpus=4)

        @ray_tpu.remote(num_cpus=4)
        def where():
            import os

            return os.getpid()

        pids = set(ray_tpu.get([where.remote() for _ in range(8)],
                               timeout=120))
        assert pids  # executed somewhere; correctness via values above
    finally:
        cluster.shutdown()


def test_stripped_spec_survives_node_death_resubmission():
    """Resubmission after node death reships from the ORIGINAL spec
    (function body intact for the new target)."""
    import time

    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=4)

        @ray_tpu.remote(num_cpus=2, max_retries=3)
        def slowish(i):
            import time as t

            t.sleep(0.5)
            return i * 7

        # warm the cache so later sends are stripped
        assert ray_tpu.get(slowish.remote(1), timeout=60) == 7
        refs = [slowish.remote(i) for i in range(4)]
        time.sleep(0.1)
        victim = next(iter(cluster.head.nodes))
        cluster.add_node(num_cpus=4)  # survivor capacity first
        cluster.remove_node(victim, graceful=False)
        assert ray_tpu.get(refs, timeout=120) == [0, 7, 14, 21]
    finally:
        cluster.shutdown()
