"""PrefixCache decision-core unit suite (PR 16).

Pure-Python and fast: this file is also one of rayspec's
``DEFAULT_PATHS``, so every op here runs under the history recorder in
the tier-1 rayspec leg and the recorded interleavings are checked
against ``KvCacheSpec`` for linearizability — keep it driving every
public op (lookup/pin/release/admit/evict), including concurrently.
"""

import threading

import pytest

from ray_tpu._private.kv_cache import (
    BlockHandle,
    PrefixCache,
    chain_keys,
)

_BT = 4
_NB = 64  # block payload bytes used throughout


def _chain(tokens):
    return chain_keys(tokens, _BT, seed="test")


def test_chain_keys_commit_to_prefix_and_seed():
    toks = list(range(12))
    keys = _chain(toks)
    assert len(keys) == 3  # full chunks only
    assert _chain(toks[:11]) == keys[:2]  # partial tail never keyed
    # A different earlier token changes EVERY later key (hash chain).
    other = _chain([99] + toks[1:])
    assert all(a != b for a, b in zip(keys, other))
    # A different seed (= model identity) is fully disjoint.
    assert not set(keys) & set(chain_keys(toks, _BT, seed="other"))


def test_lookup_longest_resident_prefix_and_counters():
    pc = PrefixCache(capacity_bytes=_NB * 8, block_tokens=_BT)
    chain = _chain(list(range(16)))  # 4 blocks
    created, evicted = pc.admit(chain[:3], "job-a", _NB)
    assert [h.key for h in created] == list(chain[:3]) and not evicted
    pc.release(created)

    hit = pc.lookup(chain)
    assert [h.key for h in hit] == list(chain[:3])
    assert pc.stats()["hits"] == 3 and pc.stats()["misses"] == 1
    pc.release(hit)

    # Handles carry the chunk position, so a sub-chain lookup pins
    # exactly the blocks it names.
    hit1 = pc.lookup(chain[:1])
    assert [h.index for h in hit1] == [0]
    pc.release(hit1)


def test_pinned_blocks_never_evicted_lru_order_and_charges():
    pc = PrefixCache(capacity_bytes=_NB * 2, block_tokens=_BT)
    c1 = _chain([1, 2, 3, 4])
    c2 = _chain([5, 6, 7, 8])
    c3 = _chain([9, 10, 11, 12])
    h1, _ = pc.admit(c1, "job-a", _NB)
    h2, _ = pc.admit(c2, "job-b", _NB)
    assert pc.charges() == {"job-a": _NB, "job-b": _NB}

    # Both resident blocks are pinned: admitting a third cannot evict
    # them — it degrades to a no-op admit instead of freeing held KV.
    h3, evicted = pc.admit(c3, "job-c", _NB)
    assert h3 == [] and evicted == []
    assert pc.contains(c1[0]) and pc.contains(c2[0])

    # Unpin c1 only: now c1 (LRU, unpinned) is the victim; c2 (still
    # pinned) survives. The evicted block's charge moves off job-a.
    pc.release(h1)
    h3, evicted = pc.admit(c3, "job-c", _NB)
    assert [h.key for h in h3] == list(c3)
    assert [e.key for e in evicted] == list(c1)
    assert not pc.contains(c1[0]) and pc.contains(c2[0])
    assert pc.charges() == {"job-b": _NB, "job-c": _NB}
    assert pc.resident_bytes == 2 * _NB
    pc.release(h2)
    pc.release(h3)


def test_refcount_misuse_raises_typed():
    pc = PrefixCache(capacity_bytes=_NB * 4, block_tokens=_BT)
    created, _ = pc.admit(_chain([1, 2, 3, 4]), "j", _NB)
    pc.release(created)
    with pytest.raises(ValueError):
        pc.release(created)  # double release = freed-bytes-in-flight
    with pytest.raises(ValueError):
        pc.pin([BlockHandle("no-such-key", 1, 0)])
    stale = BlockHandle(created[0].key, created[0].block_id + 999, 0)
    with pytest.raises(ValueError):
        pc.pin([stale])  # wrong generation: a re-admitted key


def test_evict_frees_only_unpinned_and_digests_are_mru():
    pc = PrefixCache(capacity_bytes=_NB * 8, block_tokens=_BT)
    ca = _chain(list(range(8)))       # 2 blocks, will stay pinned
    cb = _chain(list(range(50, 58)))  # 2 blocks, released
    ha, _ = pc.admit(ca, "j", _NB)
    hb, _ = pc.admit(cb, "j", _NB)
    pc.release(hb)
    out = pc.evict(_NB * 8)
    assert {e.key for e in out} == set(cb)
    assert pc.resident_bytes == 2 * _NB
    assert set(pc.hot_digests(8)) == set(ca)
    pc.release(ha)


def test_concurrent_admit_lookup_evict_is_safe():
    """Race the full op surface from many threads; the invariants the
    spec checks (no negative refs, pinned never evicted, charge
    conservation) must hold under every interleaving."""
    pc = PrefixCache(capacity_bytes=_NB * 6, block_tokens=_BT)
    chains = [_chain(list(range(base, base + 12)))
              for base in (0, 100, 200, 300)]
    errors = []

    def worker(chain, job):
        try:
            for _ in range(25):
                hit = pc.lookup(chain, job)
                pc.pin(hit)
                pc.release(hit)
                created, _evicted = pc.admit(chain, job, _NB)
                pc.release(created)
                pc.release(hit)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    def evictor():
        try:
            for _ in range(40):
                pc.evict(_NB)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(c, f"job-{i}"))
               for i, c in enumerate(chains)]
    threads.append(threading.Thread(target=evictor))
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    # Quiesced: nothing is pinned, so resident bytes equal the sum of
    # per-job charges (conservation) and everything is evictable.
    assert pc.resident_bytes == sum(pc.charges().values())
    pc.evict(pc.resident_bytes)
    assert pc.resident_bytes == 0
    assert pc.charges() == {}
