"""raysan.sched: the deterministic interleaving harness itself.

These pin the schedule semantics the race-replay fixtures
(``test_concurrency_races.py``) build on: scripted gate ordering,
occurrence suffixes, free passage of unlisted points, the loud timeout
instead of a hang, and seeded exploration recording a replayable trace.
"""

import threading
import time

import pytest

from ray_tpu._private import sanitize_hooks
from tools.raysan.sched import Schedule, ScheduleTimeout, find_race


def _spawn(*fns):
    threads = [threading.Thread(target=fn, name=f"sched-t{i}")
               for i, fn in enumerate(fns)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(5.0)
    assert not any(t.is_alive() for t in threads)


def test_scripted_order_is_enforced():
    log = []
    sched = Schedule(order=["b.step", "a.step"], timeout_s=3.0)

    def a():
        log.append("a-before")
        sched.cross("a.step")
        log.append("a-after")

    def b():
        time.sleep(0.05)  # wall-clock says a first; the script says b
        log.append("b-before")
        sched.cross("b.step")

    with sched:
        _spawn(a, b)
    assert log == ["a-before", "b-before", "a-after"]
    assert sched.completed
    assert sched.trace_order() == ["b.step#1", "a.step#1"]


def test_occurrence_suffix_gates_the_kth_crossing():
    log = []
    sched = Schedule(order=["other.go", "loop.edge#3"], timeout_s=3.0)

    def looper():
        for i in range(3):
            sched.cross("loop.edge")  # #1 and #2 pass freely
            log.append(i)

    def other():
        time.sleep(0.05)
        log.append("other")
        sched.cross("other.go")

    with sched:
        _spawn(looper, other)
    assert log == [0, 1, "other", 2]


def test_unlisted_points_pass_freely_and_are_traced():
    sched = Schedule(order=[], timeout_s=1.0)
    with sched:
        sched.cross("free.one")
        sched.cross("free.one")
        sched.cross("free.two")
    assert sched.trace_order() == ["free.one#1", "free.one#2",
                                   "free.two#1"]


def test_gate_timeout_raises_with_diagnostic():
    sched = Schedule(order=["never.happens", "a.step"], timeout_s=0.3)
    with sched:
        with pytest.raises(ScheduleTimeout) as e:
            sched.cross("a.step")
    msg = str(e.value)
    assert "never.happens" in msg and "a.step" in msg


def test_parked_at_observes_gated_thread():
    sched = Schedule(order=["release", "gate.point"], timeout_s=3.0)

    def gated():
        sched.cross("gate.point")

    t = threading.Thread(target=gated)
    with sched:
        t.start()
        deadline = time.monotonic() + 2.0
        while not sched.parked_at("gate.point"):
            assert time.monotonic() < deadline
            time.sleep(0.005)
        sched.cross("release")
        t.join(3.0)
    assert not t.is_alive() and sched.completed


def test_install_routes_product_yield_points():
    """``sanitize_hooks.sched_point`` (the seam product code calls) is
    a no-op without a schedule and gates under one; exiting restores
    the previous hook."""
    sanitize_hooks.sched_point("no.schedule")  # must not raise
    sched = Schedule(order=[], timeout_s=1.0)
    with sched:
        sanitize_hooks.sched_point("seamed.point")
    assert sched.trace_order() == ["seamed.point#1"]
    assert sanitize_hooks._sched_point is None


def test_exit_releases_parked_threads():
    """Tearing the schedule down mid-park releases the thread instead
    of stranding it behind a gate nobody will open."""
    sched = Schedule(order=["never", "stuck.point"], timeout_s=30.0)

    def stuck():
        sched.cross("stuck.point")

    t = threading.Thread(target=stuck)
    with sched:
        t.start()
        deadline = time.monotonic() + 2.0
        while not sched.parked_at("stuck.point"):
            assert time.monotonic() < deadline
            time.sleep(0.005)
    t.join(2.0)
    assert not t.is_alive()


def test_seeded_schedule_records_replayable_trace():
    """A seeded run records crossings; replaying the filtered trace as
    a script reproduces the same crossing order deterministically."""
    order_seen = []

    def run(sched):
        def a():
            sched.cross("x.a")
            order_seen.append("a")

        def b():
            sched.cross("x.b")
            order_seen.append("b")

        _spawn(a, b)
        return False  # not hunting a race, just recording

    sched = Schedule(seed=7, pause_max_s=0.05)
    with sched:
        run(sched)
    trace = [k for k in sched.trace_order() if k.startswith("x.")]
    assert sorted(trace) == ["x.a#1", "x.b#1"]

    replayed = []
    replay = Schedule(order=trace, timeout_s=3.0)

    def ra():
        replay.cross("x.a")
        replayed.append("x.a#1")

    def rb():
        replay.cross("x.b")
        replayed.append("x.b#1")

    with replay:
        _spawn(ra, rb)
    assert replayed == trace
    assert replay.completed


def test_find_race_returns_none_when_no_race():
    assert find_race(lambda sched: False, seeds=range(3)) is None


def test_order_and_seed_are_mutually_exclusive():
    with pytest.raises(ValueError):
        Schedule(order=["a"], seed=1)
    with pytest.raises(ValueError):
        Schedule(order=["a", "a"])


def test_completed_stays_false_when_gate_never_crossed():
    """Tearing down a schedule must not forge completion: `completed`
    is the acceptance signal the race fixtures assert on, so a script
    that never played out has to read False after the with block."""
    sched = Schedule(order=["never.crossed"], timeout_s=0.5)
    with sched:
        sched.cross("unrelated.point")
    assert not sched.completed
    # A released gate passes threads through but still doesn't count.
    sched2 = Schedule(order=["other.first", "gate.point"], timeout_s=30.0)

    def gated():
        sched2.cross("gate.point")

    t = threading.Thread(target=gated)
    with sched2:
        t.start()
        deadline = time.monotonic() + 2.0
        while not sched2.parked_at("gate.point"):
            assert time.monotonic() < deadline
            time.sleep(0.005)
    t.join(2.0)
    assert not t.is_alive()
    assert not sched2.completed


# -- role-qualified entries, crash injection, diagnostics (raymc seams) ------


def test_role_qualified_entries_pin_threads_not_occurrences():
    """Two same-named crossings by different threads: @role entries
    order them by WHO crosses, which global occurrence keys cannot do
    when arrival order is the thing under test."""
    log = []
    sched = Schedule(order=["sym.point@second", "sym.point@first"],
                     timeout_s=3.0)

    def body(tag):
        def run():
            sched.cross("sym.point")
            log.append(tag)
        return run

    first = threading.Thread(target=body("first"), name="first")
    second = threading.Thread(target=body("second"), name="second")
    with sched:
        first.start()
        # `first` must park even though it arrives first (global occ 1
        # would have let it through) — its @role entry is second.
        deadline = time.monotonic() + 2.0
        while not sched.parked_at("sym.point"):
            assert time.monotonic() < deadline
            time.sleep(0.005)
        second.start()
        first.join(3.0)
        second.join(3.0)
    assert log == ["second", "first"]
    assert sched.completed


def test_role_qualified_occurrence_suffix():
    log = []
    sched = Schedule(order=["other.point", "loop.edge@worker#2"],
                     timeout_s=3.0)

    def worker():
        sched.cross("loop.edge")   # occ 1: unlisted → passes freely
        log.append(1)
        sched.cross("loop.edge")   # @worker#2 gates THIS crossing
        log.append(2)

    t = threading.Thread(target=worker, name="worker")
    with sched:
        t.start()
        deadline = time.monotonic() + 2.0
        while not sched.parked_at("loop.edge"):
            assert time.monotonic() < deadline
            time.sleep(0.005)
        assert log == [1], "worker should be parked at its 2nd crossing"
        sched.cross("other.point")
        t.join(3.0)
    assert log == [1, 2]
    assert sched.completed


def test_crash_at_raises_simulated_crash_after_gating():
    """crash_at kills the matching crossing AFTER it is recorded and
    its gate marked done — the raymc counterexample replay contract."""
    crashes = []

    sched = Schedule(order=["boom.point"], crash_at=["boom.point"],
                     timeout_s=3.0)

    def body():
        try:
            sanitize_hooks.sched_point("boom.point")
        except sanitize_hooks.SimulatedCrash as e:
            crashes.append(e.point)

    with sched:
        _spawn(body)
    assert crashes == ["boom.point"]
    assert sched.completed, "the crashed crossing still counts"
    assert [k for k, _ in sched.trace] == ["boom.point#1"]


def test_crash_at_fires_once_per_entry():
    crashes = []

    sched = Schedule(crash_at=["re.point"], timeout_s=3.0)

    def body():
        for _ in range(3):
            try:
                sanitize_hooks.sched_point("re.point")
            except sanitize_hooks.SimulatedCrash:
                crashes.append(1)

    with sched:
        _spawn(body)
    assert crashes == [1], "a crash entry is a single death, not a curse"


def test_crash_point_hook_is_gated_and_crashable():
    """Product crash_point() crossings route through the installed
    schedule exactly like sched_point() ones."""
    order = []

    sched = Schedule(order=["gate.open", "gcs.commit.before"],
                     crash_at=["gcs.commit.before"], timeout_s=3.0)

    def faulty():
        try:
            sanitize_hooks.crash_point("gcs.commit.before")
            order.append("survived")
        except sanitize_hooks.SimulatedCrash:
            order.append("crashed")

    t = threading.Thread(target=faulty, name="faulty")
    with sched:
        t.start()
        deadline = time.monotonic() + 2.0
        while not sched.parked_at("gcs.commit.before"):
            assert time.monotonic() < deadline
            time.sleep(0.005)
        sched.cross("gate.open")
        t.join(3.0)
    assert order == ["crashed"]
    assert sched.completed


def test_timeout_diagnostic_names_last_crossed_point():
    sched = Schedule(order=["a.step", "never.happens", "b.step"],
                     timeout_s=0.3)
    with sched:
        sched.cross("a.step")
        with pytest.raises(ScheduleTimeout) as e:
            sched.cross("b.step")
    msg = str(e.value)
    assert "last successfully crossed point" in msg
    assert "a.step#1" in msg, msg
    assert "never.happens" in msg


def test_timeout_diagnostic_when_nothing_crossed():
    sched = Schedule(order=["never.happens", "b.step"], timeout_s=0.2)
    with sched:
        with pytest.raises(ScheduleTimeout) as e:
            sched.cross("b.step")
    assert "no point was ever crossed" in str(e.value)


def test_on_cross_seam_observes_every_crossing():
    seen = []
    sched = Schedule(on_cross=lambda key, role: seen.append((key, role)))

    def body():
        sanitize_hooks.sched_point("x.one")
        sanitize_hooks.sched_point("x.one")

    t = threading.Thread(target=body, name="observer-target")
    with sched:
        t.start()
        t.join(3.0)
    assert seen == [("x.one#1", "observer-target"),
                    ("x.one#2", "observer-target")]


def test_crash_at_server_dispatch_tombstones_the_dedupe_claim():
    """A crash injected at the rpc.server.dispatch crossing itself
    (after the in-flight dedupe claim is taken) must tombstone the
    claim: the connection dies, and a retry under the same rid gets a
    SimulatedCrash failure reply promptly — never a hang on the
    stranded event, never a second execution."""
    from ray_tpu._private.rpc import (RemoteCallError, RpcClient,
                                      RpcServer)

    calls = []
    server = RpcServer({"apply": lambda **kw: calls.append(1)},
                       dedupe_methods=frozenset({"apply"}))
    sched = Schedule(crash_at=["rpc.server.dispatch"], timeout_s=3.0)
    try:
        with sched:
            client = RpcClient.dedicated(server.address)
            t0 = time.monotonic()
            try:
                client.call("apply")
                raise AssertionError(
                    "call succeeded through a simulated crash")
            except RemoteCallError as e:
                assert "SimulatedCrash" in str(e), e
            except (ConnectionError, OSError):
                pass  # retry raced the teardown window: also a death
            assert time.monotonic() - t0 < 3.0, "retry hung"
        assert calls == [], (
            "the crash fired BEFORE dispatch; the handler must not "
            "have run")
    finally:
        server.shutdown()
