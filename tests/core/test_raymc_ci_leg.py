"""Tier-1 bounded model-checking leg: the real protocol code proves
its invariants over EVERY bounded interleaving and crash placement, on
every CI run, inside a hard wall-clock budget.

What the leg pins (the ISSUE's acceptance criteria):

- ``python -m tools.raymc`` (the default scenario set: router-cap,
  group-commit durability, pipelined close) exits 0 with ZERO findings
  and writes the ``RAYMC_REPORT.json`` artifact at the repo root;
- the router-cap and crash-fault durability checks are EXHAUSTIVE at
  their small scope — not a sampled smoke test but a drained DFS: the
  report's ``exhausted`` flag is load-bearing;
- the decision-core scenarios (quota_admission, dep_sweep,
  actor_restart, lineage_reconstruction) run in rayspec CONFORMANCE
  mode: every quiescent state also cross-checks the live core against
  its executable sequential spec's reachable states — the
  ``conformance_checks`` counters prove the refinement pass really ran;
- the leg stays under its wall budget so it can live in tier-1
  forever (raised from 60s to 75s when conformance mode added ~25%
  for ~450k refinement checks per run, then to 90s when the
  seam-coverage audit added a per-crossing recording cost — the leg
  runs ~68s solo but shares the budget with full-suite load);
- raymc holds itself to the repo's own gates: its sources pass raylint
  (asserted in test_raylint.py's tier-1 sweep alongside ray_tpu and
  raysan), and its harness machinery runs clean under the raysan
  leak/ambient sanitizers (the ``mc_harness``-marked subset, via the
  real raysan CLI — tools checking tools).
"""

import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_LEG_BUDGET_S = 90.0
_ARTIFACT = os.path.join(REPO_ROOT, "RAYMC_REPORT.json")


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def test_raymc_leg_clean_exhaustive_and_bounded():
    t0 = time.monotonic()
    out = subprocess.run(
        [sys.executable, "-m", "tools.raymc",
         "--report", "json", "--report-file", _ARTIFACT],
        cwd=REPO_ROOT, env=_env(), capture_output=True, text=True,
        timeout=_LEG_BUDGET_S + 60)
    wall = time.monotonic() - t0
    assert out.returncode == 0, (
        f"raymc leg failed (rc={out.returncode}):\n"
        f"{out.stdout[-4000:]}\n{out.stderr[-2000:]}")
    assert wall < _LEG_BUDGET_S, (
        f"raymc leg took {wall:.1f}s — over the {_LEG_BUDGET_S:.0f}s "
        f"budget; shrink scenario scopes before shrinking coverage")

    with open(_ARTIFACT, "r", encoding="utf-8") as f:
        report = json.load(f)
    assert report["pass"] is True
    by_name = {s["scenario"]: s for s in report["scenarios"]}
    assert set(by_name) == {"router_cap", "gcs_durability",
                            "pipelined_close", "spill_race",
                            "lineage_reconstruction", "actor_restart",
                            "head_crash_recovery", "quota_admission",
                            "dep_sweep", "replica_direct",
                            "kv_cache_reuse", "cross_shard"}
    for name, scenario in by_name.items():
        assert scenario["findings"] == [], (
            f"{name} found protocol violations in REAL code:\n"
            + json.dumps(scenario["findings"], indent=2))
        assert scenario["exhausted"] is True, (
            f"{name} did not drain its bounded schedule space "
            f"(executions={scenario['executions']}, "
            f"truncated={scenario['truncated']}, "
            f"divergences={scenario['divergences']}) — the tier-1 "
            f"claim is EVERY bounded interleaving, not a sample")
    # The crash-fault property really explored crash placements: the
    # durability scenario's schedule count must exceed the fault-free
    # interleavings alone (26 at this scope without crash branching).
    assert by_name["gcs_durability"]["executions"] >= 50, by_name
    assert by_name["head_crash_recovery"]["executions"] >= 50, by_name
    # The actor replay-or-reject space is the largest in the leg: a
    # shrunk count means the scenario lost its death placements.
    assert by_name["actor_restart"]["executions"] >= 5000, by_name
    # Tenancy admission: the grant/release race + WFQ put/pop space
    # drained — a shrunk count means the racing submitters (or the
    # queue race) fell out of the scenario.
    assert by_name["quota_admission"]["executions"] >= 5000, by_name
    # Dep-park exactly-once handoff (ROADMAP FT gap d): the two-ready-
    # vs-sweep space drained — a shrunk count means the multi-dep item
    # (or the sweeper) fell out of the scenario.
    assert by_name["dep_sweep"]["executions"] >= 1000, by_name
    # Serve replica-direct: the two-dispatcher-vs-removal space
    # drained — a shrunk count means a dispatcher (or the updater)
    # fell out of the scenario and the no-stale-dispatch property is
    # being proven over less than it claims.
    assert by_name["replica_direct"]["executions"] >= 1000, by_name
    # LLM prefix/KV cache: the lookup-vs-admit-vs-evict space drained
    # — a shrunk count means the pin-to-read window (or an action)
    # fell out and the no-stale-hit property is proven over less than
    # it claims.
    assert by_name["kv_cache_reuse"]["executions"] >= 500, by_name
    # Conformance mode really ran: each decision-core scenario
    # cross-checked its live core against the rayspec sequential spec
    # at quiescent states (a zero here means the refinement pass
    # silently fell out — the scenario would still 'pass' but prove
    # strictly less).
    for name in ("quota_admission", "dep_sweep", "actor_restart",
                 "lineage_reconstruction", "kv_cache_reuse"):
        assert by_name[name]["conformance_checks"] >= \
            by_name[name]["executions"], (
                name, by_name[name]["conformance_checks"])
    # Seam-coverage audit folded into the artifact: the default set
    # must keep crossing a substantial majority of the registered
    # sched/crash catalog. The audit is advisory per-point (a new
    # point starts uncovered until a scenario reaches it), but a
    # collapse in the crossed count means scenarios silently stopped
    # exercising seams they used to schedule around.
    cov = report["seam_coverage"]
    assert cov["catalog"] >= 70
    assert len(cov["crossed"]) >= 50, cov["uncovered"]
    assert not (set(cov["crossed"]) & set(cov["uncovered"]))


def test_raymc_harness_clean_under_raysan_sanitizers(tmp_path):
    """raymc passes the raysan tier-1 gate: its explorer/minimizer/CLI
    machinery leaks no threads/fds/ambient state, checked by the real
    raysan CLI over the mc_harness-marked tests."""
    report_file = tmp_path / "raysan_raymc.json"
    out = subprocess.run(
        [sys.executable, "-m", "tools.raysan",
         "tests/core/test_raymc.py",
         "--sanitize", "leaks,ambient",
         "--report-file", str(report_file),
         "--pytest-args", "-q -m mc_harness"],
        cwd=REPO_ROOT, env=_env(), capture_output=True, text=True,
        timeout=120)
    assert out.returncode == 0, (
        f"raysan over the raymc harness failed "
        f"(rc={out.returncode}):\n{out.stdout[-4000:]}\n"
        f"{out.stderr[-2000:]}")
    report = json.loads(report_file.read_text())
    assert report["findings"] == [], report["findings"]
    assert report["tests_checked"] >= 9, (
        f"mc_harness subset shrank to {report['tests_checked']} tests")
