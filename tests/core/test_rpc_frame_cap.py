"""rpc framing hardening, regression-tested at the raw-socket level:
the pre-allocation cap, typed skew rejection at server dispatch, and
the frame-aligned keep-the-connection recovery path.

Everything here drives a live ``RpcServer`` with hand-built byte
streams — no client library in the request path — because the defects
this guards against (allocation bombs, connection-killing on malformed
frames, silent envelope confusion) live below the client abstraction.
"""

import os
import socket
import struct
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import pytest  # noqa: E402

from ray_tpu._private import rpc, wire  # noqa: E402
from ray_tpu._private.config import ray_config  # noqa: E402
from ray_tpu._private.rpc import (FrameTooLarge, RpcClient,  # noqa: E402
                                  RpcServer, recv_msg, send_msg)

_LEN = struct.Struct("!I")


@pytest.fixture()
def server():
    srv = RpcServer({"echo": lambda **kw: kw})
    try:
        yield srv
    finally:
        srv.shutdown()


def _frame(payload: bytes) -> bytes:
    return _LEN.pack(len(payload)) + payload


def _request(rid="r1", method="echo", **kwargs) -> bytes:
    return _frame(wire.encode(
        wire.Request(id=rid, method=method, kwargs=kwargs)))


def _reply_of(sock) -> wire.Reply:
    msg = recv_msg(sock)
    assert isinstance(msg, wire.Reply)
    return msg


# -- the pre-allocation cap -------------------------------------------------


def test_recv_msg_rejects_oversized_header_before_body():
    class OneShot:
        def __init__(self, data):
            self.data = data
            self.recv_calls = 0

        def recv(self, n):
            self.recv_calls += 1
            chunk, self.data = self.data[:n], self.data[n:]
            return chunk

    sock = OneShot(_LEN.pack(0x7FFFFF00) + b"x" * 64)
    with pytest.raises(FrameTooLarge, match="rpc_max_frame_bytes"):
        recv_msg(sock)
    # The reject happened off the 4-byte header alone — the claimed
    # 2GiB body was never pulled from the socket.
    assert sock.recv_calls <= 2


def test_frame_cap_is_a_config_knob(monkeypatch):
    monkeypatch.setattr(ray_config, "rpc_max_frame_bytes", 64)

    class Buf:
        def __init__(self, data):
            self.data = data

        def recv(self, n):
            chunk, self.data = self.data[:n], self.data[n:]
            return chunk

    payload = wire.encode(b"x" * 256)
    with pytest.raises(FrameTooLarge):
        recv_msg(Buf(_frame(payload)))
    small = wire.encode(b"x" * 8)
    assert recv_msg(Buf(_frame(small))) == b"x" * 8


def test_server_replies_frame_too_large_then_drops(server):
    with socket.create_connection(server.address) as sock:
        sock.sendall(_LEN.pack(1 << 31))
        reply = _reply_of(sock)
        assert not reply.ok and "rpc_max_frame_bytes" in reply.error
        # After an oversized header the stream cannot resync (the
        # server never read the claimed body) — connection closes.
        sock.settimeout(5.0)
        assert sock.recv(4) == b""


# -- typed skew rejection at dispatch, frame-aligned recovery ---------------


def test_malformed_frame_gets_error_reply_and_connection_survives(
        server):
    with socket.create_connection(server.address) as sock:
        sock.sendall(_frame(b"\xff\xfe garbage"))
        reply = _reply_of(sock)
        assert not reply.ok and "wire:" in reply.error
        # Framing is intact (the bad bytes were length-delimited), so
        # the SAME connection serves the next request.
        sock.sendall(_request(x=1))
        reply = _reply_of(sock)
        assert reply.ok and reply.result == {"x": 1}


def test_future_version_request_rejected_typed(server):
    raw = bytearray(wire.encode(
        wire.Request(id="r9", method="echo", kwargs={})))
    name_len = _LEN.unpack_from(raw, 1)[0]
    struct.pack_into("!H", raw, 5 + name_len, 99)   # version u16
    with socket.create_connection(server.address) as sock:
        sock.sendall(_frame(bytes(raw)))
        reply = _reply_of(sock)
        assert not reply.ok
        assert "newer than known" in reply.error
        sock.sendall(_request(x=2))
        assert _reply_of(sock).result == {"x": 2}


def test_non_request_envelope_rejected_typed(server):
    # A well-formed frame of the wrong TYPE (a skewed peer speaking a
    # different protocol role) gets a typed rejection naming the type,
    # and the connection keeps serving.
    with socket.create_connection(server.address) as sock:
        sock.sendall(_frame(wire.encode({"method": "echo"})))
        reply = _reply_of(sock)
        assert not reply.ok
        assert "expected rpc.Request envelope, got dict" in reply.error
        sock.sendall(_request(x=3))
        assert _reply_of(sock).result == {"x": 3}


def test_normal_client_unaffected_by_hardening(server):
    client = RpcClient(server.address)
    assert client.call("echo", a=1, b="two") == {"a": 1, "b": "two"}


def test_client_closes_on_malformed_reply(server, monkeypatch):
    # The client side of the same contract: a garbage reply must
    # surface as RemoteCallError, not UnicodeDecodeError, and must
    # tear the connection down (the stream is untrustworthy).
    client = RpcClient(server.address)
    assert client.call("echo", x=1) == {"x": 1}

    def bad_recv(sock):
        raise wire.WireError("malformed reply frame")

    monkeypatch.setattr(rpc, "recv_msg", bad_recv)
    with pytest.raises(rpc.RemoteCallError, match="malformed reply"):
        client.call("echo", x=2)
    assert client._sock is None
