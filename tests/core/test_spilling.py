"""Disk spilling under memory pressure: spill cold objects, transparent
restore on get, file deletion on ref release.

Reference: `src/ray/raylet/local_object_manager.h:41` (SpillObjects),
`python/ray/_private/external_storage.py:72/:246`.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.config import ray_config

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture
def small_budget(monkeypatch):
    # 4MB budget, spill above 50%, spill anything >= 256KB.
    monkeypatch.setattr(ray_config, "object_store_memory_bytes", 4 * 2**20)
    monkeypatch.setattr(ray_config, "object_spilling_threshold", 0.5)
    monkeypatch.setattr(ray_config, "min_spilling_size_bytes", 256 * 1024)
    yield


@pytest.fixture
def ray_local(small_budget):
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    yield ray_tpu._private_worker()
    ray_tpu.shutdown()


def _private_worker():
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker()


ray_tpu._private_worker = _private_worker


def test_put_beyond_budget_spills_and_restores(ray_local):
    w = ray_local
    manager = w.memory_store.spill_manager
    arrays = [np.full((256, 1024), i, dtype=np.float32) for i in range(8)]
    refs = [ray_tpu.put(a) for a in arrays]  # 8 x 1MB > 4MB budget

    stats = manager.stats()
    assert stats["num_spilled"] > 0, stats
    assert stats["in_memory_bytes"] <= manager.budget
    spill_dir = manager.storage.directory
    assert len(os.listdir(spill_dir)) == stats["num_spilled"]

    # Every value — spilled or resident — reads back intact.
    for i, ref in enumerate(refs):
        out = ray_tpu.get(ref)
        assert out.shape == (256, 1024) and float(out[0, 0]) == float(i)
    assert manager.stats()["num_restored"] > 0


def test_release_deletes_spill_files(ray_local):
    w = ray_local
    manager = w.memory_store.spill_manager
    refs = [ray_tpu.put(np.ones((256, 1024), np.float32) * i)
            for i in range(8)]
    assert manager.stats()["num_spilled"] > 0
    spill_dir = manager.storage.directory
    assert os.listdir(spill_dir)
    del refs
    import gc

    gc.collect()
    assert os.listdir(spill_dir) == []


def test_spilled_task_output_roundtrip(ray_local):
    import time

    @ray_tpu.remote
    def big(i):
        return np.full((256, 1024), i, dtype=np.float32)

    refs = [big.remote(i) for i in range(8)]
    outs = ray_tpu.get(refs)
    for i, out in enumerate(outs):
        assert float(out[0, 0]) == float(i)
    # get() returns when values resolve; the last put's spill sweep may
    # still be running on its executor thread — bounded wait, not race.
    manager = ray_local.memory_store.spill_manager
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and \
            manager.stats()["num_spilled"] == 0:
        time.sleep(0.01)
    assert manager.stats()["num_spilled"] > 0


def test_small_objects_never_spill(ray_local):
    refs = [ray_tpu.put(np.ones(16, np.float32)) for _ in range(100)]
    assert ray_local.memory_store.spill_manager.stats()["num_spilled"] == 0
    assert all(r is not None for r in ray_tpu.get(refs))


def test_restored_object_respills_without_rewrite(ray_local):
    manager = ray_local.memory_store.spill_manager
    refs = [ray_tpu.put(np.full((256, 1024), i, np.float32))
            for i in range(8)]
    first_spills = manager.stats()["num_spilled"]
    assert first_spills > 0
    # Touch everything (restores spilled values back into memory)...
    for ref in refs:
        ray_tpu.get(ref)
    # ...then push new data: restored copies may be dropped again, but
    # their bytes are already on disk — num_spilled (fresh writes) should
    # not grow by re-serializing them.
    extra = [ray_tpu.put(np.full((256, 1024), 100 + i, np.float32))
             for i in range(4)]
    assert extra
    stats = manager.stats()
    assert stats["num_spilled"] <= first_spills + 4
