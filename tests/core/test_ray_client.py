"""Ray-client mode: a separate client process drives the cluster
through `init(address=...)` (reference `util/client/` ray:// mode)."""

import subprocess
import sys
import textwrap

import pytest

import ray_tpu

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


CLIENT_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, {repo!r})
    import ray_tpu

    ray_tpu.init(address=sys.argv[1])

    # tasks
    @ray_tpu.remote
    def square(x):
        return x * x

    assert ray_tpu.get([square.remote(i) for i in range(5)]) == \\
        [0, 1, 4, 9, 16]

    # put/get + nested ref through a task
    ref = ray_tpu.put({{"k": 41}})

    @ray_tpu.remote
    def bump(d):
        d["k"] += 1
        return d

    assert ray_tpu.get(bump.remote(ref))["k"] == 42

    # wait
    refs = [square.remote(i) for i in range(4)]
    ready, rest = ray_tpu.wait(refs, num_returns=2, timeout=30)
    assert len(ready) == 2 and len(rest) == 2

    # actors + named actors
    @ray_tpu.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start
        def inc(self):
            self.n += 1
            return self.n

    c = Counter.options(name="client_counter").remote(start=10)
    assert ray_tpu.get(c.inc.remote()) == 11
    again = ray_tpu.get_actor("client_counter")
    assert ray_tpu.get(again.inc.remote()) == 12

    # exceptions propagate with their original type
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    try:
        ray_tpu.get(boom.remote())
        raise SystemExit("expected ValueError")
    except ValueError as e:
        assert "kaboom" in str(e)

    # num_returns="dynamic" generator tasks (ADVICE r3: client mode
    # raised TypeError on range('dynamic'))
    @ray_tpu.remote(num_returns="dynamic")
    def gen(n):
        for i in range(n):
            yield i * i

    g = ray_tpu.get(gen.remote(4))
    item_refs = list(g)
    del g  # dropping the generator must not drop the yielded objects
    assert ray_tpu.get(item_refs) == [0, 1, 4, 9]

    ray_tpu.kill(c)
    print("CLIENT OK")
""")


def test_task_error_pickle_roundtrip():
    """TaskError (and its dynamic dual-type wrapper) must survive
    pickling with cause/desc/traceback intact — the client ships them
    across processes (previously both reconstructed from the message
    string and blew up on attribute access)."""
    import pickle

    from ray_tpu.exceptions import TaskError

    te = TaskError(ValueError("boom"), "f()")
    te2 = pickle.loads(pickle.dumps(te))
    assert isinstance(te2, TaskError)
    assert isinstance(te2.cause, ValueError)
    assert te2.task_desc == "f()"
    assert "boom" in te2.remote_traceback

    wrapped = te.as_instanceof_cause()
    assert isinstance(wrapped, ValueError)
    w2 = pickle.loads(pickle.dumps(wrapped))
    assert isinstance(w2, ValueError) and isinstance(w2, TaskError)
    assert "boom" in str(w2)


def test_client_process_drives_server():
    server = ray_tpu.enable_client_server(host="127.0.0.1", port=0)
    try:
        script = CLIENT_SCRIPT.format(repo=".")
        out = subprocess.run(
            [sys.executable, "-c", script,
             f"{server.address[0]}:{server.address[1]}"],
            capture_output=True, text=True, timeout=180)
        assert out.returncode == 0, (out.stdout, out.stderr)
        assert "CLIENT OK" in out.stdout
    finally:
        server.shutdown()


def test_client_get_outlives_poll_slice():
    """A get on a task slower than the long-poll slice (and a wait with
    a sub-slice timeout) must behave correctly — the blocking RPC is
    sliced below the socket timeout."""
    from ray_tpu._private import ray_client as rc

    server = ray_tpu.enable_client_server(host="127.0.0.1", port=0)
    old_slice = rc.ClientWorker._POLL_SLICE_S
    rc.ClientWorker._POLL_SLICE_S = 1.0  # make slicing observable fast
    try:
        script = textwrap.dedent("""
            import os, sys, time
            os.environ["JAX_PLATFORMS"] = "cpu"
            sys.path.insert(0, ".")
            import ray_tpu
            from ray_tpu._private import ray_client as rc
            rc.ClientWorker._POLL_SLICE_S = 1.0

            ray_tpu.init(address=sys.argv[1])

            @ray_tpu.remote
            def slow():
                time.sleep(3.5)
                return "done"

            ref = slow.remote()
            # wait with a short timeout reports not-ready, not an error
            ready, rest = ray_tpu.wait([ref], num_returns=1, timeout=0.5)
            assert not ready and len(rest) == 1
            # a multi-slice blocking get succeeds
            assert ray_tpu.get(ref, timeout=60) == "done"
            # and a too-short get raises GetTimeoutError
            ref2 = slow.remote()
            from ray_tpu.exceptions import GetTimeoutError
            try:
                ray_tpu.get(ref2, timeout=0.5)
                raise SystemExit("expected timeout")
            except GetTimeoutError:
                pass
            ray_tpu.get(ref2, timeout=60)
            print("SLOW OK")
        """)
        out = subprocess.run(
            [sys.executable, "-c", script,
             f"{server.address[0]}:{server.address[1]}"],
            capture_output=True, text=True, timeout=180)
        assert out.returncode == 0, (out.stdout, out.stderr)
        assert "SLOW OK" in out.stdout
    finally:
        rc.ClientWorker._POLL_SLICE_S = old_slice
        server.shutdown()


def test_client_frees_release_server_pins():
    server = ray_tpu.enable_client_server(host="127.0.0.1", port=0)
    try:
        script = textwrap.dedent("""
            import os, sys
            os.environ["JAX_PLATFORMS"] = "cpu"
            sys.path.insert(0, ".")
            import gc
            import ray_tpu

            ray_tpu.init(address=sys.argv[1])
            ref = ray_tpu.put(list(range(1000)))
            assert ray_tpu.get(ref)[-1] == 999
            del ref
            gc.collect()
            print("FREED")
        """)
        out = subprocess.run(
            [sys.executable, "-c", script,
             f"{server.address[0]}:{server.address[1]}"],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, (out.stdout, out.stderr)
        assert "FREED" in out.stdout
        assert not server._pins, list(server._pins)
    finally:
        server.shutdown()


def test_client_drives_multinode_cluster():
    """Thin client → client server in the CLUSTER driver → tasks spill
    to worker nodes (full composition)."""
    import textwrap as tw

    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=4)
        server = ray_tpu.enable_client_server(host="127.0.0.1", port=0)
        script = tw.dedent("""
            import os, sys
            os.environ["JAX_PLATFORMS"] = "cpu"
            sys.path.insert(0, ".")
            import ray_tpu

            ray_tpu.init(address=sys.argv[1])

            @ray_tpu.remote(num_cpus=2)
            def where():
                import os, time
                time.sleep(0.5)
                return os.getpid()

            # 2 concurrent 2-CPU tasks > head's 1 CPU: neither fits the
            # head, so both must run in the worker NODE's process — not
            # in the driver/server process (pid passed as argv[2]).
            driver_pid = int(sys.argv[2])
            pids = set(ray_tpu.get([where.remote() for _ in range(2)]))
            assert driver_pid not in pids, (driver_pid, pids)
            print("CLUSTER CLIENT OK", pids)
        """)
        import os as _os

        out = subprocess.run(
            [sys.executable, "-c", script,
             f"{server.address[0]}:{server.address[1]}",
             str(_os.getpid())],
            capture_output=True, text=True, timeout=180)
        assert out.returncode == 0, (out.stdout, out.stderr)
        assert "CLUSTER CLIENT OK" in out.stdout
        server.shutdown()
    finally:
        cluster.shutdown()
        ray_tpu.shutdown()
