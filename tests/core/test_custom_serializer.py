"""Custom serializer registry (reference ray.util.register_serializer)."""

import pickle
import threading

import pytest

import ray_tpu
from ray_tpu.util import deregister_serializer, register_serializer


class Handle:
    """Holds an unpicklable member (a lock)."""

    def __init__(self, x):
        self.x = x
        self.lock = threading.Lock()


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    yield
    deregister_serializer(Handle)
    ray_tpu.shutdown()


def test_register_serializer_roundtrip():
    with pytest.raises(TypeError):
        pickle.dumps(Handle(1))

    register_serializer(Handle, serializer=lambda h: h.x,
                        deserializer=lambda x: Handle(x))

    # Crosses every wire path: task arg, task return, put/get.
    @ray_tpu.remote
    def bump(h):
        return Handle(h.x + 1)

    out = ray_tpu.get(bump.remote(Handle(41)))
    assert isinstance(out, Handle) and out.x == 42
    assert ray_tpu.get(ray_tpu.put(Handle(7))).x == 7

    deregister_serializer(Handle)
    with pytest.raises(TypeError):
        pickle.dumps(Handle(1))


def test_register_serializer_validates():
    with pytest.raises(TypeError, match="must be a class"):
        register_serializer(42, serializer=str, deserializer=int)
