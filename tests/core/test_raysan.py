"""raysan framework: findings, policy, sanitizer units, CLI contract.

The per-sanitizer units drive snapshot→mutate→diff directly (no inner
pytest), so they pin the detection semantics cheaply; the CLI test runs
``python -m tools.raysan`` end-to-end on tiny out-of-tree fixtures to
pin the exit-code/report contract the CI leg relies on.
"""

import os
import subprocess
import sys
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.raysan.core import (  # noqa: E402
    Allow,
    Finding,
    Session,
    apply_policy,
    make_sanitizers,
)


# -- core policy/report ------------------------------------------------------


def test_apply_policy_suppression_requires_justification():
    findings = [
        Finding("leaks", "t::a", "thread leaked: 'x'"),
        Finding("leaks", "t::b", "fd leaked: socket fd=3"),
    ]
    out = apply_policy(findings, [
        Allow("leaks", r"thread leaked", reason="deliberate fixture"),
        Allow("leaks", r"fd leaked"),  # no reason: must NOT suppress
    ])
    by_msg = {f.message: f for f in out if f.sanitizer == "leaks"}
    assert by_msg["thread leaked: 'x'"].suppressed
    assert by_msg["thread leaked: 'x'"].justification == \
        "deliberate fixture"
    assert not by_msg["fd leaked: socket fd=3"].suppressed
    meta = [f for f in out if f.sanitizer == "policy"]
    assert len(meta) == 1 and "no justification" in meta[0].message


def test_allow_scoped_to_sanitizer():
    f = Finding("ambient", "t", "thread leaked: 'x'")
    assert not Allow("leaks", "thread leaked", reason="r").matches(f)
    assert Allow("ambient", "thread leaked", reason="r").matches(f)


def test_make_sanitizers_unknown_name():
    try:
        make_sanitizers(["leaks", "valgrind"])
    except KeyError as e:
        assert "valgrind" in e.args[0] and "leaks" in e.args[0]
    else:
        raise AssertionError("unknown sanitizer accepted")


def test_session_report_json_contract():
    import json

    session = Session(make_sanitizers(["leaks"]))
    session.before_test("t::one")
    session.after_test("t::one")
    report = session.report()
    data = json.loads(report.to_json())
    assert data["sanitizers"] == ["leaks"]
    assert data["tests_checked"] == 1
    assert data["findings"] == [] and data["suppressed"] == []


# -- leak sanitizer ----------------------------------------------------------


def test_leak_sanitizer_flags_thread_and_fd_and_clears():
    import socket

    san = make_sanitizers(["leaks"])[0]
    san.grace_s = 0.2
    san.before_test("t::leaky")
    stop = threading.Event()
    t = threading.Thread(target=stop.wait, daemon=True,
                         name="leak-fixture")
    t.start()
    sock = socket.socket()
    try:
        findings = san.after_test("t::leaky")
        msgs = [f.message for f in findings]
        assert any("thread leaked: 'leak-fixture'" in m for m in msgs)
        assert any("fd leaked" in m and "socket" in m for m in msgs)
    finally:
        stop.set()
        sock.close()
        t.join(2.0)
    # Same census with the resources released: clean.
    san.before_test("t::clean")
    assert san.after_test("t::clean") == []


def test_leak_sanitizer_thread_grace_tolerates_retiring_threads():
    """A thread observing its shutdown flag within the grace window is
    NOT a leak — teardown latency must not read as a finding."""
    san = make_sanitizers(["leaks"])[0]
    san.grace_s = 1.0
    san.before_test("t::grace")
    t = threading.Thread(target=lambda: time.sleep(0.15), daemon=True)
    t.start()
    assert san.after_test("t::grace") == []


def test_leak_sanitizer_memory_store_growth(ray_start_regular):
    import ray_tpu
    from ray_tpu._private.worker import global_worker

    san = make_sanitizers(["leaks"])[0]
    san.grace_s = 0.2
    san.before_test("t::store")
    # Pin an entry so teardown GC cannot collect it (a module-global
    # ref is exactly the leak shape this guards against).
    leak_holder.append(ray_tpu.put(list(range(256))))
    findings = san.after_test("t::store")
    assert any("memory_store leaked" in f.message for f in findings), \
        [f.message for f in findings]
    leak_holder.clear()
    assert global_worker() is not None


leak_holder: list = []


# -- ambient sanitizer -------------------------------------------------------


def test_ambient_sanitizer_serve_records_self_heal():
    from ray_tpu._private import perf_stats

    san = make_sanitizers(["ambient"])[0]
    san.start_session()
    try:
        san.before_test("t::records")
        stat = perf_stats.dist(
            "serve_request_seconds",
            tags={"route": "/raysan-unit", "status": "503"},
            bounds=perf_stats.SERVE_LATENCY_BOUNDS)
        before_total = stat.total
        stat.record(0.001)
        findings = san.after_test("t::records")
        assert any("serve_request_seconds records mutated" in f.message
                   for f in findings)
        # Self-heal: the records were rolled back, so the next test
        # starts clean instead of cascading.
        assert stat.total == before_total
        san.before_test("t::after-heal")
        assert san.after_test("t::after-heal") == []
    finally:
        san.stop_session()


def test_ambient_sanitizer_tracker_and_lag_state():
    from ray_tpu._private import health

    san = make_sanitizers(["ambient"])[0]
    san.start_session()
    try:
        san.before_test("t::tracker")
        health.tracker.sample()
        health.note_loop_lag("raysan-unit-component", 0.5)
        findings = san.after_test("t::tracker")
        assert any("health tracker/loop-lag state mutated" in f.message
                   for f in findings)
        assert "raysan-unit-component" not in health.recent_loop_lag()
    finally:
        san.stop_session()


def test_ambient_sanitizer_thread_local_residue():
    from ray_tpu._private.task_spec import set_ambient_job_id

    san = make_sanitizers(["ambient"])[0]
    san.start_session()
    try:
        san.before_test("t::tag")
        prev = set_ambient_job_id("raysan-unit-tenant")
        findings = san.after_test("t::tag")
        set_ambient_job_id(prev)
        assert any("ambient job_id 'raysan-unit-tenant'" in f.message
                   for f in findings)
        # A proper token-restore pattern is clean.
        san.before_test("t::tag2")
        tok = set_ambient_job_id("raysan-unit-tenant2")
        set_ambient_job_id(tok)
        assert san.after_test("t::tag2") == []
    finally:
        san.stop_session()


# -- loop sanitizer ----------------------------------------------------------


def test_loop_sanitizer_flags_blocking_callback_with_stack():
    import asyncio

    from tools.raysan.loop_blocking import LoopBlockingSanitizer

    san = LoopBlockingSanitizer(threshold_ms=60.0)
    san.start_session()
    try:
        san.before_test("t::loop")

        def stall():
            time.sleep(0.2)

        async def main():
            asyncio.get_event_loop().call_soon(stall)
            await asyncio.sleep(0.35)

        asyncio.run(main())
        findings = san.after_test("t::loop")
        assert len(findings) == 1
        assert "event loop blocked" in findings[0].message
        assert "stall" in findings[0].message
        # The watchdog sampled the loop thread MID-stall: the offending
        # synchronous frame is in the detail.
        assert "time.sleep(0.2)" in findings[0].detail

        # Clean async code: no findings.
        san.before_test("t::loop2")
        asyncio.run(asyncio.sleep(0.01))
        assert san.after_test("t::loop2") == []
    finally:
        san.stop_session()


# -- lock witness edge semantics --------------------------------------------


def test_lock_witness_reports_cycle_once():
    from tools.raysan.lock_witness import LockOrderSanitizer

    src = ("import threading\n"
           "la = threading.Lock()\n"
           "lb = threading.Lock()\n"
           "def ab():\n    with la:\n        with lb:\n            pass\n"
           "def ba():\n    with lb:\n        with la:\n            pass\n")
    san = LockOrderSanitizer()
    san.start_session()
    try:
        san.before_test("t::first")
        ns = {}
        exec(compile(src, "/tmp/raysan_once_fixture.py", "exec"), ns)
        ns["ab"]()
        ns["ba"]()
        first = san.after_test("t::first")
        assert len(first) == 1 \
            and "lock-order cycle" in first[0].message
        # The cycle's edges were retired with the finding: later tests
        # are not re-failed for the same inversion.
        san.before_test("t::second")
        assert san.after_test("t::second") == []
    finally:
        san.stop_session()


def test_lock_witness_condition_aliases_to_its_lock():
    """``threading.Condition(existing_lock)`` must share the lock's
    identity (raylint R2's aliasing): waiting on your own condition
    while holding only its lock is the normal protocol, not a cycle."""
    from tools.raysan.lock_witness import (
        LockOrderSanitizer,
        witnessed_edges,
    )

    san = LockOrderSanitizer()
    san.start_session()
    try:
        san.before_test("t::cond")
        src = ("import threading\n"
               "lk = threading.Lock()\n"
               "cv = threading.Condition(lk)\n"
               "def use():\n"
               "    with cv:\n"
               "        cv.notify_all()\n"
               "    with lk:\n"
               "        pass\n")
        ns = {}
        exec(compile(src, "/tmp/raysan_cond_fixture.py", "exec"), ns)
        ns["use"]()
        assert san.after_test("t::cond") == []
        # No self-edges between the condition and its own lock.
        assert all(a != b for a, b in witnessed_edges())
    finally:
        san.stop_session()


# -- CLI contract ------------------------------------------------------------


def _run_cli(args, cwd):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(
        [sys.executable, "-m", "tools.raysan", *args],
        cwd=cwd, env=env, capture_output=True, text=True, timeout=120)


def test_cli_exit_codes_and_json_report(tmp_path):
    import json

    clean = tmp_path / "test_cli_clean.py"
    clean.write_text("def test_ok():\n    assert True\n")
    leaky = tmp_path / "test_cli_leaky.py"
    leaky.write_text(
        "import threading\n"
        "def test_leak():\n"
        "    e = threading.Event()\n"
        "    t = threading.Thread(target=e.wait, daemon=True)\n"
        "    t.start()\n"
        "    globals()['_keep'] = (t, e)\n")

    out = _run_cli([str(clean), "--sanitize", "leaks",
                    "--report", "json"], cwd=REPO_ROOT)
    assert out.returncode == 0, out.stdout + out.stderr
    report = json.loads(out.stdout[out.stdout.index("{"):])
    assert report["findings"] == [] and report["tests_checked"] == 1

    report_file = tmp_path / "report.json"
    out = _run_cli([str(leaky), "--sanitize", "leaks",
                    "--report", "json",
                    "--report-file", str(report_file)], cwd=REPO_ROOT)
    assert out.returncode == 1, out.stdout + out.stderr
    saved = json.loads(report_file.read_text())
    assert any("thread leaked" in f["message"]
               for f in saved["findings"])

    out = _run_cli(["--sanitize", "tsan"], cwd=REPO_ROOT)
    assert out.returncode == 2
    out = _run_cli([str(tmp_path / "missing.py")], cwd=REPO_ROOT)
    assert out.returncode == 2


def test_ambient_sanitizer_flags_in_place_lag_value_mutation():
    """Key-set comparison would miss an existing component's lag being
    overwritten; the sanitizer must diff values, not just keys."""
    from ray_tpu._private import health

    health.note_loop_lag("raysan-mut-component", 0.001)
    san = make_sanitizers(["ambient"])[0]
    san.start_session()
    try:
        san.before_test("t::mutate")
        health.note_loop_lag("raysan-mut-component", 5.0)
        findings = san.after_test("t::mutate")
        assert any("health tracker/loop-lag state mutated" in f.message
                   for f in findings)
        # Self-heal restored the original sample.
        assert health.recent_loop_lag()["raysan-mut-component"] == 0.001
    finally:
        san.stop_session()
        health.remove_loop_lag_component("raysan-mut-component")


def test_session_reports_bad_allow_once_not_per_test():
    """One reason-less session-level Allow is one authorship error:
    it must fail once, not cascade a policy finding onto every test
    in the run (the R0 analog reports a bare disable once)."""
    session = Session(make_sanitizers(["leaks"]),
                      extra_allows=[Allow("leaks", "whatever")])
    session.before_test("t::one")
    first = session.after_test("t::one")
    assert [f.sanitizer for f in first] == ["policy"]
    session.before_test("t::two")
    assert session.after_test("t::two") == []
