"""Canonical runtime metrics exported alongside user metrics."""

import pytest

import ray_tpu
from ray_tpu.util.metrics import export_prometheus


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def test_runtime_metrics_exported():
    @ray_tpu.remote
    def f(x):
        return x + 1

    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    a = A.remote()
    ray_tpu.get([f.remote(i) for i in range(5)] + [a.ping.remote()])
    ray_tpu.put(list(range(100)))

    text = export_prometheus()
    assert 'ray_tpu_tasks{state="FINISHED"}' in text
    assert "ray_tpu_actors" in text
    assert "ray_tpu_object_store_objects" in text
    assert 'ray_tpu_resources_total{resource="CPU"} 2' in text
    # Prometheus exposition shape intact for the gauges.
    assert "# TYPE ray_tpu_tasks gauge" in text
