"""Regression tests for bugs found in review/verification of the core runtime."""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError


def test_get_timeout_is_total_deadline(ray_start_regular):
    @ray_tpu.remote
    def slow(t):
        time.sleep(t)
        return t

    refs = [slow.remote(0.4) for _ in range(4)]
    start = time.monotonic()
    with pytest.raises(GetTimeoutError):
        ray_tpu.get(refs, timeout=0.2)
    # per-ref timeouts would allow up to 0.8s; total deadline must cut at ~0.2
    assert time.monotonic() - start < 0.5


def test_num_returns_zero(ray_start_regular):
    ran = []

    @ray_tpu.remote(num_returns=0)
    def fire_and_forget():
        ran.append(1)

    assert fire_and_forget.remote() is None
    for _ in range(100):
        if ran:
            break
        time.sleep(0.02)
    assert ran == [1]


def test_named_actor_reusable_after_ctor_failure(ray_start_regular):
    @ray_tpu.remote
    class Fragile:
        def __init__(self, ok):
            if not ok:
                raise ValueError("nope")
            self.ok = ok

        def ping(self):
            return "pong"

    h = Fragile.options(name="svc").remote(ok=False)
    with pytest.raises(Exception):
        ray_tpu.get(h.ping.remote(), timeout=5)
    # the name must be released so a retry can claim it
    for _ in range(100):
        try:
            h2 = Fragile.options(name="svc").remote(ok=True)
            break
        except ValueError:
            time.sleep(0.02)
    else:
        pytest.fail("name 'svc' never released after constructor failure")
    assert ray_tpu.get(h2.ping.remote()) == "pong"


def test_kill_releases_resources_exactly_once(ray_start_2_cpus):
    @ray_tpu.remote(num_cpus=2)
    class Big:
        def ping(self):
            return 1

    b = Big.remote()
    ray_tpu.get(b.ping.remote())
    assert ray_tpu.available_resources().get("CPU", 0) == 0
    ray_tpu.kill(b)
    time.sleep(0.1)
    assert ray_tpu.available_resources().get("CPU", 0) == 2.0
    # double-kill must not over-release
    ray_tpu.kill(b)
    time.sleep(0.1)
    assert ray_tpu.available_resources().get("CPU", 0) == 2.0


def test_submit_after_kill_gets_error_not_hang(ray_start_regular):
    @ray_tpu.remote
    class A:
        def f(self):
            return 1

    a = A.remote()
    ray_tpu.get(a.f.remote())
    ray_tpu.kill(a)
    time.sleep(0.05)
    with pytest.raises(Exception):
        ray_tpu.get(a.f.remote(), timeout=5)
