"""Auto-generated round-trip property suite: every ``@message`` class,
byte-identity included.

``test_wire.py`` hand-picks values; this suite is schema-driven — it
enumerates the live registry, generates seeded field values of each
declared wire type (big ints past i64, unicode, nested containers,
None-able defaults), and asserts the full contract per instance:

    decode(encode(x)) == x              (value identity)
    encode(decode(encode(x))) == encode(x)   (byte identity)

Byte identity is the stronger half: template ids and dedupe keys are
content hashes over encoded bytes, so a decode-encode cycle that
produces different bytes for an equal value silently splits identical
templates into distinct ids across processes.

Values are natively-encodable only — an Opaque (pickle) section decodes
to the unwrapped object and legitimately re-encodes differently, so
byte identity is only promised for the structural encoding (and
``test_opaque_not_byte_identical`` pins that boundary honestly).
"""

import os
import random
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:  # `tools` must resolve from the repo root
    sys.path.insert(0, REPO_ROOT)

import pytest  # noqa: E402

from ray_tpu._private import wire  # noqa: E402
from tools.raywire import extract, gen  # noqa: E402

_EXTRACTION = extract.extract(REPO_ROOT)


def _message_names():
    return sorted(_EXTRACTION.schema["messages"])


def test_extraction_is_clean():
    # The suite below trusts the schema; drift between the AST and the
    # live registry invalidates it.
    assert _EXTRACTION.problems == []


@pytest.mark.parametrize("name", _message_names())
def test_roundtrip_byte_identity(name):
    entry = _EXTRACTION.schema["messages"][name]
    rng = random.Random(hash(name) & 0xFFFFFFFF)
    for _ in range(50):
        inst = gen.build_instance(name, entry, rng)
        raw = wire.encode(inst)
        back = wire.decode(raw)
        assert back == inst, (name, inst, back)
        assert wire.encode(back) == raw, (
            f"{name}: decode-encode cycle changed the bytes — "
            f"content hashes over this frame are not stable")


@pytest.mark.parametrize("name", _message_names())
def test_defaulted_fields_roundtrip_as_none(name):
    # None is wire-legal in any field; defaulted fields carry it often
    # in practice (e.g. Reply.result on errors).
    entry = _EXTRACTION.schema["messages"][name]
    cls, _version = wire._REGISTRY[name]
    defaulted = [f["name"] for f in entry["fields"] if f["has_default"]]
    if not defaulted:
        pytest.skip(f"{name} has no defaulted fields")
    inst = cls(**{fname: None for fname in defaulted})
    raw = wire.encode(inst)
    back = wire.decode(raw)
    assert back == inst
    assert wire.encode(back) == raw


def test_catalog_driven_frames_match_live_encoder():
    # gen.build_frame (the skew simulator's standalone encoder) must
    # produce byte-identical frames to the live encoder when driven
    # with the live shape — otherwise skew evidence is evidence about
    # the wrong bytes.
    rng = random.Random(99)
    for name in _message_names():
        entry = _EXTRACTION.schema["messages"][name]
        inst = gen.build_instance(name, entry, rng)
        fields = [(f["name"], getattr(inst, f["name"]))
                  for f in entry["fields"]]
        assert gen.build_frame(name, entry["version"], fields) \
            == wire.encode(inst), name


def test_opaque_not_byte_identical_is_the_known_boundary():
    # An Opaque payload decodes to the wrapped object; re-encoding
    # wraps it again but pickle bytes need not match. Pin the boundary
    # so byte identity's scope stays explicit.
    class Custom:
        def __init__(self, x):
            self.x = x

        def __eq__(self, other):
            return isinstance(other, Custom) and other.x == self.x

    raw = wire.encode({"v": Custom(3)})
    back = wire.decode(raw)
    assert back == {"v": Custom(3)}
