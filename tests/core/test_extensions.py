"""Core-extension tests: dag, workflow, queue, metrics, state API,
timeline, placement groups, actor pool."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode
from ray_tpu.experimental import state
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.metrics import (
    Counter,
    Gauge,
    Histogram,
    export_prometheus,
)
from ray_tpu.util.placement_group import (
    PlacementGroupFactory,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.queue import Empty, Queue


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


# -- dag --------------------------------------------------------------------


def test_dag_function_graph():
    @ray_tpu.remote
    def a(x):
        return x + 1

    @ray_tpu.remote
    def b(x):
        return x * 2

    @ray_tpu.remote
    def combine(x, y):
        return x + y

    with InputNode() as inp:
        dag = combine.bind(a.bind(inp), b.bind(inp))
    assert dag.execute(10) == 11 + 20


def test_dag_actor_graph():
    @ray_tpu.remote
    class Acc:
        def __init__(self, start):
            self.v = start

        def add(self, x):
            self.v += x
            return self.v

    with InputNode() as inp:
        node = Acc.bind(100)
        dag = node.add.bind(inp)
    assert dag.execute(5) == 105
    assert dag.execute(7) == 112  # same actor reused


def test_dag_diamond_executes_shared_node_once():
    calls = []

    @ray_tpu.remote
    def source():
        calls.append(1)
        return 1

    @ray_tpu.remote
    def double(x):
        return x * 2

    @ray_tpu.remote
    def add(x, y):
        return x + y

    src = source.bind()
    dag = add.bind(double.bind(src), double.bind(src))
    assert dag.execute() == 4
    assert len(calls) == 1


# -- workflow ---------------------------------------------------------------


def test_workflow_run_and_resume(tmp_path):
    workflow.init(str(tmp_path))
    executed = []

    @ray_tpu.remote
    def step_a():
        executed.append("a")
        return 10

    @ray_tpu.remote
    def step_b(x):
        executed.append("b")
        return x * 2

    dag = step_b.bind(step_a.bind())
    out = workflow.run(dag, workflow_id="wf1")
    assert out == 20
    assert workflow.get_status("wf1") == "SUCCESSFUL"
    assert workflow.get_output("wf1") == 20

    # Re-running skips completed steps entirely.
    executed.clear()
    out2 = workflow.run(dag, workflow_id="wf1")
    assert out2 == 20
    assert executed == []


def test_workflow_failure_then_resume(tmp_path):
    workflow.init(str(tmp_path))
    state_holder = {"fail": True}

    @ray_tpu.remote
    def good():
        return 5

    @ray_tpu.remote
    def flaky(x):
        if state_holder["fail"]:
            raise RuntimeError("boom")
        return x + 1

    dag = flaky.bind(good.bind())
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="wf2")
    assert workflow.get_status("wf2") == "FAILED"
    state_holder["fail"] = False
    out = workflow.run(dag, workflow_id="wf2")  # resumes: `good` cached
    assert out == 6
    assert ("wf2", "SUCCESSFUL") in workflow.list_all()


# -- queue ------------------------------------------------------------------


def test_queue_basic():
    q = Queue(maxsize=2)
    q.put(1)
    q.put(2)
    assert q.qsize() == 2
    assert q.get() == 1
    assert q.get() == 2
    with pytest.raises(Empty):
        q.get_nowait()
    q.shutdown()


def test_queue_across_tasks():
    q = Queue()

    @ray_tpu.remote
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return True

    ray_tpu.get(producer.remote(q, 5))
    assert [q.get() for _ in range(5)] == [0, 1, 2, 3, 4]
    q.shutdown()


# -- metrics ----------------------------------------------------------------


def test_metrics():
    c = Counter("test_requests", "desc", tag_keys=("route",))
    c.inc(1, tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    c.inc(5, tags={"route": "/b"})
    assert c.get({"route": "/a"}) == 3
    g = Gauge("test_gauge")
    g.set(42)
    assert g.get() == 42
    h = Histogram("test_lat", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    stats = h.get()
    assert stats["count"] == 3
    assert stats["buckets"] == [1, 1, 1]
    text = export_prometheus()
    assert "test_requests" in text and "test_lat_bucket" in text


# -- state API + timeline ---------------------------------------------------


def test_state_api_tasks_and_actors():
    @ray_tpu.remote
    def work(x):
        return x

    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    ray_tpu.get([work.remote(i) for i in range(3)])
    a = A.remote()
    ray_tpu.get(a.ping.remote())

    tasks = state.list_tasks()
    names = {t["name"] for t in tasks}
    assert any("work" in n for n in names)
    finished = state.list_tasks(filters=[("state", "=", "FINISHED")])
    assert len(finished) >= 3
    actors = state.list_actors()
    assert any(r["class_name"] == "A" for r in actors)
    summary = state.summarize_tasks()
    assert any("work" in k for k in summary)


def test_timeline_chrome_trace(tmp_path):
    @ray_tpu.remote
    def traced():
        time.sleep(0.01)
        return 1

    ray_tpu.get(traced.remote())
    path = str(tmp_path / "trace.json")
    events = ray_tpu.timeline(path)
    assert any("traced" in e["name"] for e in events)
    import json

    with open(path) as f:
        data = json.load(f)
    assert isinstance(data, list) and data


# -- placement groups -------------------------------------------------------


def test_placement_group_reserve_and_use():
    pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="PACK")
    assert pg.wait(timeout=5)

    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    @ray_tpu.remote
    def inside():
        return "ok"

    out = ray_tpu.get(inside.options(
        num_cpus=2,
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0),
    ).remote())
    assert out == "ok"
    table = placement_group_table()
    assert any(v["state"] == "CREATED" for v in table.values())
    remove_placement_group(pg)


def test_placement_group_factory():
    factory = PlacementGroupFactory([{"CPU": 0}, {"CPU": 1}],
                                    strategy="PACK")
    assert factory.required_resources() == {"CPU": 1}
    pg = factory()
    assert pg.wait(timeout=5)
    remove_placement_group(pg)


def test_actor_pool():
    @ray_tpu.remote
    class Worker:
        def double(self, x):
            return x * 2

    pool = ActorPool([Worker.remote() for _ in range(3)])
    got = list(pool.map(lambda a, v: a.double.remote(v), range(10)))
    assert got == [x * 2 for x in range(10)]
    got2 = sorted(pool.map_unordered(lambda a, v: a.double.remote(v),
                                     range(5)))
    assert got2 == [0, 2, 4, 6, 8]
