"""Typed wire contracts: roundtrips, validation, versioning, opaque
confinement, and the RPC envelope integration."""

import dataclasses

import numpy as np
import pytest

from ray_tpu._private import wire


def roundtrip(v):
    return wire.decode(wire.encode(v))


def test_scalar_roundtrips():
    for v in [None, True, False, 0, -1, 2 ** 40, -(2 ** 70), 2 ** 100,
              3.25, float("inf"), "", "héllo", b"", b"\x00\xff",
              [1, 2, [3]], (1, (2,)), {"a": 1, 2: "b", b"k": None}]:
        got = roundtrip(v)
        assert got == v
        assert type(got) is type(v) or isinstance(v, bool)


def test_nested_structures_stay_native():
    msg = {"method": "report", "kwargs": {"available": {"CPU": 4.0},
                                          "ids": [b"\x01" * 16] * 3}}
    assert wire.encodes_natively(msg)
    assert roundtrip(msg) == msg


def test_unknown_objects_become_tagged_opaque():
    arr = np.arange(5)
    enc = wire.encode({"x": arr})
    assert not wire.encodes_natively({"x": arr})
    got = wire.decode(enc)
    np.testing.assert_array_equal(got["x"], arr)
    # A receiver can refuse opaque payloads outright.
    with pytest.raises(wire.WireError, match="opaque"):
        wire.decode(enc, allow_opaque=False)


def test_envelope_messages():
    req = wire.Request(id="c:1", method="f", kwargs={"x": 1})
    got = roundtrip(req)
    assert isinstance(got, wire.Request)
    assert (got.id, got.method, got.kwargs) == ("c:1", "f", {"x": 1})
    assert wire.encodes_natively(req)

    rep = wire.Reply(ok=False, error="boom", traceback="tb")
    got = roundtrip(rep)
    assert isinstance(got, wire.Reply)
    assert not got.ok and got.error == "boom"


def test_field_type_validation():
    bad = wire.Request(id=7, method="f", kwargs=None)  # id must be str
    with pytest.raises(wire.WireError, match="expected str"):
        roundtrip(bad)


def test_unknown_message_rejected():
    @wire.message("test.Ephemeral", version=1)
    class Ephemeral:
        x: int = 0

    enc = wire.encode(Ephemeral(x=1))
    del wire._REGISTRY["test.Ephemeral"]
    with pytest.raises(wire.WireError, match="unknown message"):
        wire.decode(enc)


def test_newer_version_rejected_older_fields_skipped():
    @wire.message("test.Versioned", version=2)
    class V2:
        x: int = 0
        y: str = ""

    enc = wire.encode(V2(x=1, y="z"))

    # Re-register as v1 with fewer fields (an "older receiver").
    @wire.message("test.Versioned", version=1)
    class V1:
        x: int = 0

    with pytest.raises(wire.WireError, match="newer than known"):
        wire.decode(enc)

    # Same version, extra field: skipped, not fatal (forward-compatible
    # field addition).
    @wire.message("test.Versioned2", version=1)
    class W2:
        x: int = 0
        y: str = ""

    enc = wire.encode(W2(x=5, y="keep"))
    del wire._REGISTRY["test.Versioned2"]

    @wire.message("test.Versioned2", version=1)
    class W1:
        x: int = 0

    got = wire.decode(enc)
    assert got.x == 5 and not hasattr(got, "y")
    del wire._REGISTRY["test.Versioned"]
    del wire._REGISTRY["test.Versioned2"]


def test_truncation_and_trailing_errors():
    enc = wire.encode({"a": [1, 2, 3]})
    with pytest.raises(wire.WireError, match="truncated"):
        wire.decode(enc[:-2])
    with pytest.raises(wire.WireError, match="trailing"):
        wire.decode(enc + b"N")


def test_rpc_envelope_end_to_end():
    from ray_tpu._private.rpc import RemoteCallError, RpcClient, RpcServer

    server = RpcServer({"add": lambda a, b: a + b,
                        "boom": lambda: 1 / 0})
    try:
        client = RpcClient.dedicated(server.address)
        assert client.call("add", a=2, b=40) == 42
        with pytest.raises(RemoteCallError, match="ZeroDivisionError"):
            client.call("boom")
        # user payloads (arbitrary objects) still flow
        assert client.call("add", a=[1, 2], b=[np.int64(3)]) \
            == [1, 2, np.int64(3)]
        client.close()
    finally:
        server.shutdown()


def test_resource_report_contract():
    rep = wire.ResourceReport(node_id="n1", available={"CPU": 2.0},
                              labels={}, stats={"cpu_percent": 1.5})
    got = roundtrip(rep)
    assert dataclasses.asdict(got) == dataclasses.asdict(rep)
    assert wire.encodes_natively(rep)
