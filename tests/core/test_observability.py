"""Cluster-wide observability plane: cross-node trace/event shipping,
fast-path metrics, snapshot APIs, and the merged Prometheus exposition.

Reference roles: GcsTaskManager (task events flow worker→GCS so the
state API and `ray.timeline()` are cluster-wide) + the per-node metrics
agents behind one scrape endpoint.
"""

import json
import time

import pytest

import ray_tpu
from ray_tpu.experimental import tracing


@pytest.fixture
def ray_local():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_task_event_snapshot_and_drain(ray_local):
    """The public TaskEventBuffer surface: snapshot() (full view, no
    private-attr reach-in) and drain_updates() (bounded delta with
    per-task coalescing — the shipping source)."""

    @ray_tpu.remote
    def f(x):
        return x

    w = ray_tpu._private.worker.global_worker()
    w.task_events.drain_updates(10 ** 6)  # clear older deltas
    ray_tpu.get([f.remote(i) for i in range(10)])

    snap = w.task_events.snapshot()
    assert len(snap) >= 10
    assert w.task_events.snapshot(limit=3) == snap[-3:]

    # Delta is coalesced per task (start + finish = one terminal entry)
    # and BOUNDED: a small limit leaves the rest dirty for next cycle.
    first = w.task_events.drain_updates(4)
    assert len(first) == 4
    rest = w.task_events.drain_updates(10 ** 6)
    drained = first + rest
    ours = [d for d in drained if d["name"].endswith(".f")]
    assert len(ours) == 10
    assert all(d["state"] == "FINISHED" for d in ours)
    # Drained again: nothing new.
    assert w.task_events.drain_updates(10 ** 6) == []

    # Round trip through the wire-friendly dict form.
    from ray_tpu._private.task_events import TaskEvent

    ev = TaskEvent.from_dict(ours[0])
    assert ev.task_id == ours[0]["task_id"]
    assert ev.state == "FINISHED"


def test_fastpath_metrics_exported(ray_local):
    """Submit/wait instrumentation lands in the Prometheus exposition:
    submit→start latency quantiles, wait-path counters, intern hit
    rate — computed on scrape, not on the hot path."""
    from ray_tpu.util.metrics import export_prometheus

    @ray_tpu.remote
    def f(x):
        return x + 1

    refs = [f.remote(i) for i in range(20)]
    ray_tpu.wait(refs, num_returns=len(refs), timeout=60)
    ray_tpu.get(refs)
    # Re-submitting the same shape exercises the intern hit counter.
    ray_tpu.get([f.remote(i) for i in range(5)])

    text = export_prometheus()
    for needle in (
        "ray_tpu_sched_submit_to_start_seconds_p50",
        "ray_tpu_sched_submit_to_start_seconds_p95",
        "ray_tpu_sched_submit_to_start_seconds_count",
        "ray_tpu_wait_calls_total",
        "ray_tpu_wait_snapshot_hits_total",
        "ray_tpu_intern_hits_total",
        "ray_tpu_intern_misses_total",
    ):
        assert needle in text, needle

    # The scheduler actually observed those submissions.
    from ray_tpu._private import perf_stats

    stat = perf_stats.latency("sched_submit_to_start_seconds")
    assert stat.total >= 25
    assert stat.quantile(0.95) >= stat.quantile(0.5) > 0


def test_aggregator_merge_prefers_terminal_state():
    """Duplicate task ids across reports (RUNNING then FINISHED, or a
    re-execution after node death) resolve to the terminal record."""
    from ray_tpu._private.obs_plane import ObsAggregator, _prefer
    from ray_tpu._private.task_events import TaskEvent

    running = TaskEvent(task_id="t1", name="f", kind="NORMAL_TASK",
                        state="RUNNING", start_s=1.0)
    done = TaskEvent(task_id="t1", name="f", kind="NORMAL_TASK",
                     state="FINISHED", start_s=1.0, end_s=2.0)
    assert _prefer(running, done) is done
    assert _prefer(done, running) is done

    agg = ObsAggregator(max_events=3)
    agg.report("n1", events=[running.to_dict()])
    agg.report("n1", events=[done.to_dict()])
    events = agg.task_events()
    assert len(events) == 1 and events[0].state == "FINISHED"
    # Bounded: oldest evicted first.
    for i in range(5):
        agg.report("n1", events=[TaskEvent(
            task_id=f"x{i}", name="f", kind="NORMAL_TASK",
            state="FINISHED", start_s=float(i)).to_dict()])
    assert agg.stats()["events_stored"] == 3


def test_cross_node_trace_stitching_and_cluster_views():
    """The tentpole acceptance path: driver → task on node-1 → actor
    call on node-2 is ONE trace with a correct parent chain after
    shipping; timeline() emits valid Chrome-trace JSON spanning both
    nodes; the head's merged exposition carries node-tagged series from
    every node plus the fast-path histograms."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu._private.task_spec import NodeAffinitySchedulingStrategy

    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        n1 = cluster.add_node(num_cpus=2)
        n2 = cluster.add_node(num_cpus=2)

        @ray_tpu.remote
        class A:
            def f(self, x):
                return x * 2

        a = A.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=n2)).remote()

        @ray_tpu.remote(
            scheduling_strategy=NodeAffinitySchedulingStrategy(node_id=n1))
        def outer(handle):
            return ray_tpu.get(handle.f.remote(21))

        assert ray_tpu.get(outer.remote(a), timeout=120) == 42

        # Lease-batched fan-out (too big for the 1-CPU head) so the
        # coalescing batcher runs and its histograms have samples —
        # submitted under an ambient trace so the end-to-end survival
        # of trace_parent through the interned TaskCall HEADER path
        # (not the full-spec path) is observable in the shipped spans.
        from ray_tpu._private.task_spec import set_ambient_trace_parent

        @ray_tpu.remote(num_cpus=2)
        def fan(x):
            return x

        set_ambient_trace_parent(("e2e-fan-trace", "e2e-fan-span"))
        try:
            fan_refs = [fan.remote(i) for i in range(8)]
        finally:
            set_ambient_trace_parent(None)
        assert sorted(ray_tpu.get(fan_refs, timeout=120)) == \
            list(range(8))

        # Shipping is periodic: poll until both remote spans arrived.
        deadline = time.monotonic() + 60
        outer_span = method_span = None
        while time.monotonic() < deadline:
            spans = tracing.export_spans()
            outer_span = next((s for s in spans
                               if s["name"].endswith("outer")), None)
            method_span = next((s for s in spans
                                if s["name"] == "A.f"), None)
            if outer_span is not None and method_span is not None and \
                    method_span["status"]["code"] == "STATUS_CODE_OK":
                break
            time.sleep(0.3)
        assert outer_span is not None and method_span is not None

        # One trace, rooted at the driver-submitted task, stitched
        # across two different executing nodes.
        assert outer_span["traceId"] == outer_span["spanId"]
        assert outer_span["parentSpanId"] is None
        assert method_span["traceId"] == outer_span["traceId"]
        assert method_span["parentSpanId"] == outer_span["spanId"]
        assert (method_span["attributes"]["ray_tpu.node_id"]
                != outer_span["attributes"]["ray_tpu.node_id"])

        trace = tracing.get_trace(outer_span["traceId"])
        assert [s["name"].rsplit(".", 1)[-1] for s in trace] == \
            ["outer", "f"]

        # trace_parent survived the interned TaskCall HEADER path: the
        # fan tasks ran on a worker node (shipped as template-id +
        # header, not full specs) yet carry the ambient trace.
        deadline = time.monotonic() + 60
        fan_spans = []
        while time.monotonic() < deadline:
            fan_spans = tracing.get_trace("e2e-fan-trace")
            if len(fan_spans) >= 8:
                break
            time.sleep(0.3)
        assert len(fan_spans) >= 8
        assert all(s["parentSpanId"] == "e2e-fan-span"
                   for s in fan_spans)
        # ...and they executed off-head (a worker node's buffer shipped
        # them), proving the header path, not local execution.
        head_node = cluster.driver_worker.backend.local_backend \
            .node_id.hex()
        assert any(s["attributes"]["ray_tpu.node_id"] != head_node
                   for s in fan_spans)

        # Chrome-trace dump: valid JSON, required fields, both nodes.
        events = ray_tpu.timeline()
        parsed = json.loads(json.dumps(events))
        assert parsed and all(
            e["ph"] == "X" and isinstance(e["ts"], float) and e["pid"]
            for e in parsed)
        assert len({e["pid"] for e in parsed}) >= 2

        # State API sees node-executed tasks too.
        from ray_tpu.experimental import state

        rows = state.list_tasks()
        assert any(r["name"] == "A.f" for r in rows)

        # Merged exposition: node-tagged series from BOTH nodes plus
        # the fast-path histograms, under the Prometheus content type.
        from ray_tpu._private.obs_plane import export_cluster_prometheus
        from ray_tpu.util.metrics import PROMETHEUS_CONTENT_TYPE

        assert PROMETHEUS_CONTENT_TYPE == "text/plain; version=0.0.4"
        deadline = time.monotonic() + 30
        text = ""
        while time.monotonic() < deadline:
            text = export_cluster_prometheus(cluster.driver_worker)
            if f'node="{n1}"' in text and f'node="{n2}"' in text:
                break
            time.sleep(0.3)
        assert f'node="{n1}"' in text and f'node="{n2}"' in text
        assert "ray_tpu_batcher_queue_delay_seconds_p95" in text
        assert "ray_tpu_batcher_flush_items_p95" in text
        assert "ray_tpu_sched_submit_to_start_seconds_p95" in text
        # Node-shipped snapshots carry the nodes' own runtime gauges.
        assert f'ray_tpu_tasks{{node="{n1}",state="FINISHED"}}' in text \
            or f'ray_tpu_tasks{{node="{n2}",state="FINISHED"}}' in text
    finally:
        cluster.shutdown()


def test_trace_parent_survives_interned_call_header():
    """The TaskCall wire header carries trace_parent: a spec rebuilt
    from an interned template on the receiving side keeps the exact
    (trace_id, parent_span) pair end-to-end."""
    from ray_tpu._private import wire
    from ray_tpu._private.ids import TaskID
    from ray_tpu._private.task_spec import TaskKind, intern_template

    tpl = intern_template(kind=TaskKind.NORMAL_TASK,
                          func=lambda x: x, name="traced",
                          num_returns=1, resources={})
    call = wire.TaskCall(template_id=tpl.template_id,
                         task_id=TaskID.from_random().binary(),
                         args=None, kwargs=None, num_returns=1,
                         trace_parent=("trace-abc", "span-def"))
    decoded = wire.decode(wire.encode(call))
    assert tuple(decoded.trace_parent) == ("trace-abc", "span-def")
    spec = tpl.make_spec(TaskID(decoded.task_id), (), {},
                         trace_parent=tuple(decoded.trace_parent))
    from ray_tpu._private.task_spec import trace_id_of

    assert trace_id_of(spec) == "trace-abc"
    assert spec.trace_parent[1] == "span-def"
