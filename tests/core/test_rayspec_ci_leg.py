"""Tier-1 rayspec leg: the recorder + linearizability checker run over
the decision-core suites via the real CLI, on every CI run, inside a
hard wall-clock budget.

What the leg pins (the ISSUE's acceptance criteria):

- ``python -m tools.rayspec`` (default paths: the fault-semantics and
  scheduler-scale suites, which drive every catalog core) exits 0 with
  ZERO linearizability violations and writes the deterministic
  ``RAYSPEC_REPORT.json`` artifact at the repo root (volatile counters
  in the gitignored ``.timing.json`` sidecar);
- every ``SPEC_CATALOG`` core actually recorded history — a core whose
  taps went silent would "pass" vacuously;
- the leg stays under 60s so it can live in tier-1 forever;
- rayspec holds itself to the repo's own gates: its sources pass
  raylint (asserted in test_raylint.py's tier-1 sweep alongside
  ray_tpu, raysan and raymc).
"""

import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_LEG_BUDGET_S = 60.0
_ARTIFACT = os.path.join(REPO_ROOT, "RAYSPEC_REPORT.json")


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def test_rayspec_leg_clean_bounded_and_deterministic():
    t0 = time.monotonic()
    out = subprocess.run(
        [sys.executable, "-m", "tools.rayspec",
         "--report", "json", "--report-file", _ARTIFACT],
        cwd=REPO_ROOT, env=_env(), capture_output=True, text=True,
        timeout=_LEG_BUDGET_S + 60)
    wall = time.monotonic() - t0
    assert out.returncode == 0, (
        f"rayspec leg failed (rc={out.returncode}):\n"
        f"{out.stdout[-4000:]}\n{out.stderr[-2000:]}")
    assert wall < _LEG_BUDGET_S, (
        f"rayspec leg took {wall:.1f}s — over the "
        f"{_LEG_BUDGET_S:.0f}s budget; shrink the recorded suites "
        f"before shrinking coverage")

    with open(_ARTIFACT, "r", encoding="utf-8") as f:
        report = json.load(f)
    assert report["pass"] is True
    assert report["recorder_overflowed"] is False
    assert report["undecided"] == 0, (
        "the checker washed out on a recorded history — raise the "
        "search budget or shrink the history, but keep a verdict")
    from tools.rayspec.specs import SPEC_CATALOG

    assert set(report["cores"]) == set(SPEC_CATALOG), (
        f"recorded cores {sorted(report['cores'])} != catalog "
        f"{sorted(SPEC_CATALOG)} — a silent tap means a vacuous pass")
    for name, row in report["cores"].items():
        assert row["violations"] == [], (
            f"{name}: real recorded history is NOT linearizable:\n"
            + json.dumps(row["violations"], indent=2))

    # Deterministic artifact: volatile counters are normalized to the
    # placeholder; the real values live in the gitignored sidecar.
    from tools.rayspec.__main__ import VOLATILE_FIELDS

    assert report["elapsed_s"] == 0
    for row in report["cores"].values():
        for key in VOLATILE_FIELDS:
            if key in row:
                assert row[key] == 0, (key, row)
    with open(_ARTIFACT + ".timing.json", "r", encoding="utf-8") as f:
        timings = json.load(f)
    assert timings["elapsed_s"] > 0
    assert any(k.endswith("recorded_events") and v > 0
               for k, v in timings.items()), timings
