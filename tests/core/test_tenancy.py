"""Tenancy enforcement plane (`_private/tenancy.py` + its four wiring
layers): quotas at admission/dispatch, weighted fair queuing, ingress
token buckets + auth, and per-job arena budgets.

The flagship scenario is the ISSUE's N-adversarial-jobs test: a submit
flood, an object hog, and a latency-sensitive serve app run
concurrently with enforcement ON — the serve app's p99 stays bounded,
the flood is capped at its quota (rejections typed + metered), and the
hog's arena spills land in its OWN job_summary row. The enforcement-off
control proves the flood actually floods without the plane.
"""

import json
import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import tenancy
from ray_tpu._private.config import ray_config
from ray_tpu._private.task_spec import set_ambient_job_id
from ray_tpu.exceptions import JobQuotaExceededError


@pytest.fixture
def enforcement(monkeypatch):
    monkeypatch.setattr(ray_config, "tenancy_enforcement", True)
    yield


def _spec(job="a", cpus=1.0, attempt=0):
    from types import SimpleNamespace

    return SimpleNamespace(job_id=job, resources={"CPU": cpus},
                           attempt=attempt)


# -- config grammar ----------------------------------------------------------


def test_parse_job_quotas_grammar_and_malformed():
    q = tenancy.parse_job_quotas(
        "a=cpus:2,queued:100,leases:2; b=cpus:0.5 ;"
        "junk; =cpus:1; c=cpus:abc,queued:7; d=weird:3")
    assert q["a"].cpu_milli == 2000 and q["a"].queued == 100 \
        and q["a"].leases == 2
    assert q["b"].cpu_milli == 500 and q["b"].queued == -1
    assert q["c"].queued == 7 and q["c"].cpu_milli == -1
    assert "junk" not in q and "" not in q and "d" not in q


def test_parse_weights_rates_budgets():
    w = tenancy.parse_job_weights("a=4,b=1,c=0,d=x")
    assert w == {"a": 4.0, "b": 1.0}  # zero/garbage weights rejected
    r = tenancy.parse_rate_limits("a=100:200;b=10;c=0;d=x")
    assert r == {"a": (100.0, 200.0), "b": (10.0, 10.0)}
    b = tenancy.parse_arena_budgets("a=64m;b=1g;c=4096;d=junk")
    assert b == {"a": 64 * 2**20, "b": 2**30, "c": 4096}
    assert tenancy.parse_bytes("2k") == 2048
    assert tenancy.parse_bytes("nope") is None


# -- quota ledger ------------------------------------------------------------


def test_ledger_queued_ceiling_and_release(enforcement, monkeypatch):
    monkeypatch.setattr(ray_config, "job_quotas", "a=queued:2")
    ledger = tenancy.QuotaLedger()
    s1, s2, s3 = _spec(), _spec(), _spec()
    assert ledger.note_queued(s1) is None
    assert ledger.note_queued(s1) is None  # idempotent per spec
    assert ledger.note_queued(s2) is None
    reason = ledger.note_queued(s3)
    assert reason is not None and "queued:2" in reason \
        and "job_quotas" in reason
    ledger.note_dequeued(s1)
    assert ledger.note_queued(s3) is None  # slot freed
    # Replays (attempt > 0) and actor-restart creation resubmits
    # (restarts_used > 0) bypass the ceiling: accepted work retries,
    # and a bounced restart would strand the gate in RESTARTING.
    assert ledger.note_queued(_spec(attempt=1)) is None
    restart = _spec()
    restart.restarts_used = 1
    assert ledger.note_queued(restart) is None
    assert ledger.usage("a")["queued"] == 2


def test_ledger_cpu_quota_peak_and_conservation(enforcement,
                                                monkeypatch):
    monkeypatch.setattr(ray_config, "job_quotas", "a=cpus:2")
    ledger = tenancy.QuotaLedger()
    s1, s2, s3 = _spec(), _spec(), _spec()
    assert ledger.try_acquire_cpu(s1)
    assert ledger.try_acquire_cpu(s1)  # idempotent: still one charge
    assert ledger.try_acquire_cpu(s2)
    assert not ledger.try_acquire_cpu(s3)  # at 2000 milli
    assert ledger.usage("a")["cpu_milli"] == 2000
    assert ledger.usage("a")["peak_cpu_milli"] == 2000
    ledger.release_cpu(s1)
    ledger.release_cpu(s1)  # idempotent: token cleared on first
    assert ledger.usage("a")["cpu_milli"] == 1000
    assert ledger.try_acquire_cpu(s3)
    # Unquota'd jobs and zero-CPU specs always pass.
    assert ledger.try_acquire_cpu(_spec(job="other"))
    assert ledger.try_acquire_cpu(_spec(cpus=0.0))


def test_ledger_lease_quota(enforcement, monkeypatch):
    monkeypatch.setattr(ray_config, "job_quotas", "a=leases:1")
    ledger = tenancy.QuotaLedger()
    assert ledger.try_acquire_lease("a")
    assert not ledger.try_acquire_lease("a")
    assert ledger.try_acquire_lease("b")  # unquota'd
    ledger.release_lease("a")
    assert ledger.try_acquire_lease("a")


def test_ledger_disabled_and_enforcement_off(monkeypatch):
    monkeypatch.setattr(ray_config, "job_quotas", "a=cpus:1,queued:0")
    # Enforcement off: everything passes.
    monkeypatch.setattr(ray_config, "tenancy_enforcement", False)
    ledger = tenancy.QuotaLedger()
    assert ledger.note_queued(_spec()) is None
    assert ledger.try_acquire_cpu(_spec())
    # Node-side disable: same, even with enforcement on.
    monkeypatch.setattr(ray_config, "tenancy_enforcement", True)
    node_ledger = tenancy.QuotaLedger()
    node_ledger.disable()
    assert node_ledger.note_queued(_spec()) is None
    assert node_ledger.try_acquire_cpu(_spec())


def test_ledger_park_and_atomic_drain(enforcement, monkeypatch):
    monkeypatch.setattr(ray_config, "job_quotas", "a=cpus:1")
    ledger = tenancy.QuotaLedger()
    running = _spec()
    assert ledger.try_acquire_cpu(running)
    parked = [_spec(), _spec()]
    for s in parked:
        assert not ledger.try_acquire_cpu(s)
        ledger.park(s)
    assert ledger.parked_count() == 2
    assert ledger.take_dispatchable() == []  # no headroom yet
    ledger.release_cpu(running)
    out = ledger.take_dispatchable()
    # Exactly ONE dispatches into the single freed slot, charged
    # atomically under the ledger lock; the other stays parked.
    assert len(out) == 1 and getattr(out[0], "_quota_cpu", None)
    assert ledger.parked_count() == 1
    assert ledger.usage("a")["peak_cpu_milli"] == 1000


# -- weighted fair queue -----------------------------------------------------


def test_fair_queue_is_fifo_with_enforcement_off(monkeypatch):
    monkeypatch.setattr(ray_config, "tenancy_enforcement", False)
    q = tenancy.FairTaskQueue()
    items = [_spec(job=j) for j in ("a", "b", "a", "c", "b")]
    for item in items:
        q.put(item)
    assert [q.get_nowait() for _ in range(5)] == items


def test_fair_queue_serves_by_weight():
    q = tenancy.FairTaskQueue(weights={"fast": 3.0, "slow": 1.0})
    for i in range(12):
        q.put(_spec(job="fast"))
        q.put(_spec(job="slow"))
    first8 = [q.get_nowait().job_id for _ in range(8)]
    # 3:1 weights: of the first 8 serves, "fast" gets ~6.
    assert first8.count("fast") == 6, first8
    # Non-starvation witness: nobody was bypassed past the WFQ bound
    # (total_weight / weight = 4 for the slow class).
    assert q.max_bypass <= 4
    # Everything eventually drains exactly once.
    rest = []
    while not q.empty():
        rest.append(q.get_nowait().job_id)
    assert (first8 + rest).count("slow") == 12


def test_fair_queue_get_timeout_raises_empty():
    import queue as _queue

    q = tenancy.FairTaskQueue(weights={"a": 1.0})
    with pytest.raises(_queue.Empty):
        q.get(timeout=0.05)
    with pytest.raises(_queue.Empty):
        q.get_nowait()


def test_fair_queue_idle_class_gets_no_credit():
    q = tenancy.FairTaskQueue(weights={"a": 1.0, "b": 1.0})
    for _ in range(6):
        q.put(_spec(job="a"))
    for _ in range(4):
        q.get_nowait()
    # b joins AFTER a burned virtual time: it starts at the global
    # clock, not at zero — it cannot monopolize the queue to "catch
    # up" on credit it never earned.
    for _ in range(4):
        q.put(_spec(job="b"))
    nxt = [q.get_nowait().job_id for _ in range(4)]
    assert nxt.count("b") <= 3 and nxt.count("a") >= 1, nxt


def test_fair_queue_vt_bounded_by_tracked_jobs():
    q = tenancy.FairTaskQueue(weights={"a": 1.0})
    for i in range(tenancy.MAX_TRACKED_JOBS + 50):
        q.put(_spec(job=f"ephemeral-{i}"))
        q.get_nowait()
    # Per-submission job ids must not mint permanent clock entries.
    assert len(q._vt) <= tenancy.MAX_TRACKED_JOBS + 1
    assert not q._bypass


def test_actor_creations_count_against_cpu_quota(enforcement,
                                                 monkeypatch):
    """A tenant cannot dodge its cpus: quota by running its flood as
    ACTORS: creations charge the same ledger (lifetime hold, released
    on actor death), so at most quota-many construct at once."""
    monkeypatch.setattr(ray_config, "job_quotas", "acjob=cpus:1")
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    try:
        @ray_tpu.remote(num_cpus=1)
        class Holder:
            def ping(self):
                return 1

        prev = set_ambient_job_id("acjob")
        try:
            actors = [Holder.remote() for _ in range(3)]
        finally:
            set_ambient_job_id(prev)
        # Exactly one constructs; the others park behind the quota.
        assert ray_tpu.get(actors[0].ping.remote(), timeout=30) == 1
        w = ray_tpu._private.worker.global_worker()
        time.sleep(0.3)  # give the dispatcher a beat to (not) launch
        usage = w.backend.quota_ledger.usage("acjob")
        assert usage["cpu_milli"] == 1000 \
            and usage["peak_cpu_milli"] == 1000, usage
        # Death releases the lifetime charge: the next one constructs.
        ray_tpu.kill(actors[0])
        assert ray_tpu.get(actors[1].ping.remote(), timeout=30) == 1
        assert w.backend.quota_ledger.usage(
            "acjob")["peak_cpu_milli"] == 1000
    finally:
        ray_tpu.shutdown()


# -- router fair share -------------------------------------------------------


def test_fair_share_turns_follow_weights(enforcement, monkeypatch):
    monkeypatch.setattr(ray_config, "job_weights", "vip=4,flood=1")
    fair = tenancy.FairShare()
    fair.enter_wait("vip")
    fair.enter_wait("flood")
    served = []
    for _ in range(10):
        for job in ("flood", "vip"):  # flood polls first every round
            if fair.may_dispatch(job):
                fair.charge(job)
                served.append(job)
                break
    assert served.count("vip") >= 7, served
    fair.exit_wait("vip")
    fair.exit_wait("flood")
    # No waiters: everything passes.
    assert fair.may_dispatch("anyone")


def test_fair_share_noop_when_enforcement_off(monkeypatch):
    monkeypatch.setattr(ray_config, "tenancy_enforcement", False)
    fair = tenancy.FairShare()
    fair.enter_wait("a")
    assert fair.may_dispatch("b")


# -- ingress token buckets ---------------------------------------------------


def test_token_bucket_refill_math():
    bucket = tenancy.TokenBucket(rate=2.0, burst=4.0, now=100.0)
    assert all(bucket.try_take(now=100.0) for _ in range(4))
    assert not bucket.try_take(now=100.0)
    assert bucket.retry_after_s() == pytest.approx(0.5)
    assert bucket.try_take(now=100.6)  # 1.2 tokens accrued
    assert not bucket.try_take(now=100.6)
    # Clock never runs backwards on the bucket.
    assert not bucket.try_take(now=99.0)


def test_ingress_limiter_per_job_and_cap(enforcement, monkeypatch):
    monkeypatch.setattr(ray_config, "ingress_rate_limits",
                        "limited=2:2")
    clock = [1000.0]
    limiter = tenancy.IngressLimiter(clock=lambda: clock[0])
    assert limiter.try_admit("limited") is None
    assert limiter.try_admit("limited") is None
    wait = limiter.try_admit("limited")
    assert wait is not None and wait > 0
    # Unlimited jobs (no entry, zero default) never shed.
    for _ in range(50):
        assert limiter.try_admit("free") is None
    clock[0] += 1.0  # 2 tokens accrue
    assert limiter.try_admit("limited") is None
    # Off switch.
    monkeypatch.setattr(ray_config, "tenancy_enforcement", False)
    assert limiter.try_admit("limited") is None


def test_ingress_limiter_overflow_uses_default_limit(enforcement,
                                                     monkeypatch):
    """Past the cardinality cap, overflow tags share the DEFAULT
    bucket — with the default's parameters, not whichever overflow
    job's limit arrived first; with no default rate they pass free."""
    monkeypatch.setattr(ray_config, "ingress_default_rate_per_s", 1.0)
    monkeypatch.setattr(ray_config, "ingress_default_burst", 1.0)
    clock = [0.0]
    limiter = tenancy.IngressLimiter(clock=lambda: clock[0])
    monkeypatch.setattr(tenancy, "MAX_TRACKED_JOBS", 4)
    for i in range(4):
        assert limiter.try_admit(f"j{i}") is None
    # 5th distinct tag: shares the "" default bucket (burst 1).
    assert limiter.try_admit("overflow-a") is None
    assert limiter.try_admit("overflow-b") is not None
    assert "" in limiter._buckets and limiter._buckets[""].burst == 1.0
    # No default rate configured: overflow tags are simply unlimited.
    monkeypatch.setattr(ray_config, "ingress_default_rate_per_s", 0.0)
    limiter2 = tenancy.IngressLimiter(clock=lambda: clock[0])
    monkeypatch.setattr(ray_config, "ingress_rate_limits",
                        "a=1;b=1;c=1;d=1;e=1")
    for job in ("a", "b", "c", "d"):
        assert limiter2.try_admit(job) is None
    assert limiter2.try_admit("e") is None  # overflow, no default
    assert "" not in limiter2._buckets


# -- arena budget helpers ----------------------------------------------------


def test_over_budget_and_victim_order(enforcement, monkeypatch):
    monkeypatch.setattr(ray_config, "job_arena_budgets", "hog=1k")
    over = tenancy.over_budget_jobs({"hog": 2048, "meek": 10 * 2**20})
    assert over == {"hog"}  # no budget -> never "over"
    job_of = {b"h1": "hog", b"v1": "meek", b"h2": "hog",
              b"v2": "meek"}.get
    ordered = tenancy.order_spill_victims(
        [b"v1", b"h1", b"v2", b"h2"], job_of, over)
    # Hog's objects first, cold-first preserved within each tier.
    assert ordered == [b"h1", b"h2", b"v1", b"v2"]
    assert tenancy.order_spill_victims([b"v1", b"h1"], job_of,
                                       set()) == [b"v1", b"h1"]


def test_arena_budget_victimizes_hog_not_neighbor(enforcement,
                                                  monkeypatch):
    """Pressure spill with a tenant over its arena budget: the HOG's
    cold objects leave the arena first — the innocent neighbor's older
    (colder) working set stays resident — and the spilled bytes are
    charged to the hog's counter."""
    import numpy as np

    from ray_tpu._private import perf_stats
    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.shm_plane import (SharedPlane,
                                            publish_task_output)

    monkeypatch.setattr(ray_config, "job_arena_budgets", "job-hog=4m")
    ray_tpu.shutdown()
    w = worker_mod.init(num_cpus=2)
    plane = SharedPlane(f"/rt_tenancy_{os.getpid()}", create=True,
                        capacity=24 * 2**20)
    plane.install(w)
    try:
        def publish(job, fill):
            oid = ObjectID.from_random()
            value = np.full(1_000_000, fill)  # 8 MB
            w.memory_store.put(oid, value, job_id=job)
            assert publish_task_output(w, oid, value)
            return oid

        base = perf_stats.counter("job_arena_spill_bytes",
                                  {"job": "job-hog"}).value
        victims = [publish("job-victim", 1.0), publish("job-victim", 2.0)]
        hogs = [publish("job-hog", float(10 + i)) for i in range(3)]
        # 5 x 8MB through a 24MB arena: pressure spilled ~2 objects —
        # the hog's own (it was over its 4m budget), never the
        # victim's colder ones.
        entries = w.memory_store._entries
        assert all(entries[oid].spilled_url is None
                   and entries[oid].shm_backed for oid in victims), \
            "the neighbor's working set was evicted by the hog"
        hog_spilled = [oid for oid in hogs
                       if entries[oid].spilled_url is not None]
        assert hog_spilled, "arena pressure spilled nothing of the hog"
        spilled_bytes = perf_stats.counter(
            "job_arena_spill_bytes", {"job": "job-hog"}).value - base
        assert spilled_bytes >= 8 * 10**6
        # Usage accounting: the hog's resident charge is visible.
        usage = plane.job_arena_bytes()
        assert usage.get("job-hog", 0) > 0
        assert usage.get("job-victim", 0) >= 16 * 10**6
        # Every value still reads back intact (transparent restore).
        for i, oid in enumerate(hogs):
            assert float(w.memory_store.get(oid)[0]) == float(10 + i)
    finally:
        plane.destroy()
        ray_tpu.shutdown()


# -- ingress integration (auth + 429) ----------------------------------------


@pytest.fixture
def serve_app(enforcement):
    from ray_tpu import serve

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)

    @serve.deployment
    class Echo:
        def __call__(self, request):
            return {"ok": True}

    serve.run(Echo.bind(), route_prefix="/echo")
    proxy = serve.start_http_proxy()
    yield proxy
    try:
        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()


def _http(proxy, path="/echo", headers=None):
    import http.client

    conn = http.client.HTTPConnection(proxy.host, proxy.port,
                                      timeout=30)
    try:
        conn.request("POST", path, body=json.dumps({}),
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def test_ingress_auth_shared_secret(serve_app, monkeypatch):
    proxy = serve_app
    monkeypatch.setattr(ray_config, "ingress_auth_token", "s3cret")
    status, _h, body = _http(proxy)
    assert status == 401 and b"credentials" in body
    status, _h, _b = _http(proxy, headers={"X-Auth-Token": "wrong"})
    assert status == 401
    status, _h, _b = _http(proxy,
                           headers={"Authorization": "Bearer s3cret"})
    assert status == 200
    status, _h, _b = _http(proxy, headers={"X-Auth-Token": "s3cret"})
    assert status == 200
    # Non-ASCII header bytes must be a clean 401, never an unhandled
    # exception on the connection (compare_digest refuses non-ASCII
    # str — the comparison runs over latin-1 bytes).
    status, _h, _b = _http(proxy,
                           headers={"Authorization": "Bearer caf\xe9"})
    assert status == 401
    # Unknown paths without credentials are ALSO 401, not 404 — no
    # route enumeration for unauthenticated clients.
    status, _h, _b = _http(proxy, path="/definitely-not-a-route")
    assert status == 401
    assert proxy.stats()["denied_401"] == 4


def test_ingress_rate_limit_429_retry_after(serve_app, monkeypatch):
    from ray_tpu._private import perf_stats

    proxy = serve_app
    monkeypatch.setattr(ray_config, "ingress_rate_limits",
                        "limited=2:2")
    base = perf_stats.counter("job_rate_limited",
                              {"job": "limited"}).value
    results = [_http(proxy, headers={"X-Job-Id": "limited"})
               for _ in range(5)]
    statuses = [s for s, _h, _b in results]
    assert statuses.count(200) == 2 and statuses.count(429) == 3, \
        statuses
    shed = next(h for s, h, _b in results if s == 429)
    assert "Retry-After" in shed
    # Untagged (unlimited) traffic is untouched.
    assert _http(proxy)[0] == 200
    assert proxy.stats()["limited_429"] == 3
    # The per-job counter reaches the metrics fold.
    assert perf_stats.counter("job_rate_limited",
                              {"job": "limited"}).value - base == 3
    # A slow-rate tenant's Retry-After carries the limiter's COMPUTED
    # accrual time, not a hardcoded 1s.
    monkeypatch.setattr(ray_config, "ingress_rate_limits",
                        "crawl=0.2:1")
    assert _http(proxy, headers={"X-Job-Id": "crawl"})[0] == 200
    status, headers, _b = _http(proxy, headers={"X-Job-Id": "crawl"})
    assert status == 429 and int(headers["Retry-After"]) >= 4, headers


# -- the flagship: N adversarial jobs ----------------------------------------


_FLOOD_LOCK = threading.Lock()
_FLOOD_STATE = {"running": 0, "peak": 0}


def _flood_body():
    with _FLOOD_LOCK:
        _FLOOD_STATE["running"] += 1
        _FLOOD_STATE["peak"] = max(_FLOOD_STATE["peak"],
                                   _FLOOD_STATE["running"])
    time.sleep(0.15)
    with _FLOOD_LOCK:
        _FLOOD_STATE["running"] -= 1
    return 1


def _reset_flood():
    with _FLOOD_LOCK:
        _FLOOD_STATE["running"] = 0
        _FLOOD_STATE["peak"] = 0


def test_adversarial_jobs_enforced(monkeypatch):
    """Flood + hog + latency-sensitive serve app, enforcement ON: the
    flood runs at most its CPU quota (excess parked behind its own
    limit, overflow rejected typed), the hog's arena spills land in
    its own job_summary row, and the serve app's p99 stays bounded
    while both run."""
    import numpy as np

    from ray_tpu import serve
    from ray_tpu._private import perf_stats
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.shm_plane import (SharedPlane,
                                            publish_task_output)
    from ray_tpu.experimental import state

    monkeypatch.setattr(ray_config, "tenancy_enforcement", True)
    monkeypatch.setattr(ray_config, "job_quotas",
                        "job-flood=cpus:1,queued:12")
    monkeypatch.setattr(ray_config, "job_weights",
                        "job-serve=8,job-flood=1")
    monkeypatch.setattr(ray_config, "job_arena_budgets", "job-hog=4m")
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    _reset_flood()
    plane = SharedPlane(f"/rt_adv_{os.getpid()}", create=True,
                        capacity=24 * 2**20)
    w = ray_tpu._private.worker.global_worker()
    plane.install(w)
    try:
        @serve.deployment
        class Api:
            def __call__(self, request):
                return {"out": 42}

        handle = serve.run(Api.bind(), route_prefix="/api")
        assert ray_tpu.get(handle.remote({}), timeout=30) == {"out": 42}

        flood = ray_tpu.remote(num_cpus=1)(_flood_body)
        prev = set_ambient_job_id("job-flood")
        try:
            flood_refs = [flood.remote() for _ in range(30)]
        finally:
            set_ambient_job_id(prev)

        # The hog, mid-flood: 4 x 8MB through a 24MB arena with a 4m
        # budget — its own objects spill.
        hog_base = perf_stats.counter("job_arena_spill_bytes",
                                      {"job": "job-hog"}).value
        for i in range(4):
            oid = ObjectID.from_random()
            value = np.full(1_000_000, float(i))
            w.memory_store.put(oid, value, job_id="job-hog")
            assert publish_task_output(w, oid, value)

        # The latency-sensitive job, also mid-flood: every request
        # must land, with p99 far under the flood's drain time.
        latencies = []
        for _ in range(25):
            t0 = time.monotonic()
            out = ray_tpu.get(handle.remote({}, _job="job-serve"),
                              timeout=30)
            latencies.append(time.monotonic() - t0)
            assert out == {"out": 42}
        latencies.sort()
        p99 = latencies[min(len(latencies) - 1,
                            -(-len(latencies) * 99 // 100) - 1)]
        assert p99 < 3.0, f"serve p99 {p99:.3f}s under flood"

        # Flood verdicts: capped at its quota the whole time...
        ledger = w.backend.quota_ledger
        assert ledger.usage("job-flood")["peak_cpu_milli"] <= 1000
        ok = rejected = 0
        for ref in flood_refs:
            try:
                ray_tpu.get(ref, timeout=60)
                ok += 1
            except JobQuotaExceededError:
                rejected += 1
        # ...admitted work completes, overflow was rejected TYPED.
        assert rejected >= 1 and ok >= 12, (ok, rejected)
        with _FLOOD_LOCK:
            assert _FLOOD_STATE["peak"] <= 1, _FLOOD_STATE

        # Attribution: the offenders' pressure shows up in THEIR rows.
        summary = state.job_summary()
        enforcement_row = summary["job-flood"].get("enforcement", {})
        assert enforcement_row.get("job_quota_rejections", 0) >= 1
        hog_spill = perf_stats.counter(
            "job_arena_spill_bytes", {"job": "job-hog"}).value - hog_base
        assert hog_spill >= 8 * 10**6
        assert summary["job-hog"].get("arena_bytes", 0) > 0
        # And the quota counters reach the exposition names the ISSUE
        # pins (ray_tpu_job_quota_*).
        from ray_tpu._private.runtime_metrics import \
            collect_runtime_metrics
        from ray_tpu.util.metrics import snapshot_registry

        collect_runtime_metrics()
        snap = snapshot_registry()
        assert "ray_tpu_job_quota_rejections_total" in snap
        assert "ray_tpu_job_arena_spill_bytes_total" in snap
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        plane.destroy()
        ray_tpu.shutdown()
        _reset_flood()


def test_adversarial_flood_without_enforcement_floods(monkeypatch):
    """The control: enforcement OFF, same flood — it grabs every CPU
    it can (peak concurrency far past the quota the enforced variant
    held), and nothing is rejected. This is what the enforcement plane
    is FOR."""
    monkeypatch.setattr(ray_config, "tenancy_enforcement", False)
    monkeypatch.setattr(ray_config, "job_quotas",
                        "job-flood=cpus:1,queued:12")
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    _reset_flood()
    try:
        flood = ray_tpu.remote(num_cpus=1)(_flood_body)
        prev = set_ambient_job_id("job-flood")
        try:
            refs = [flood.remote() for _ in range(30)]
        finally:
            set_ambient_job_id(prev)
        assert ray_tpu.get(refs, timeout=120) == [1] * 30  # none shed
        with _FLOOD_LOCK:
            peak = _FLOOD_STATE["peak"]
        assert peak >= 3, f"flood only reached {peak} concurrent"
    finally:
        ray_tpu.shutdown()
        _reset_flood()
