"""Node-death fault tolerance: health checks, lineage reconstruction,
in-flight resubmission, actor restart across nodes, distributed release.

Reference test models: `python/ray/tests/test_reconstruction.py`,
`test_actor_failures.py`, the NodeKiller chaos fixture
(`python/ray/_private/test_utils.py:1347`).

These tests pin work to subprocess nodes with a custom resource the head
doesn't have, so killing the node provably kills the only copy.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture
def fast_health(monkeypatch):
    from ray_tpu._private.config import ray_config

    monkeypatch.setattr(ray_config, "health_check_period_s", 0.2)
    monkeypatch.setattr(ray_config, "health_check_failure_threshold", 2)
    yield ray_config


@pytest.fixture
def cluster(fast_health):
    c = Cluster(head_node_args={"num_cpus": 1})
    yield c
    c.shutdown()


def _wait_for(pred, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {msg}")


def test_object_reconstruction_on_node_kill(cluster):
    """Objects whose only copy died are re-created from lineage."""
    # simulate_remote_host: the node gets its own shm segment, so killing
    # it genuinely loses the object (a shared segment would survive).
    node = cluster.add_node(num_cpus=2, simulate_remote_host=True)

    @ray_tpu.remote(num_cpus=2)
    def produce(x):
        return {"value": x * 2, "pid": os.getpid()}

    ref = produce.remote(21)
    first = ray_tpu.get(ref, timeout=60)
    assert first["value"] == 42
    producer_pid = first["pid"]
    assert producer_pid != os.getpid()  # ran on the node, not the driver

    # Drop the driver's cached copy so the next get must re-fetch, then
    # kill the node without telling the head.
    cluster.driver_worker.memory_store.evict([ref.id])
    cluster.kill_node(node)
    node2 = cluster.add_node(num_cpus=2, simulate_remote_host=True)
    assert node2

    again = ray_tpu.get(ref, timeout=60)
    assert again["value"] == 42
    assert again["pid"] != producer_pid  # re-executed, not cached


def test_inflight_task_resubmitted_on_node_death(cluster):
    """A task running on a node that dies is re-executed elsewhere."""
    node = cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=2)
    def slow():
        time.sleep(3.0)
        return os.getpid()

    ref = slow.remote()
    time.sleep(0.8)  # let it dispatch and start
    cluster.kill_node(node)
    pid = ray_tpu.get(ref, timeout=90)
    assert pid != os.getpid()


def test_health_checker_marks_node_dead(cluster):
    node = cluster.add_node(num_cpus=1)
    assert cluster.head.nodes[node].alive
    cluster.kill_node(node)
    _wait_for(lambda: not cluster.head.nodes[node].alive,
              msg="health checker to mark node dead")


def test_actor_restart_on_node_death(cluster):
    node = cluster.add_node(num_cpus=2)
    node2 = cluster.add_node(num_cpus=2)
    assert node2

    @ray_tpu.remote(max_restarts=1, num_cpus=2)
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def pid(self):
            return os.getpid()

    c = Counter.remote()
    first_pid = ray_tpu.get(c.pid.remote(), timeout=60)
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1

    # Kill exactly the node hosting the actor (recorded BEFORE the kill:
    # reading it afterwards can race the health checker's restart and
    # kill the actor's *new* home too, exhausting the restart budget).
    host = next(iter(cluster.head.actor_nodes.values()))
    assert host in (node, node2)
    cluster.kill_node(host)
    _wait_for(lambda: not cluster.head.nodes[host].alive,
              msg="dead node detected")

    # After restart the actor lives on the surviving node with fresh
    # state (reference restart semantics: state is reconstructed by
    # rerunning __init__).
    def call_ok():
        try:
            return ray_tpu.get(c.incr.remote(), timeout=10) >= 1
        except Exception:
            return False

    _wait_for(call_ok, timeout=60, msg="actor restart")
    new_pid = ray_tpu.get(c.pid.remote(), timeout=30)
    assert new_pid != first_pid


def test_actor_without_restart_budget_dies(cluster):
    node = cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=2)  # max_restarts defaults to 0
    class A:
        def f(self):
            return 1

    a = A.remote()
    assert ray_tpu.get(a.f.remote(), timeout=60) == 1
    cluster.kill_node(node)
    _wait_for(lambda: not cluster.head.nodes[node].alive,
              msg="node death detection")
    with pytest.raises(Exception):
        ray_tpu.get(a.f.remote(), timeout=30)


def _counter_value(name, outcome=None):
    from ray_tpu._private import perf_stats

    # counter() is create-or-get on the process-global registry.
    return perf_stats.counter(
        name, {"outcome": outcome} if outcome else None).value


def test_transitive_reconstruction_chain(cluster):
    """Chain a → b → c across nodes; kill the node holding all the
    intermediates. get(c) completes via RECURSIVE re-execution, and
    the attempt charge lands per object, not per chain."""
    import numpy as np

    node = cluster.add_node(num_cpus=2, simulate_remote_host=True)

    @ray_tpu.remote(num_cpus=2)
    def a():
        return np.arange(1000, dtype=np.float64)

    @ray_tpu.remote(num_cpus=2)
    def b(x):
        return x * 2

    @ray_tpu.remote(num_cpus=2)
    def c(x):
        return float(x.sum())

    ra = a.remote()
    rb = b.remote(ra)
    rc = c.remote(rb)
    want = float(np.arange(1000, dtype=np.float64).sum() * 2)
    assert ray_tpu.get(rc, timeout=90) == want

    # Lose every copy: evict the driver's caches, kill the producer.
    cluster.driver_worker.memory_store.evict([ra.id, rb.id, rc.id])
    before = _counter_value("reconstructions", "reexecute")
    cluster.kill_node(node)
    node2 = cluster.add_node(num_cpus=2, simulate_remote_host=True)
    assert node2

    assert ray_tpu.get(rc, timeout=120) == want
    # The whole lost chain re-executed — one charge per OBJECT (c alone
    # re-executing could never produce the value; a per-chain charge
    # would burn c's budget on a/b's attempts).
    delta = _counter_value("reconstructions", "reexecute") - before
    assert delta >= 2, f"expected recursive re-execution, saw {delta}"
    from ray_tpu._private.config import ray_config

    assert all(v <= ray_config.max_reconstruction_attempts
               for v in cluster.head._recon_attempts.values())


def test_actor_call_with_retry_budget_survives_node_death(cluster):
    """Acceptance: a call with max_task_retries > 0 whose node dies
    MID-CALL returns the retried result — not ActorDiedError."""
    node = cluster.add_node(num_cpus=2)
    node2 = cluster.add_node(num_cpus=2)
    assert node2

    @ray_tpu.remote(max_restarts=1, max_task_retries=2, num_cpus=2)
    class Slow:
        def work(self, delay):
            time.sleep(delay)
            return "made-it"

    actor = Slow.remote()
    assert ray_tpu.get(actor.work.remote(0.0), timeout=60) == "made-it"
    host = next(iter(cluster.head.actor_nodes.values()))

    ref = actor.work.remote(3.0)
    time.sleep(0.8)  # dispatched and running on `host`
    cluster.kill_node(host)
    # The call REPLAYS against the restarted actor on the survivor.
    assert ray_tpu.get(ref, timeout=120) == "made-it"


def test_actor_call_without_retry_budget_rejects_naming_it(cluster):
    """Acceptance: with retries exhausted the call rejects with an
    error naming the restart state and budget."""
    from ray_tpu.exceptions import ActorUnavailableError

    node = cluster.add_node(num_cpus=2)
    node2 = cluster.add_node(num_cpus=2)
    assert node2

    @ray_tpu.remote(max_restarts=1, num_cpus=2)  # max_task_retries=0
    class Slow:
        def work(self, delay):
            time.sleep(delay)
            return "made-it"

    actor = Slow.remote()
    assert ray_tpu.get(actor.work.remote(0.0), timeout=60) == "made-it"
    host = next(iter(cluster.head.actor_nodes.values()))

    ref = actor.work.remote(5.0)
    time.sleep(0.8)
    cluster.kill_node(host)
    with pytest.raises(ActorUnavailableError) as ei:
        ray_tpu.get(ref, timeout=120)
    assert "max_task_retries" in str(ei.value)


def test_tombstoned_actor_names_exhausted_budget(cluster):
    """Satellite regression: after the restart budget is exhausted,
    calls fail FAST with an ActorDiedError naming the budget — they
    must not dispatch into a backend that has never heard of the
    actor."""
    node = cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=2)  # max_restarts=0
    class A:
        def f(self):
            return 1

    from ray_tpu.exceptions import ActorDiedError

    a = A.remote()
    assert ray_tpu.get(a.f.remote(), timeout=60) == 1
    cluster.kill_node(node)
    _wait_for(lambda: not cluster.head.nodes[node].alive,
              msg="node death detection")

    with pytest.raises(ActorDiedError) as ei:
        ray_tpu.get(a.f.remote(), timeout=30)
    assert "max_restarts=0" in str(ei.value)


def test_release_propagates_to_owner_node(cluster):
    from ray_tpu._private.rpc import RpcClient

    node = cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=2)
    def produce():
        return list(range(1000))

    ref = produce.remote()
    assert len(ray_tpu.get(ref, timeout=60)) == 1000
    oid = ref.id
    record = cluster.head.nodes[node]
    _wait_for(lambda: RpcClient.to(record.address).call(
        "contains_object", oid=oid.binary()), msg="object on node")

    del ref
    _wait_for(lambda: not RpcClient.to(record.address).call(
        "contains_object", oid=oid.binary()),
        msg="release to reach the owner node")
    assert oid.binary() not in cluster.head.lineage
