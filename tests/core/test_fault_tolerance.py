"""Node-death fault tolerance: health checks, lineage reconstruction,
in-flight resubmission, actor restart across nodes, distributed release.

Reference test models: `python/ray/tests/test_reconstruction.py`,
`test_actor_failures.py`, the NodeKiller chaos fixture
(`python/ray/_private/test_utils.py:1347`).

These tests pin work to subprocess nodes with a custom resource the head
doesn't have, so killing the node provably kills the only copy.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture
def fast_health(monkeypatch):
    from ray_tpu._private.config import ray_config

    monkeypatch.setattr(ray_config, "health_check_period_s", 0.2)
    monkeypatch.setattr(ray_config, "health_check_failure_threshold", 2)
    yield ray_config


@pytest.fixture
def cluster(fast_health):
    c = Cluster(head_node_args={"num_cpus": 1})
    yield c
    c.shutdown()


def _wait_for(pred, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {msg}")


def test_object_reconstruction_on_node_kill(cluster):
    """Objects whose only copy died are re-created from lineage."""
    # simulate_remote_host: the node gets its own shm segment, so killing
    # it genuinely loses the object (a shared segment would survive).
    node = cluster.add_node(num_cpus=2, simulate_remote_host=True)

    @ray_tpu.remote(num_cpus=2)
    def produce(x):
        return {"value": x * 2, "pid": os.getpid()}

    ref = produce.remote(21)
    first = ray_tpu.get(ref, timeout=60)
    assert first["value"] == 42
    producer_pid = first["pid"]
    assert producer_pid != os.getpid()  # ran on the node, not the driver

    # Drop the driver's cached copy so the next get must re-fetch, then
    # kill the node without telling the head.
    cluster.driver_worker.memory_store.evict([ref.id])
    cluster.kill_node(node)
    node2 = cluster.add_node(num_cpus=2, simulate_remote_host=True)
    assert node2

    again = ray_tpu.get(ref, timeout=60)
    assert again["value"] == 42
    assert again["pid"] != producer_pid  # re-executed, not cached


def test_inflight_task_resubmitted_on_node_death(cluster):
    """A task running on a node that dies is re-executed elsewhere."""
    node = cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=2)
    def slow():
        time.sleep(3.0)
        return os.getpid()

    ref = slow.remote()
    time.sleep(0.8)  # let it dispatch and start
    cluster.kill_node(node)
    pid = ray_tpu.get(ref, timeout=90)
    assert pid != os.getpid()


def test_health_checker_marks_node_dead(cluster):
    node = cluster.add_node(num_cpus=1)
    assert cluster.head.nodes[node].alive
    cluster.kill_node(node)
    _wait_for(lambda: not cluster.head.nodes[node].alive,
              msg="health checker to mark node dead")


def test_actor_restart_on_node_death(cluster):
    node = cluster.add_node(num_cpus=2)
    node2 = cluster.add_node(num_cpus=2)
    assert node2

    @ray_tpu.remote(max_restarts=1, num_cpus=2)
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def pid(self):
            return os.getpid()

    c = Counter.remote()
    first_pid = ray_tpu.get(c.pid.remote(), timeout=60)
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1

    # Kill exactly the node hosting the actor (recorded BEFORE the kill:
    # reading it afterwards can race the health checker's restart and
    # kill the actor's *new* home too, exhausting the restart budget).
    host = next(iter(cluster.head.actor_nodes.values()))
    assert host in (node, node2)
    cluster.kill_node(host)
    _wait_for(lambda: not cluster.head.nodes[host].alive,
              msg="dead node detected")

    # After restart the actor lives on the surviving node with fresh
    # state (reference restart semantics: state is reconstructed by
    # rerunning __init__).
    def call_ok():
        try:
            return ray_tpu.get(c.incr.remote(), timeout=10) >= 1
        except Exception:
            return False

    _wait_for(call_ok, timeout=60, msg="actor restart")
    new_pid = ray_tpu.get(c.pid.remote(), timeout=30)
    assert new_pid != first_pid


def test_actor_without_restart_budget_dies(cluster):
    node = cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=2)  # max_restarts defaults to 0
    class A:
        def f(self):
            return 1

    a = A.remote()
    assert ray_tpu.get(a.f.remote(), timeout=60) == 1
    cluster.kill_node(node)
    _wait_for(lambda: not cluster.head.nodes[node].alive,
              msg="node death detection")
    with pytest.raises(Exception):
        ray_tpu.get(a.f.remote(), timeout=30)


def test_release_propagates_to_owner_node(cluster):
    from ray_tpu._private.rpc import RpcClient

    node = cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=2)
    def produce():
        return list(range(1000))

    ref = produce.remote()
    assert len(ray_tpu.get(ref, timeout=60)) == 1000
    oid = ref.id
    record = cluster.head.nodes[node]
    _wait_for(lambda: RpcClient.to(record.address).call(
        "contains_object", oid=oid.binary()), msg="object on node")

    del ref
    _wait_for(lambda: not RpcClient.to(record.address).call(
        "contains_object", oid=oid.binary()),
        msg="release to reach the owner node")
    assert oid.binary() not in cluster.head.lineage
