"""Distributed refcount / borrower protocol (reference:
reference_count.h borrowing): a driver release must not free an object
out from under a node that still holds a handle, nor from under an
in-flight task's args; the deferred free happens when the last holder
drops."""

import gc
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    yield c
    c.shutdown()


@ray_tpu.remote(num_cpus=2)
def _produce():
    return np.arange(2048)


@ray_tpu.remote(num_cpus=2)
class _Holder:
    def __init__(self):
        self.held = None

    def hold(self, ref_in_list):
        # Receiving a ref INSIDE a container keeps it unresolved: the
        # actor stores the handle, not the value (the borrow case).
        self.held = ref_in_list[0]
        return True

    def read(self):
        return int(ray_tpu.get(self.held).sum())

    def drop(self):
        self.held = None
        gc.collect()
        return True


def _hog(cluster):
    @ray_tpu.remote(num_cpus=2)
    def hog():
        time.sleep(1.0)
        return 1

    return hog.remote()


def test_borrowed_object_survives_driver_release(cluster):
    cluster.add_node(num_cpus=2)
    head = cluster.head

    h = _hog(cluster)  # push the producer + actor off-head
    ref = _produce.remote()
    holder = _Holder.remote()
    assert ray_tpu.get(holder.hold.remote([ref]), timeout=60)
    ray_tpu.get(h)

    oid = ref.binary()
    # Give the borrow registration a beat to land.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and oid not in head.borrowers:
        time.sleep(0.1)
    assert oid in head.borrowers, "node never registered as borrower"

    # Driver drops its handle; the object must survive for the actor.
    del ref
    gc.collect()
    time.sleep(0.5)  # release loop batches at 50ms
    assert oid in head.driver_released or oid in head.object_locations
    assert ray_tpu.get(holder.read.remote(), timeout=60) \
        == 2047 * 1024  # value intact after driver release

    # Actor drops the last handle → deferred free actually runs.
    assert ray_tpu.get(holder.drop.remote(), timeout=60)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and (
            oid in head.borrowers or oid in head.driver_released):
        time.sleep(0.1)
    assert oid not in head.borrowers
    assert oid not in head.driver_released, \
        "deferred free never executed"


def test_inflight_task_args_pinned_against_release(cluster):
    cluster.add_node(num_cpus=2)
    head = cluster.head

    @ray_tpu.remote(num_cpus=2)
    def slow_consume(arr):
        time.sleep(1.0)
        return int(arr.sum())

    h = _hog(cluster)
    ref = _produce.remote()
    out = slow_consume.remote(ref)
    # Drop the arg's driver handle while the consumer is in flight.
    del ref
    gc.collect()
    assert ray_tpu.get(out, timeout=60) == 2047 * 1024
    ray_tpu.get(h)
    # After completion nothing should stay pinned forever.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and head.task_pins:
        time.sleep(0.1)
    assert not head.task_pins


def test_nested_ref_arg_pinned_against_release(cluster):
    """A ref nested in a container arg is pinned at dispatch
    (nested_dependencies): `f.remote([r]); del r` must not race the
    release."""
    cluster.add_node(num_cpus=2)
    head = cluster.head

    @ray_tpu.remote(num_cpus=2)
    def consume_list(lst):
        time.sleep(0.6)
        return int(ray_tpu.get(lst[0]).sum())

    h = _hog(cluster)
    ref = _produce.remote()
    out = consume_list.remote([ref])
    del ref
    gc.collect()
    assert ray_tpu.get(out, timeout=60) == 2047 * 1024
    ray_tpu.get(h)


def test_driver_reacquire_cancels_deferred_release(cluster):
    """Driver drops its handle, an actor still borrows, then hands the
    ref back — the re-acquired driver handle must cancel the deferred
    release so the later borrower drop doesn't free it."""
    cluster.add_node(num_cpus=2)
    head = cluster.head

    @ray_tpu.remote(num_cpus=2)
    class Keeper:
        def __init__(self):
            self.held = None

        def hold(self, lst):
            self.held = lst[0]
            return True

        def give_back(self):
            return [self.held]

        def drop(self):
            self.held = None
            gc.collect()
            return True

    h = _hog(cluster)
    ref = _produce.remote()
    keeper = Keeper.remote()
    assert ray_tpu.get(keeper.hold.remote([ref]), timeout=60)
    ray_tpu.get(h)
    oid = ref.binary()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and oid not in head.borrowers:
        time.sleep(0.1)

    del ref
    gc.collect()
    time.sleep(0.5)

    # Driver re-acquires the same object's ref from the actor.
    ref_again = ray_tpu.get(keeper.give_back.remote(), timeout=60)[0]
    assert ref_again.binary() == oid
    time.sleep(0.3)
    assert oid not in head.driver_released

    # Actor drops; the driver's live handle must keep the object.
    assert ray_tpu.get(keeper.drop.remote(), timeout=60)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and oid in head.borrowers:
        time.sleep(0.1)
    assert int(ray_tpu.get(ref_again, timeout=60).sum()) == 2047 * 1024


def test_dead_borrower_unblocks_deferred_free(cluster):
    """A node holding the only borrow dies: its borrower entry must drop
    so the deferred free finally runs (no leak)."""
    node_id = cluster.add_node(num_cpus=2)
    head = cluster.head

    h = _hog(cluster)
    ref = _produce.remote()
    holder = _Holder.remote()
    assert ray_tpu.get(holder.hold.remote([ref]), timeout=60)
    ray_tpu.get(h)
    oid = ref.binary()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and oid not in head.borrowers:
        time.sleep(0.1)
    assert oid in head.borrowers

    del ref
    gc.collect()
    time.sleep(0.5)
    assert oid in head.driver_released  # deferred behind the borrow

    cluster.kill_node(node_id)
    head.mark_node_dead(node_id, reason="chaos")
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and (
            oid in head.borrowers or oid in head.driver_released):
        time.sleep(0.1)
    assert oid not in head.borrowers
    assert oid not in head.driver_released, "free leaked past node death"


def test_second_driver_handle_keeps_object(cluster):
    """Two driver handles to one object: dropping one must not release
    cluster-wide (the became-zero gate)."""
    import pickle

    cluster.add_node(num_cpus=2)
    head = cluster.head
    h = _hog(cluster)
    ref = _produce.remote()
    ray_tpu.wait([ref], timeout=60)
    ref2 = pickle.loads(pickle.dumps(ref))
    oid = ref.binary()
    del ref
    gc.collect()
    time.sleep(0.5)
    assert oid not in head.driver_released
    assert ray_tpu.get(ref2, timeout=60).sum() == 2047 * 1024
    ray_tpu.get(h)
