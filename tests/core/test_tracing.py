"""Distributed tracing spans: parent linkage across task/actor hops.

Reference role: OpenTelemetry span propagation (`tracing_helper.py`);
here span context rides the TaskSpec and exports OTLP-shaped JSON.
"""

import json

import pytest

import ray_tpu
from ray_tpu.experimental import tracing


@pytest.fixture
def ray_local():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_nested_task_spans_link(ray_local):
    @ray_tpu.remote
    def child(x):
        return x + 1

    @ray_tpu.remote
    def parent(x):
        return ray_tpu.get(child.remote(x)) * 10

    ref = parent.remote(1)
    assert ray_tpu.get(ref, timeout=60) == 20

    spans = tracing.export_spans()
    p = next(s for s in spans if s["name"].endswith(".parent"))
    c = next(s for s in spans if s["name"].endswith(".child"))
    # Root span: its own id is the trace id, no parent.
    assert p["traceId"] == p["spanId"] and p["parentSpanId"] is None
    # Child joins the parent's trace with correct linkage.
    assert c["traceId"] == p["spanId"]
    assert c["parentSpanId"] == p["spanId"]
    assert c["status"]["code"] == "STATUS_CODE_OK"

    trace = tracing.get_trace(p["traceId"])
    assert [s["name"].rsplit(".", 1)[-1] for s in trace] == \
        ["parent", "child"]


def test_actor_call_spans_link(ray_local):
    @ray_tpu.remote
    class A:
        def f(self, x):
            return x * 2

    @ray_tpu.remote
    def driver_task(handle):
        return ray_tpu.get(handle.f.remote(21))

    a = A.remote()
    assert ray_tpu.get(driver_task.remote(a), timeout=60) == 42
    spans = tracing.export_spans()
    task_span = next(s for s in spans if s["name"].endswith("driver_task"))
    method_span = next(s for s in spans if s["name"] == "A.f")
    assert method_span["traceId"] == task_span["traceId"]
    assert method_span["parentSpanId"] == task_span["spanId"]
    assert method_span["attributes"]["ray_tpu.task_kind"] == "ACTOR_TASK"


def test_error_span_status_and_save(ray_local, tmp_path):
    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("traced failure")

    with pytest.raises(ValueError):
        ray_tpu.get(boom.remote(), timeout=60)
    spans = tracing.export_spans()
    err = next(s for s in spans if s["name"].endswith("boom"))
    assert err["status"]["code"] == "STATUS_CODE_ERROR"
    assert "traced failure" in err["status"]["message"]

    path = tmp_path / "spans.json"
    n = tracing.save_spans(str(path))
    assert n == len(json.loads(path.read_text()))
