"""Tier-1 wire-protocol leg: the fifth analysis rung runs green on
every CI run, inside a hard wall-clock budget.

What the leg pins (the ISSUE's acceptance criteria):

- ``python -m tools.raywire`` exits 0 and writes the
  ``RAYWIRE_REPORT.json`` artifact at the repo root;
- extraction is clean (AST and live registry agree) and the committed
  ``RAYWIRE_SCHEMA.json`` baseline matches the checked-out wire.py —
  zero gate changes on an unchanged tree;
- the grammar-derived fuzz campaign drives >= 10k seeded inputs across
  all four targets (wire.decode, rpc framing, shard-row apply, proxy
  parser) with ZERO findings, zero time-bound breaches, and every
  allocation-bomb probe bounded;
- the per-message round-trip byte-identity suite and the minimized
  fixture corpus replay are folded into the same report and pass;
- a synthetic breaking change (field removed from a doctored baseline)
  makes the SAME command exit 1 naming the version-bump requirement —
  the gate demonstrably gates;
- the leg stays under 60s wall so it can live in tier-1 forever.
"""

import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_LEG_BUDGET_S = 60.0
_ARTIFACT = os.path.join(REPO_ROOT, "RAYWIRE_REPORT.json")
_BASELINE = os.path.join(REPO_ROOT, "RAYWIRE_SCHEMA.json")


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def _run(*extra):
    return subprocess.run(
        [sys.executable, "-m", "tools.raywire",
         "--report", "json", *extra],
        cwd=REPO_ROOT, env=_env(), capture_output=True, text=True,
        timeout=_LEG_BUDGET_S + 60)


def test_raywire_leg_clean_and_bounded():
    t0 = time.monotonic()
    out = _run("--fuzz", "10000", "--report-file", _ARTIFACT)
    wall = time.monotonic() - t0

    assert out.returncode == 0, (
        f"raywire leg failed (rc={out.returncode}):\n"
        f"{out.stdout}\n{out.stderr}")
    assert wall < _LEG_BUDGET_S, (
        f"raywire leg took {wall:.1f}s against a {_LEG_BUDGET_S:.0f}s "
        f"budget; shrink the campaign before shrinking coverage")

    report = json.loads(out.stdout)
    assert report["pass"] is True

    # Extraction: clean cross-check, the full registry covered.
    assert report["extraction"]["ok"] is True
    assert report["extraction"]["messages"] >= 7

    # Gate: an unchanged tree diffs to zero against the committed
    # baseline, and every message's skew simulation is compatible in
    # both directions.
    assert report["gate"]["ok"] is True
    assert report["gate"]["changes"] == []
    assert len(report["gate"]["skew"]) >= 7
    for name, skew in report["gate"]["skew"].items():
        assert skew["classified"] == "compatible", name
        assert skew["old_to_new"]["ok"] and skew["new_to_old"]["ok"], \
            name
        assert skew["byte_identity"] is True, name

    # Fuzz: the full seeded campaign, all targets and mutators
    # exercised, nothing escaped typed rejection, nothing slow,
    # allocation probes bounded.
    fz = report["fuzz"]
    assert fz["inputs"] >= 10000
    assert fz["findings"] == []
    assert fz["slow"] == []
    assert all(n > 0 for n in fz["per_target"].values())
    assert all(n > 0 for n in fz["per_mutator"].values())
    assert all(p["ok"] for p in fz["alloc_probes"])

    # Round-trip byte identity over every message; fixture corpus
    # replayed in full.
    assert report["roundtrip"]["ok"] is True
    assert report["roundtrip"]["checked"] >= 7 * 25
    assert report["fixtures"]["ok"] is True
    assert report["fixtures"]["replayed"] >= 15

    # The artifact the run wrote is the canonical committed form.
    assert os.path.exists(_ARTIFACT)
    with open(_ARTIFACT, "r", encoding="utf-8") as f:
        artifact = json.load(f)
    assert artifact["pass"] is True


def test_breaking_change_fixture_fails_the_gate(tmp_path):
    # Doctor the baseline so it carries a field the live code lacks —
    # exactly what the tree looks like the day after a careless field
    # removal ships. The same command must exit 1 naming the escape
    # hatch (version bump + migration note).
    with open(_BASELINE, "r", encoding="utf-8") as f:
        baseline = json.load(f)
    baseline["messages"]["rpc.Request"]["fields"].append(
        {"name": "legacy_token", "type": "bytes",
         "has_default": True})
    doctored = tmp_path / "RAYWIRE_SCHEMA.json"
    doctored.write_text(json.dumps(baseline))

    out = _run("--fuzz", "0", "--baseline", str(doctored))
    assert out.returncode == 1, out.stdout
    report = json.loads(out.stdout)
    assert report["gate"]["ok"] is False
    assert report["gate"]["breaking"] == ["rpc.Request"]
    kinds = {c["kind"] for c in report["gate"]["changes"]}
    assert "field_removed" in kinds
    assert any("version bump" in f for f in report["gate"]["failures"])
    # The skew evidence names the silent dataloss: old frames carry
    # legacy_token, the live receiver drops it.
    skew = report["gate"]["skew"]["rpc.Request"]
    assert skew["classified"] == "breaking"


def test_missing_baseline_is_a_usage_error(tmp_path):
    out = _run("--fuzz", "0",
               "--baseline", str(tmp_path / "nope.json"))
    assert out.returncode == 2
    assert "--write-baseline" in out.stderr
