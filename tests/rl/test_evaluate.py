"""Algorithm.evaluate + py_modules runtime env."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import DQNConfig, PPOConfig, SACConfig


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_evaluate_trained_ppo_beats_untrained():
    config = (PPOConfig()
              .environment("CartPole-v1")
              .rollouts(num_rollout_workers=2, num_envs_per_worker=8,
                        rollout_fragment_length=64)
              .training(lr=3e-4)
              .debugging(seed=0))
    algo = config.build()
    algo.setup({})
    before = algo.evaluate(num_episodes=3)["evaluation"]
    for _ in range(10):
        algo.train()
    after = algo.evaluate(num_episodes=3)["evaluation"]
    algo.cleanup()
    assert after["episode_reward_mean"] > before["episode_reward_mean"]
    assert after["episode_len_mean"] >= after["episode_reward_mean"] - 1


def test_evaluate_policy_shapes():
    # Q-network (DQN) and tanh-Gaussian (SAC) paths both evaluate.
    dqn = (DQNConfig().environment("CartPole-v1")
           .rollouts(num_rollout_workers=1,
                     rollout_fragment_length=16)).build()
    dqn.setup({})
    out = dqn.evaluate(num_episodes=2)["evaluation"]
    dqn.cleanup()
    assert out["episodes"] == 2 and out["episode_reward_mean"] > 0

    sac = (SACConfig().environment("Pendulum-v1")
           .rollouts(num_rollout_workers=1,
                     rollout_fragment_length=16)).build()
    sac.setup({})
    out = sac.evaluate(num_episodes=2,
                       max_steps_per_episode=50)["evaluation"]
    sac.cleanup()
    assert out["episode_reward_mean"] < 0  # pendulum costs


def test_py_modules_runtime_env(tmp_path):
    pkg = tmp_path / "my_plugin_pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("MAGIC = 1234\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(pkg)]})
    def use_plugin():
        import my_plugin_pkg

        return my_plugin_pkg.MAGIC

    assert ray_tpu.get(use_plugin.remote()) == 1234

    # Outside the env the module is NOT importable.
    @ray_tpu.remote
    def no_plugin():
        try:
            import my_plugin_pkg  # noqa: F401

            return "importable"
        except ImportError:
            return "absent"

    import sys

    sys.modules.pop("my_plugin_pkg", None)
    assert ray_tpu.get(no_plugin.remote()) == "absent"
