"""Learner / LearnerGroup / LearnerThread (reference
`rllib/core/learner/learner_group.py:51`,
`rllib/execution/learner_thread.py:1`)."""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import ray_tpu
from ray_tpu.rl import models
from ray_tpu.rl.learner import Learner, LearnerGroup, LearnerThread


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def _loss(params, batch):
    logits, values = jax.vmap(
        lambda o: models.actor_critic_apply(params, o))(batch["obs"])
    logp = jax.nn.log_softmax(logits)
    pick = jnp.take_along_axis(
        logp, batch["actions"][..., None], axis=-1)[..., 0]
    loss = -(pick * batch["adv"]).mean() + 0.5 * (values ** 2).mean()
    return loss, {"pi": -(pick * batch["adv"]).mean()}


def _make(seed=0):
    params = models.actor_critic_init(jax.random.PRNGKey(seed), 6, 3)
    tx = optax.adam(1e-3)
    return params, tx


def _batch(rng, n=16, t=8):
    return {
        "obs": rng.normal(size=(n, t, 6)).astype(np.float32),
        "actions": rng.randint(0, 3, size=(n, t)).astype(np.int64),
        "adv": rng.normal(size=(n, t)).astype(np.float32),
    }


def test_mesh_sharded_update_matches_unsharded():
    """The pjit-sharded step (batch over the 8-device 'data' axis, XLA
    gradient all-reduce) must produce the same parameters as the plain
    single-device step — DDP as a compiler rewrite, not a protocol."""
    from jax.sharding import Mesh

    rng = np.random.RandomState(0)
    batches = [_batch(rng) for _ in range(3)]

    params, tx = _make()
    plain = Learner.from_loss(_loss, params, tx)
    for b in batches:
        plain.update(b)

    params2, tx2 = _make()
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    sharded = Learner.from_loss(_loss, params2, tx2, mesh=mesh)
    for b in batches:
        sharded.update(b)

    a = jax.device_get(plain.get_weights())
    b = jax.device_get(sharded.get_weights())
    flat_a = jax.tree_util.tree_leaves(a)
    flat_b = jax.tree_util.tree_leaves(b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(x, np.asarray(y), rtol=2e-4,
                                   atol=2e-5)


def test_actor_sharded_group_matches_local():
    """num_learners=2 (gradient all-reduce through util.collective) must
    track the local full-batch learner."""
    rng = np.random.RandomState(1)
    batches = [_batch(rng, n=8) for _ in range(3)]

    params, tx = _make()
    local = LearnerGroup(
        learner=Learner.from_loss(_loss, params, tx))
    import functools

    remote = LearnerGroup(
        build_learner=functools.partial(_build_learner, 0),
        num_learners=2)
    for b in batches:
        s1 = local.update(b)
        s2 = remote.update(b)
        assert set(s1) == set(s2)
    a = jax.tree_util.tree_leaves(jax.device_get(local.get_weights()))
    b = jax.tree_util.tree_leaves(remote.get_weights())
    for x, y in zip(a, b):
        np.testing.assert_allclose(x, np.asarray(y), rtol=2e-4,
                                   atol=2e-5)
    remote.shutdown()


def _build_learner(seed):
    params, tx = _make(seed)
    return Learner.from_loss(_loss, params, tx)


def test_learner_thread_consumes_and_accounts():
    params, tx = _make()
    learner = Learner.from_loss(_loss, params, tx)
    w0 = jax.device_get(learner.get_weights())
    thread = LearnerThread(learner, in_queue_size=4, num_sgd_iter=2,
                           barrier_every=4)
    thread.start()
    rng = np.random.RandomState(2)
    for _ in range(6):
        thread.put(_batch(rng))
    deadline = time.time() + 30
    while thread.updates < 12 and time.time() < deadline:
        time.sleep(0.05)
    thread.stop()
    stats = thread.stats()
    assert stats["learner_updates"] == 12
    # 6 batches x 16 x 8 transitions x 2 sgd iters
    assert stats["learner_samples_consumed"] == 6 * 16 * 8 * 2
    assert stats["learner_busy_s"] > 0
    assert 0 < stats["device_busy_fraction"] <= 1.0
    w1 = jax.device_get(thread.get_weights())
    assert not np.allclose(
        jax.tree_util.tree_leaves(w0)[0],
        jax.tree_util.tree_leaves(w1)[0])


def test_impala_learner_thread_end_to_end():
    """IMPALA with the async learner thread: sampling and learning
    overlap; stats expose the device-busy split."""
    from ray_tpu.rl import IMPALAConfig

    config = (IMPALAConfig()
              .environment("CartPole-v1")
              .rollouts(num_rollout_workers=2, num_envs_per_worker=4,
                        rollout_fragment_length=32)
              .training(lr=1e-3, updates_per_iter=6)
              .learners(use_learner_thread=True, num_sgd_iter=2,
                        learner_queue_size=4)
              .debugging(seed=0))
    algo = config.build()
    for _ in range(2):
        result = algo.train()
    algo.cleanup()
    assert result["learner_updates"] >= 12
    assert result["learner_samples_consumed"] > 0
    assert "device_busy_fraction" in result
    assert result["num_env_steps_sampled_this_iter"] > 0


def test_impala_pixel_env_cnn():
    """CatchPixels obs [H,W,C] routes to the conv torso and learns the
    trivial catch task a bit."""
    from ray_tpu.rl import IMPALAConfig

    config = (IMPALAConfig()
              .environment("CatchPixels-v0")
              .rollouts(num_rollout_workers=1, num_envs_per_worker=8,
                        rollout_fragment_length=40)
              .training(lr=1e-3, updates_per_iter=2)
              .debugging(seed=0))
    algo = config.build()
    result = algo.train()
    assert algo.apply_fn is models.cnn_actor_critic_apply
    algo.cleanup()
    assert "pi_loss" in result
    assert result["num_env_steps_sampled_this_iter"] > 0


def test_appo_mesh_sharded_learner():
    """APPO on the virtual 8-device mesh: target-net state and counter
    ride inside the sharded program."""
    from ray_tpu.rl import APPOConfig

    config = (APPOConfig()
              .environment("CartPole-v1")
              .rollouts(num_rollout_workers=1, num_envs_per_worker=8,
                        rollout_fragment_length=16)
              .training(lr=1e-3, updates_per_iter=2)
              .learners(num_devices_per_learner=8)
              .debugging(seed=0))
    algo = config.build()
    result = algo.train()
    w = algo.get_weights()
    assert "target" in w
    algo.cleanup()
    assert "mean_ratio" in result
