"""RND curiosity (reference `rllib/utils/exploration/` family): novel
observations earn larger bonuses than familiar ones, the bonus decays
with repeated exposure, and the DQN integration mixes it into replay
rewards."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import DQNConfig, RNDModule


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_rnd_bonus_decays_with_familiarity():
    rnd = RNDModule(obs_dim=4, seed=0)
    rng = np.random.RandomState(0)
    familiar = rng.randn(64, 4).astype(np.float32)
    # Train on the familiar region repeatedly.
    for _ in range(50):
        rnd.bonus(familiar)
    b_familiar = rnd.bonus(familiar).mean()
    # A far-away novel region must earn a clearly larger bonus.
    novel = familiar + 8.0
    b_novel = rnd.bonus(novel).mean()
    assert b_novel > 2.0 * b_familiar, (b_familiar, b_novel)


def test_rnd_state_roundtrip():
    rnd = RNDModule(obs_dim=3, seed=1)
    obs = np.random.RandomState(1).randn(16, 3).astype(np.float32)
    for _ in range(5):
        rnd.bonus(obs)
    st = rnd.state()
    rnd2 = RNDModule(obs_dim=3, seed=1)
    rnd2.set_state(st)
    np.testing.assert_allclose(np.asarray(rnd.bonus(obs)),
                               np.asarray(rnd2.bonus(obs)), rtol=1e-4)


def test_dqn_with_rnd_exploration():
    config = (DQNConfig()
              .environment("CartPole-v1")
              .rollouts(num_rollout_workers=1, num_envs_per_worker=2,
                        rollout_fragment_length=32)
              .training(learning_starts=64, train_batch_size=32,
                        num_sgd_per_iter=4, exploration="rnd",
                        rnd_coef=0.2)
              .debugging(seed=0))
    algo = config.build()
    result = None
    for _ in range(4):
        result = algo.train()
    algo.cleanup()
    assert "mean_intrinsic_bonus" in result
    assert np.isfinite(result["mean_intrinsic_bonus"])
    assert result["mean_intrinsic_bonus"] > 0
    assert result["buffer_size"] > 64
