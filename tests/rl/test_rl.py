"""RL tests: envs, buffers, GAE/V-trace math, and learning smoke tests."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import (
    CartPoleEnv,
    DQNConfig,
    IMPALAConfig,
    PPOConfig,
    PrioritizedReplayBuffer,
    ReplayBuffer,
    SampleBatch,
    VectorEnv,
)


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_cartpole_env():
    env = CartPoleEnv()
    obs, _ = env.reset(seed=0)
    assert obs.shape == (4,)
    total = 0.0
    for _ in range(10):
        obs, r, term, trunc, _ = env.step(env.action_space.sample(
            np.random.RandomState(0)))
        total += r
        if term or trunc:
            break
    assert total > 0


def test_vector_env():
    vec = VectorEnv("CartPole-v1", 4)
    obs = vec.reset(seed=0)
    assert obs.shape == (4, 4)
    obs, rews, terms, truncs = vec.step(np.zeros(4, np.int64))
    assert rews.shape == (4,)


def test_replay_buffers():
    buf = ReplayBuffer(capacity=100)
    batch = SampleBatch({"obs": np.random.rand(150, 4),
                         "rew": np.arange(150, dtype=np.float32)})
    buf.add(batch)
    assert len(buf) == 100
    s = buf.sample(32)
    assert s["obs"].shape == (32, 4)

    pbuf = PrioritizedReplayBuffer(capacity=64)
    pbuf.add(SampleBatch({"obs": np.random.rand(32, 4)}))
    s = pbuf.sample(8)
    assert "weights" in s and "batch_indexes" in s
    pbuf.update_priorities(s["batch_indexes"], np.ones(8) * 5)


def test_gae_math():
    from ray_tpu.rl.algorithms.ppo import compute_gae

    rewards = np.array([[1.0, 1.0, 1.0]])
    values = np.array([[0.5, 0.5, 0.5]])
    dones = np.array([[False, False, True]])
    adv, targets = compute_gae(rewards, values, dones,
                               np.array([9.9]), gamma=1.0, lam=1.0)
    # terminal step: delta = 1 - 0.5 = 0.5 (bootstrap masked)
    np.testing.assert_allclose(adv[0, 2], 0.5)
    np.testing.assert_allclose(adv[0, 0], 1 + 1 + 1 - 0.5)
    np.testing.assert_allclose(targets, adv + values)


def test_vtrace_on_policy_reduces_to_gae_lambda1():
    """With behaviour == target policy, rho = 1 and V-trace targets equal
    the lambda=1 return."""
    import jax.numpy as jnp

    from ray_tpu.rl.algorithms.impala import vtrace

    logp = jnp.log(jnp.full((1, 3), 0.5))
    rewards = jnp.ones((1, 3))
    values = jnp.array([[0.5, 0.5, 0.5]])
    dones = jnp.zeros((1, 3), bool)
    bootstrap = jnp.array([2.0])
    vs, pg = vtrace(logp, logp, rewards, values, bootstrap, dones,
                    gamma=1.0, clip_rho=1.0, clip_c=1.0)
    np.testing.assert_allclose(np.asarray(vs[0, 0]), 1 + 1 + 1 + 2.0,
                               rtol=1e-6)


def test_ppo_learns_cartpole():
    config = (PPOConfig()
              .environment("CartPole-v1")
              .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                        rollout_fragment_length=128)
              .training(lr=3e-3, num_sgd_iter=8, sgd_minibatch_size=128,
                        entropy_coeff=0.01)
              .debugging(seed=0))
    algo = config.build()
    rewards = []
    for _ in range(16):
        result = algo.train()
        rewards.append(result.get("episode_reward_mean", 0.0))
    algo.cleanup()
    assert max(rewards) > 60, rewards
    # And it actually improved substantially over the run.
    assert max(rewards) > 3 * rewards[0], rewards


def test_dqn_smoke():
    config = (DQNConfig()
              .environment("CartPole-v1")
              .rollouts(num_rollout_workers=1, num_envs_per_worker=2,
                        rollout_fragment_length=64)
              .training(lr=1e-3, learning_starts=128,
                        num_sgd_per_iter=16)
              .debugging(seed=0))
    algo = config.build()
    for _ in range(4):
        result = algo.train()
    algo.cleanup()
    assert result["buffer_size"] > 128
    assert result["mean_td_loss"] is not None


def test_impala_smoke():
    config = (IMPALAConfig()
              .environment("CartPole-v1")
              .rollouts(num_rollout_workers=2, num_envs_per_worker=1,
                        rollout_fragment_length=64)
              .training(lr=1e-3, updates_per_iter=4)
              .debugging(seed=0))
    algo = config.build()
    for _ in range(3):
        result = algo.train()
    algo.cleanup()
    assert "pi_loss" in result
    assert result["num_env_steps_sampled_this_iter"] > 0


def test_algorithm_checkpoint_roundtrip():
    config = (PPOConfig().environment("CartPole-v1")
              .rollouts(num_rollout_workers=1,
                        rollout_fragment_length=32))
    algo = config.build()
    algo.train()
    ckpt = algo.save_checkpoint()
    algo2 = (PPOConfig().environment("CartPole-v1")
             .rollouts(num_rollout_workers=1,
                       rollout_fragment_length=32)).build()
    algo2.setup({})
    algo2.load_checkpoint(ckpt)
    import jax

    for a, b in zip(jax.tree.leaves(algo.get_weights()),
                    jax.tree.leaves(algo2.get_weights())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    algo.cleanup()
    algo2.cleanup()
