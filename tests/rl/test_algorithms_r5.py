"""Round-5 RL breadth: recurrent policies + R2D2, CQL, QMIX, ES/ARS.

Reference specs: `rllib/algorithms/r2d2/`, `cql/`, `qmix/`, `es/`,
`ars/`. Each algorithm gets a mechanics test plus a learning-curve /
defining-property test (R2D2 on the partially-observable
StatelessCartPole; CQL's conservative Q property; QMIX on a
coordination game; ES improving CartPole)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import (
    ARSConfig,
    CQLConfig,
    ESConfig,
    JsonWriter,
    MultiAgentEnv,
    QMIXConfig,
    R2D2Config,
    SampleBatch,
    SequenceReplayBuffer,
    StatelessCartPoleEnv,
)


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


# -- recurrent building blocks ----------------------------------------------

def test_recurrent_unroll_matches_stepwise():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rl import models

    params = models.recurrent_q_init(jax.random.PRNGKey(0), 3, 2,
                                     hidden=8)
    obs = jax.random.normal(jax.random.PRNGKey(1), (4, 6, 3))
    h = jnp.zeros((4, 8))
    q_seq, h_final = models.recurrent_q_unroll(params, obs, h)
    # Step-by-step must agree with the scanned unroll.
    h2 = jnp.zeros((4, 8))
    for t in range(6):
        q_t, h2 = models.recurrent_q_step(params, obs[:, t], h2)
        np.testing.assert_allclose(np.asarray(q_seq[:, t]),
                                   np.asarray(q_t), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(h_final), np.asarray(h2),
                               rtol=1e-5)


def test_recurrent_unroll_resets_on_done():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rl import models

    params = models.recurrent_q_init(jax.random.PRNGKey(0), 3, 2,
                                     hidden=8)
    obs = jax.random.normal(jax.random.PRNGKey(1), (1, 6, 3))
    dones = jnp.zeros((1, 6)).at[0, 2].set(1.0)
    q_seq, _ = models.recurrent_q_unroll(params, obs, jnp.zeros((1, 8)),
                                         dones=dones)
    # Steps after the done must match a fresh unroll from zero state.
    q_fresh, _ = models.recurrent_q_unroll(params, obs[:, 3:],
                                           jnp.zeros((1, 8)))
    np.testing.assert_allclose(np.asarray(q_seq[:, 3:]),
                               np.asarray(q_fresh), rtol=1e-5)


def test_rollout_worker_recurrent_state_column():
    import jax

    from ray_tpu.rl import models as rl_models
    from ray_tpu.rl.rollout_worker import RolloutWorker

    params = rl_models.recurrent_q_init(jax.random.PRNGKey(0), 2, 2,
                                        hidden=8)

    def behaviour(p, obs, h):
        import jax.numpy as jnp
        q, h_next = rl_models.recurrent_q_step(p, obs, h)
        return jnp.log(jax.nn.softmax(q) + 1e-9), h_next

    w = RolloutWorker.remote(
        "StatelessCartPole-v0", behaviour, num_envs=2,
        rollout_fragment_length=40, seed=0, policy_kind="recurrent",
        state_size=8)
    batch = ray_tpu.get(w.sample.remote(params))
    state_in = np.asarray(batch["state_in"])
    assert state_in.shape == (2, 40, 8)
    # t=0 state is zeros; once the GRU runs it becomes non-zero...
    assert np.allclose(state_in[:, 0], 0.0)
    assert np.abs(state_in[:, 1]).sum() > 0
    # ...and resets to zero right after every done.
    dones = np.asarray(batch["dones"])
    for n in range(2):
        for t in np.nonzero(dones[n][:-1])[0]:
            assert np.allclose(state_in[n, t + 1], 0.0)


def test_sequence_replay_buffer_chops_and_stores_state():
    buf = SequenceReplayBuffer(capacity=64, seq_len=4, burn_in=2, seed=0)
    t, h = 14, 3
    batch = SampleBatch({
        "obs": np.arange(t, dtype=np.float32).reshape(1, t, 1),
        "actions": np.zeros((1, t), np.int64),
        "rewards": np.ones((1, t), np.float32),
        "dones": np.zeros((1, t), bool),
        "terminateds": np.zeros((1, t), bool),
        "next_obs": np.arange(1, t + 1, dtype=np.float32).reshape(
            1, t, 1),
        "state_in": np.tile(np.arange(t, dtype=np.float32)[None, :, None],
                            (1, 1, h)),
    })
    buf.add(batch)
    # windows of L=6 at stride 4 over T=14 -> starts at 0, 4, 8.
    assert len(buf) == 3
    out = buf.sample(3)
    assert out["obs"].shape == (3, 6, 1)
    # stored initial state equals state_in at the window start.
    starts = out["obs"][:, 0, 0]
    np.testing.assert_allclose(out["state0"][:, 0], starts)
    # priority update skews the sampling distribution toward seq 0.
    buf.update_priorities([0, 1, 2], [10.0, 0.001, 0.001])
    counts = np.zeros(3)
    for _ in range(60):
        s = buf.sample(1)
        counts[s["batch_indexes"][0]] += 1
    assert counts[0] > 45, counts


def test_r2d2_learns_memory_task():
    """The defining recurrence test: a T-maze-style cue-recall env
    where ANY memoryless policy is capped at 0.5 expected reward and a
    policy that carries the t=0 cue through its hidden state scores
    1.0. R2D2 must blow through the memoryless bound — proof the GRU
    state, stored-state replay, and burn-in all work end to end."""
    config = (R2D2Config()
              .environment("MemoryCue-v0")
              .rollouts(num_rollout_workers=2, num_envs_per_worker=4,
                        rollout_fragment_length=64)
              .training(lr=1e-3, train_batch_size=32,
                        num_sgd_per_iter=8, seq_len=8, burn_in=4,
                        n_step=1, epsilon_timesteps=4000,
                        target_update_freq=500)
              .debugging(seed=0))
    algo = config.build()
    result = None
    for _ in range(12):
        result = algo.train()
    ev = algo.evaluate(num_episodes=10,
                       max_steps_per_episode=10)["evaluation"]
    algo.cleanup()
    assert result["buffer_sequences"] > 100
    assert result["mean_td_loss"] is not None
    # 0.5 is the information-theoretic memoryless ceiling; require the
    # recurrent policy to be near-perfect, far beyond it.
    assert ev["episode_reward_mean"] >= 0.9, ev


def _pendulum_offline_dataset(path, n_fragments=30):
    """Mediocre-but-informative Pendulum data: a damping controller with
    exploration noise, recorded in the squashed [-1, 1] convention."""
    from ray_tpu.rl import PendulumEnv

    env = PendulumEnv()
    w = JsonWriter(path)
    rng = np.random.RandomState(0)
    for frag in range(n_fragments):
        obs, _ = env.reset(seed=frag)
        rows = {"obs": [], "actions": [], "rewards": [],
                "terminateds": [], "dones": [], "next_obs": []}
        for _ in range(64):
            # damping control: torque opposing angular velocity
            a = np.clip(-0.5 * obs[2] + rng.randn() * 0.4, -1, 1)
            nobs, r, term, trunc, _ = env.step(
                np.array([a * 2.0]))  # env scale [-2, 2]
            rows["obs"].append(obs)
            rows["actions"].append([a])
            rows["rewards"].append(r)
            rows["terminateds"].append(term)
            rows["dones"].append(term or trunc)
            rows["next_obs"].append(nobs)
            obs = nobs
            if term or trunc:
                obs, _ = env.reset(seed=1000 + frag)
        w.write(SampleBatch({
            "obs": np.asarray(rows["obs"], np.float32),
            "actions": np.asarray(rows["actions"], np.float32),
            "rewards": np.asarray(rows["rewards"], np.float32),
            "terminateds": np.asarray(rows["terminateds"]),
            "dones": np.asarray(rows["dones"]),
            "next_obs": np.asarray(rows["next_obs"], np.float32),
        }))
    w.close()


def _cql_action_gap(algo) -> float:
    """Mean Q(dataset action) - Q(random action) on held-out rows."""
    import jax.numpy as jnp

    from ray_tpu.rl import models as rl_models

    ds = algo._dataset
    idx = np.arange(0, len(ds["rewards"]), 7)[:128]
    obs = jnp.asarray(ds["obs"][idx])
    a_data = jnp.asarray(ds["actions"][idx])
    rng = np.random.RandomState(3)
    a_rand = jnp.asarray(rng.uniform(-1, 1, a_data.shape)
                         .astype(np.float32))
    critic = algo.params["critic"]
    q_data = np.asarray(jnp.minimum(
        *rl_models.q_sa_apply(critic, obs, a_data)))
    q_rand = np.asarray(jnp.minimum(
        *rl_models.q_sa_apply(critic, obs, a_rand)))
    return float(q_data.mean() - q_rand.mean())


def test_cql_conservative_q_property(tmp_path):
    """The defining CQL property, tested DIFFERENTIALLY: with the
    CQL(H) penalty on, Q(dataset actions) ends up above Q(random OOD
    actions); with cql_alpha=0 (plain offline SAC, the ablation) it
    does not. The penalty is what creates the conservative gap."""
    _pendulum_offline_dataset(str(tmp_path))

    def train(alpha):
        config = (CQLConfig()
                  .environment("Pendulum-v1")
                  .offline_data(input_=str(tmp_path))
                  .training(cql_alpha=alpha, bc_iters=64,
                            train_batch_size=128, num_sgd_per_iter=64)
                  .debugging(seed=0))
        algo = config.build()
        result = None
        for _ in range(15):
            result = algo.train()
        return algo, result

    algo_cql, result = train(10.0)
    assert np.isfinite(result["critic_loss"])
    assert np.isfinite(result["cql_penalty"])
    assert result["bc_phase"] == 0.0  # warm-start finished
    gap_cql = _cql_action_gap(algo_cql)
    algo_cql.cleanup()

    algo_base, _ = train(0.0)
    gap_base = _cql_action_gap(algo_base)
    algo_base.cleanup()

    assert gap_cql > 0.0, (gap_cql, gap_base)
    assert gap_cql > gap_base + 0.1, (gap_cql, gap_base)


class _ContextCoordinationEnv(MultiAgentEnv):
    """Two agents see a shared one-hot context c in {0, 1}; team reward
    is 1.0 only if BOTH play action c (independent greedy learners get
    ~0.25 from uncoordinated play; QMIX's factored Q finds the joint
    optimum). Episodes are 10 steps with fresh contexts each step."""

    agent_ids = ["a0", "a1"]

    def __init__(self, _cfg=None):
        from ray_tpu.rl.env import Box, Discrete

        self.observation_space = Box(0.0, 1.0, shape=(2,))
        self.action_space = Discrete(2)
        self._rng = np.random.RandomState(0)
        self._t = 0
        self._ctx = 0

    def _obs(self):
        o = np.zeros(2, np.float32)
        o[self._ctx] = 1.0
        return {a: o.copy() for a in self.agent_ids}

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._t = 0
        self._ctx = int(self._rng.randint(2))
        return self._obs(), {}

    def step(self, action_dict):
        r = 1.0 if all(action_dict[a] == self._ctx
                       for a in self.agent_ids) else 0.0
        self._t += 1
        done = self._t >= 10
        self._ctx = int(self._rng.randint(2))
        rewards = {a: r / 2 for a in self.agent_ids}
        terms = {a: False for a in self.agent_ids}
        terms["__all__"] = done
        truncs = {a: False for a in self.agent_ids}
        truncs["__all__"] = False
        return self._obs(), rewards, terms, truncs, {}


def test_qmix_learns_coordination():
    config = (QMIXConfig()
              .environment(_ContextCoordinationEnv)
              .rollouts(num_rollout_workers=1,
                        rollout_fragment_length=50)
              .training(lr=5e-3, train_batch_size=64,
                        num_sgd_per_iter=16, learning_starts=100,
                        epsilon_timesteps=1500, target_update_freq=200)
              .debugging(seed=0))
    algo = config.build()
    rewards = []
    for _ in range(40):
        result = algo.train()
        rewards.append(result.get("episode_reward_mean", 0.0))
    # Greedy joint action matches the context in both contexts.
    env = _ContextCoordinationEnv()
    ok = 0
    for seed in range(10):
        obs, _ = env.reset(seed=seed)
        ctx = int(np.argmax(obs["a0"]))
        acts = algo.compute_joint_action(obs)
        ok += int(all(a == ctx for a in acts.values()))
    algo.cleanup()
    # optimum is 10.0/episode; random play gives ~2.5
    assert max(rewards) > 6.0, rewards
    assert ok >= 8, ok


def test_es_improves_cartpole():
    config = (ESConfig()
              .environment("CartPole-v1")
              .rollouts(num_rollout_workers=4)
              .training(pop_size=16, noise_std=0.1, step_size=0.1,
                        max_episode_steps=200, hidden=(16,))
              .debugging(seed=0))
    algo = config.build()
    means = []
    for _ in range(15):
        result = algo.train()
        means.append(result["episode_reward_mean"])
    algo.cleanup()
    assert result["generation"] == 15
    assert result["num_env_steps_sampled_this_iter"] > 0
    # ES on CartPole: mean return over the population clearly improves.
    assert max(means) > 2.0 * max(means[0], 15.0), means


def test_ars_runs_and_improves():
    config = (ARSConfig()
              .environment("CartPole-v1")
              .rollouts(num_rollout_workers=2)
              .training(pop_size=8, noise_std=0.15, step_size=0.15,
                        top_frac=0.5, max_episode_steps=200)
              .debugging(seed=1))
    algo = config.build()
    means = []
    for _ in range(12):
        result = algo.train()
        means.append(result["episode_reward_mean"])
    algo.cleanup()
    assert max(means) > 1.5 * max(means[0], 15.0), means
