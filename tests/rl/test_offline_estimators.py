"""Off-policy estimators (reference `rllib/offline/estimators/`):
IS/WIS recover the behavior value when target == behavior, and move the
estimate in the right direction when the target prefers better
actions."""

import numpy as np

import jax

from ray_tpu.rl import (
    DirectMethod,
    ImportanceSampling,
    SampleBatch,
    WeightedImportanceSampling,
)
from ray_tpu.rl import models as rl_models


def _bandit_batch(params, n_episodes=400, seed=0):
    """1-step 'bandit': obs ~ N(0,1)^4, two actions, reward = 1 for
    action 1, 0.2 for action 0. Behavior = softmax policy given by
    `params` (so LOGPS is exact)."""
    rng = np.random.RandomState(seed)
    obs = rng.randn(n_episodes, 4).astype(np.float32)
    logits, _ = rl_models.actor_critic_apply(params, obs)
    logits = np.asarray(logits, np.float64)
    probs = np.exp(logits - logits.max(1, keepdims=True))
    probs /= probs.sum(1, keepdims=True)
    acts = (rng.rand(n_episodes) < probs[:, 1]).astype(np.int64)
    logp = np.log(probs[np.arange(n_episodes), acts])
    rew = np.where(acts == 1, 1.0, 0.2).astype(np.float32)
    return SampleBatch({
        "obs": obs,
        "actions": acts,
        "rewards": rew,
        "dones": np.ones(n_episodes, bool),
        "action_logp": logp.astype(np.float32),
    })


def test_is_wis_identity_when_target_equals_behavior():
    params = rl_models.actor_critic_init(jax.random.PRNGKey(0), 4, 2)
    batch = _bandit_batch(params)
    for cls in (ImportanceSampling, WeightedImportanceSampling):
        est = cls(rl_models.actor_critic_apply, params, gamma=1.0)
        out = est.estimate(batch)
        assert out["episodes"] == 400
        # identical policies: target estimate ~= behavior value
        assert abs(out["v_target"] - out["v_behavior"]) < 0.08, out


def test_is_detects_better_target_policy():
    behavior = rl_models.actor_critic_init(jax.random.PRNGKey(0), 4, 2)
    batch = _bandit_batch(behavior)
    # Target strongly prefers the good action (bias its pi head).
    target = {
        "pi": [dict(l) for l in behavior["pi"]],
        "vf": behavior["vf"],
    }
    import jax.numpy as jnp

    last = dict(target["pi"][-1])
    last["b"] = last["b"] + jnp.asarray([-3.0, 3.0])
    target["pi"][-1] = last
    for cls in (ImportanceSampling, WeightedImportanceSampling):
        est = cls(rl_models.actor_critic_apply, target, gamma=1.0)
        out = est.estimate(batch)
        # good action pays 1.0: the target's estimated value must beat
        # the behavior's and approach 1.0
        assert out["v_target"] > out["v_behavior"] + 0.1, (cls, out)
        assert out["v_target"] <= 1.2  # clip keeps it sane


def test_direct_method_uses_value_head():
    params = rl_models.actor_critic_init(jax.random.PRNGKey(1), 4, 2)
    batch = _bandit_batch(params, n_episodes=50)
    out = DirectMethod(rl_models.actor_critic_apply, params,
                       gamma=1.0).estimate(batch)
    assert out["episodes"] == 50
    assert np.isfinite(out["v_target"])


def test_empty_batch_is_a_clear_error():
    import pytest

    params = rl_models.actor_critic_init(jax.random.PRNGKey(0), 4, 2)
    empty = SampleBatch({"obs": np.zeros((0, 4), np.float32),
                         "actions": np.zeros(0, np.int64),
                         "rewards": np.zeros(0, np.float32),
                         "dones": np.zeros(0, bool),
                         "action_logp": np.zeros(0, np.float32)})
    for cls in (ImportanceSampling, WeightedImportanceSampling,
                DirectMethod):
        with pytest.raises(ValueError, match="empty batch"):
            cls(rl_models.actor_critic_apply, params).estimate(empty)


def test_multi_step_episode_split():
    """Episode splitting + discounting across multi-step episodes."""
    params = rl_models.actor_critic_init(jax.random.PRNGKey(0), 4, 2)
    obs = np.zeros((6, 4), np.float32)
    batch = SampleBatch({
        "obs": obs,
        "actions": np.zeros(6, np.int64),
        "rewards": np.ones(6, np.float32),
        "dones": np.array([0, 0, 1, 0, 0, 1], bool),
        "action_logp": np.full(6, -0.693, np.float32),
    })
    est = ImportanceSampling(rl_models.actor_critic_apply, params,
                             gamma=0.5)
    out = est.estimate(batch)
    assert out["episodes"] == 2
    # v_behavior = 1 + 0.5 + 0.25 per episode
    assert abs(out["v_behavior"] - 1.75) < 1e-6
