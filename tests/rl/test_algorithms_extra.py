"""Tests for the round-3 RL breadth: Pendulum env, SAC, A2C, offline
IO (JsonWriter/JsonReader), BC/MARWIL, and connector pipelines."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import (
    A2CConfig,
    BCConfig,
    ClipObs,
    ConnectorPipeline,
    FlattenObs,
    JsonReader,
    JsonWriter,
    MARWILConfig,
    NormalizeObs,
    PendulumEnv,
    SACConfig,
    SampleBatch,
    UnsquashAction,
)


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_pendulum_env():
    env = PendulumEnv()
    obs, _ = env.reset(seed=0)
    assert obs.shape == (3,)
    assert env.action_space.shape == (1,)
    total = 0.0
    for _ in range(10):
        obs, r, term, trunc, _ = env.step(np.array([0.5]))
        assert not term
        total += r
    assert total < 0  # pendulum rewards are costs


def test_sac_runs_and_entropy_tunes():
    config = (SACConfig()
              .environment("Pendulum-v1")
              .rollouts(num_rollout_workers=1, num_envs_per_worker=2,
                        rollout_fragment_length=64)
              .training(learning_starts=128, train_batch_size=64,
                        num_sgd_per_iter=8)
              .debugging(seed=0))
    algo = config.build()
    results = [algo.train() for _ in range(4)]
    algo.cleanup()
    last = results[-1]
    assert last["buffer_size"] >= 256
    assert np.isfinite(last["critic_loss"])
    assert np.isfinite(last["actor_loss"])
    assert last["alpha"] > 0


def test_a2c_learns_cartpole_somewhat():
    config = (A2CConfig()
              .environment("CartPole-v1")
              .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                        rollout_fragment_length=64)
              .training(lr=1e-3)
              .debugging(seed=0))
    algo = config.build()
    rewards = []
    for _ in range(12):
        result = algo.train()
        rewards.append(result.get("episode_reward_mean", 0.0))
    algo.cleanup()
    # A2C is noisier than PPO; require clear improvement, not mastery.
    assert max(rewards) > 1.5 * max(rewards[0], 15), rewards


def test_json_offline_roundtrip(tmp_path):
    w = JsonWriter(str(tmp_path))
    b1 = SampleBatch({"obs": np.random.randn(8, 4).astype(np.float32),
                      "actions": np.arange(8) % 2,
                      "rewards": np.ones(8, np.float32),
                      "dones": np.zeros(8, bool)})
    w.write(b1)
    w.write(b1)
    w.close()
    r = JsonReader(str(tmp_path))
    got = r.next()
    np.testing.assert_allclose(got["obs"], b1["obs"])
    allb = r.read_all()
    assert allb.count == 16


def _record_expert_data(path, n_rows=512):
    """Scripted near-optimal CartPole policy: push toward the pole."""
    from ray_tpu.rl import CartPoleEnv

    env = CartPoleEnv()
    w = JsonWriter(path)
    obs, _ = env.reset(seed=0)
    rows = {"obs": [], "actions": [], "rewards": [], "dones": []}
    for _ in range(n_rows):
        a = 1 if obs[2] + 0.5 * obs[3] > 0 else 0
        nobs, r, term, trunc, _ = env.step(a)
        rows["obs"].append(obs)
        rows["actions"].append(a)
        rows["rewards"].append(r)
        rows["dones"].append(term or trunc)
        obs = nobs
        if term or trunc:
            obs, _ = env.reset()
    w.write(SampleBatch({
        "obs": np.asarray(rows["obs"], np.float32),
        "actions": np.asarray(rows["actions"], np.int64),
        "rewards": np.asarray(rows["rewards"], np.float32),
        "dones": np.asarray(rows["dones"]),
    }))
    w.close()


def test_bc_clones_expert(tmp_path):
    _record_expert_data(str(tmp_path))
    config = (BCConfig()
              .environment("CartPole-v1")
              .offline_data(input_=str(tmp_path))
              .training(lr=5e-3, train_batch_size=256))
    algo = config.build()
    losses = [algo.train()["pi_loss"] for _ in range(80)]
    algo.cleanup()
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])

    # The cloned policy should reproduce the expert action most of the time.
    import jax
    import jax.numpy as jnp

    from ray_tpu.rl import models as rl_models
    reader = JsonReader(str(tmp_path))
    data = reader.read_all()
    logits, _ = rl_models.actor_critic_apply(
        algo.get_weights(), jnp.asarray(data["obs"]))
    acc = (np.asarray(jnp.argmax(logits, -1))
           == np.asarray(data["actions"])).mean()
    assert acc > 0.9, acc


def test_marwil_runs(tmp_path):
    _record_expert_data(str(tmp_path))
    config = (MARWILConfig()
              .environment("CartPole-v1")
              .offline_data(input_=str(tmp_path))
              .training(lr=1e-3, train_batch_size=256, beta=1.0))
    algo = config.build()
    result = None
    for _ in range(5):
        result = algo.train()
    algo.cleanup()
    assert np.isfinite(result["pi_loss"])
    assert result["mean_weight"] > 0


def test_connector_pipelines():
    pipe = ConnectorPipeline([FlattenObs(), ClipObs(-1, 1)])
    x = np.linspace(-2, 2, 24).reshape(2, 3, 4)
    out = pipe(x)
    assert out.shape == (2, 12)
    assert out.min() >= -1 and out.max() <= 1

    norm = NormalizeObs()
    data = np.random.RandomState(0).randn(64, 4) * 5 + 3
    for i in range(0, 64, 8):
        out = norm(data[i:i + 8])
    # After seeing the data the running stats roughly whiten it.
    out = norm(data)
    assert abs(out.mean()) < 0.5
    assert 0.5 < out.std() < 2.0

    # State roundtrip.
    state = norm.get_state()
    norm2 = NormalizeObs()
    norm2.set_state(state)
    np.testing.assert_allclose(norm2(data), norm(data))

    un = UnsquashAction(low=np.array([-2.0]), high=np.array([2.0]))
    np.testing.assert_allclose(un(np.array([[-1.0], [0.0], [1.0]])),
                               [[-2.0], [0.0], [2.0]])


def test_truncation_not_terminal_in_batches():
    """Pendulum never terminates; the worker must record terminateds
    all-False while dones flips at the 200-step truncation, and NEXT_OBS
    at a done row must be the true successor, not the reset obs."""
    import jax

    from ray_tpu.rl import models as rl_models
    from ray_tpu.rl.rollout_worker import RolloutWorker

    params = rl_models.gaussian_policy_init(jax.random.PRNGKey(0), 3, 1)
    w = RolloutWorker.remote(
        "Pendulum-v1", rl_models.gaussian_policy_apply,
        num_envs=1, rollout_fragment_length=210, seed=0,
        policy_kind="gaussian")
    batch = ray_tpu.get(w.sample.remote(params))
    dones = np.asarray(batch["dones"])[0]
    terms = np.asarray(batch["terminateds"])[0]
    assert dones.sum() == 1 and not terms.any()
    i = int(np.nonzero(dones)[0][0])
    next_at_done = np.asarray(batch["next_obs"])[0, i]
    obs_after = np.asarray(batch["obs"])[0, i + 1]
    # Post-reset obs differs from the true successor recorded in NEXT_OBS.
    assert not np.allclose(next_at_done, obs_after)


def test_gaussian_actions_reach_env_bounds():
    """Default UnsquashAction pipeline maps [-1,1] to the action space;
    recorded ACTIONS stay squashed."""
    import jax

    from ray_tpu.rl import models as rl_models
    from ray_tpu.rl.rollout_worker import RolloutWorker

    params = rl_models.gaussian_policy_init(jax.random.PRNGKey(0), 3, 1)
    w = RolloutWorker.remote(
        "Pendulum-v1", rl_models.gaussian_policy_apply,
        num_envs=2, rollout_fragment_length=32, seed=0,
        policy_kind="gaussian")
    batch = ray_tpu.get(w.sample.remote(params))
    acts = np.asarray(batch["actions"])
    assert acts.min() >= -1.0 and acts.max() <= 1.0


def test_marwil_returns_no_cross_fragment_leak(tmp_path):
    """Reward-to-go must reset at fragment boundaries: two fragments
    with very different rewards keep distinct return scales."""
    w = JsonWriter(str(tmp_path))
    w.write(SampleBatch({
        "obs": np.zeros((4, 4), np.float32),
        "actions": np.zeros(4, np.int64),
        "rewards": np.zeros(4, np.float32),
        "dones": np.zeros(4, bool)}))
    w.write(SampleBatch({
        "obs": np.zeros((4, 4), np.float32),
        "actions": np.zeros(4, np.int64),
        "rewards": 100 * np.ones(4, np.float32),
        "dones": np.zeros(4, bool)}))
    w.close()
    config = (MARWILConfig()
              .environment("CartPole-v1")
              .offline_data(input_=str(tmp_path))
              .training(train_batch_size=8))
    algo = config.build()
    algo.setup({})
    batch = algo._next_train_batch()
    returns = np.asarray(batch["returns"])
    # First fragment's returns stay exactly zero (no leak from the 100s).
    assert np.all(returns[:4] == 0.0), returns
    assert np.all(returns[4:] > 0.0)
    algo.cleanup()


class _TwoAgentCartPole:
    """Two independent CartPoles behind the MultiAgentEnv dict API."""

    def __init__(self, _cfg=None):
        from ray_tpu.rl import CartPoleEnv

        self.envs = {"a0": CartPoleEnv(max_steps=50),
                     "a1": CartPoleEnv(max_steps=50)}
        self.agent_ids = list(self.envs)

    def reset(self, *, seed=None):
        obs = {}
        for i, (aid, e) in enumerate(self.envs.items()):
            o, _ = e.reset(seed=None if seed is None else seed + i)
            obs[aid] = o
        return obs, {}

    def step(self, action_dict):
        obs, rew, term, trunc = {}, {}, {}, {}
        for aid, e in self.envs.items():
            o, r, te, tr, _ = e.step(action_dict[aid])
            if te or tr:
                o, _ = e.reset()
            obs[aid], rew[aid], term[aid], trunc[aid] = o, r, te, tr
        term["__all__"] = all(term[a] for a in self.envs)
        trunc["__all__"] = all(trunc[a] for a in self.envs)
        return obs, rew, term, trunc, {}


def test_multi_agent_rollout_shared_policy():
    import jax

    from ray_tpu.rl import MultiAgentRolloutWorker
    from ray_tpu.rl import models as rl_models

    params = rl_models.actor_critic_init(jax.random.PRNGKey(0), 4, 2)
    w = MultiAgentRolloutWorker.remote(
        _TwoAgentCartPole, {"shared": rl_models.actor_critic_apply},
        policy_mapping_fn=lambda aid: "shared",
        rollout_fragment_length=40, seed=0)
    batches = ray_tpu.get(w.sample.remote({"shared": params}))
    assert set(batches) == {"shared"}
    b = batches["shared"]
    assert b.count == 80  # 2 agents x 40 steps
    assert b["obs"].shape == (80, 4)
    assert set(b.keys()) >= {"obs", "actions", "rewards", "dones",
                             "terminateds", "action_logp"}


def test_multi_agent_rollout_per_agent_policies():
    import jax

    from ray_tpu.rl import MultiAgentRolloutWorker
    from ray_tpu.rl import models as rl_models

    p0 = rl_models.actor_critic_init(jax.random.PRNGKey(0), 4, 2)
    p1 = rl_models.actor_critic_init(jax.random.PRNGKey(1), 4, 2)
    w = MultiAgentRolloutWorker.remote(
        _TwoAgentCartPole,
        {"p0": rl_models.actor_critic_apply,
         "p1": rl_models.actor_critic_apply},
        policy_mapping_fn=lambda aid: "p0" if aid == "a0" else "p1",
        rollout_fragment_length=25, seed=0)
    batches = ray_tpu.get(w.sample.remote({"p0": p0, "p1": p1}))
    assert set(batches) == {"p0", "p1"}
    assert batches["p0"].count == 25
    assert batches["p1"].count == 25


def test_worker_with_connectors():
    """RolloutWorker applies obs connectors before the policy."""
    import jax.numpy as jnp

    from ray_tpu.rl import models as rl_models
    from ray_tpu.rl.rollout_worker import RolloutWorker

    params = rl_models.actor_critic_init(
        __import__("jax").random.PRNGKey(0), 4, 2)
    w = RolloutWorker.remote(
        "CartPole-v1", rl_models.actor_critic_apply,
        num_envs=2, rollout_fragment_length=8, seed=0,
        obs_connectors=ConnectorPipeline([ClipObs(-0.04, 0.04)]))
    batch = ray_tpu.get(w.sample.remote(params))
    obs = np.asarray(batch["obs"])
    assert obs.min() >= -0.04 and obs.max() <= 0.04
    state = ray_tpu.get(w.connector_state.remote())
    assert state["obs"] is not None


def test_appo_runs_and_learns_a_bit():
    from ray_tpu.rl import APPOConfig

    config = (APPOConfig()
              .environment("CartPole-v1")
              .rollouts(num_rollout_workers=2, num_envs_per_worker=8,
                        rollout_fragment_length=64)
              .training(lr=5e-4, updates_per_iter=4)
              .debugging(seed=0))
    algo = config.build()
    rewards = []
    for _ in range(8):
        result = algo.train()
        rewards.append(result.get("episode_reward_mean", 0.0))
    algo.cleanup()
    assert "pi_loss" in result and "mean_ratio" in result
    assert result["num_env_steps_sampled_this_iter"] > 0
    # async PPO on CartPole should be visibly improving by iter 8
    assert max(rewards) > 1.3 * max(rewards[0], 15), rewards


def test_td3_runs_on_pendulum():
    from ray_tpu.rl import TD3Config

    config = (TD3Config()
              .environment("Pendulum-v1")
              .rollouts(num_rollout_workers=1, num_envs_per_worker=2,
                        rollout_fragment_length=64)
              .training(learning_starts=128, train_batch_size=64,
                        num_sgd_per_iter=8)
              .debugging(seed=0))
    algo = config.build()
    results = [algo.train() for _ in range(4)]
    algo.cleanup()
    last = results[-1]
    assert last["buffer_size"] >= 256
    assert np.isfinite(last["critic_loss"])
    assert np.isfinite(last["actor_loss"])
    # Deterministic eval path works for the DDPG-family policy too.
    out = algo.evaluate(num_episodes=1,
                        max_steps_per_episode=50)["evaluation"]
    assert out["episode_reward_mean"] < 0


def test_apex_dqn_distributed_replay():
    """Ape-X: sharded replay actors, async sampling, per-worker epsilon
    ladder (reference `rllib/algorithms/apex_dqn`)."""
    from ray_tpu.rl import ApexDQNConfig

    config = (ApexDQNConfig()
              .environment("CartPole-v1")
              .rollouts(num_rollout_workers=2, num_envs_per_worker=4,
                        rollout_fragment_length=32)
              .training(lr=1e-3, learning_starts=64, buffer_size=4096,
                        train_batch_size=32, num_sgd_per_iter=8,
                        num_replay_shards=2)
              .debugging(seed=0))
    algo = config.build()
    result = None
    for _ in range(4):
        result = algo.train()
    algo.cleanup()
    assert result["buffer_size"] > 64
    assert len(result["replay_shard_sizes"]) == 2
    assert all(s > 0 for s in result["replay_shard_sizes"])
    # per-worker epsilons form a ladder, not one global schedule
    eps = result["worker_epsilons"]
    assert len(eps) == 2 and eps[0] > eps[1]
    assert result["learner_updates_this_iter"] > 0
    assert result["mean_td_loss"] is not None
