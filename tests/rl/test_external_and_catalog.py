"""Model catalog + external-env serving + native TPE searcher
(reference `rllib/models/catalog.py`, `rllib/env/policy_client.py` /
`policy_server_input.py`, `tune/search/hyperopt`)."""

import numpy as np
import pytest

import jax

import ray_tpu
from ray_tpu.rl import (
    PolicyClient,
    PolicyServer,
    get_actor_critic_model,
    get_q_model,
)
from ray_tpu.rl.env import Box, CartPoleEnv, CatchPixelsEnv, Discrete


def test_catalog_picks_models_by_space():
    cart = CartPoleEnv()
    spec = get_actor_critic_model(cart.observation_space,
                                  cart.action_space)
    params = spec.init(jax.random.PRNGKey(0))
    logits, value = spec.apply(params, np.zeros((3, 4), np.float32))
    assert logits.shape == (3, 2) and value.shape == (3,)
    assert spec.kind == "actor_critic"

    pix = CatchPixelsEnv(size=40)
    spec = get_actor_critic_model(pix.observation_space,
                                  pix.action_space)
    params = spec.init(jax.random.PRNGKey(0))
    logits, _ = spec.apply(params,
                           np.zeros((2, 40, 40, 1), np.uint8))
    assert logits.shape == (2, 3)
    assert "conv" in params

    cont_spec = get_actor_critic_model(
        Box(-1, 1, (3,)), Box(-1, 1, (2,)))
    assert cont_spec.kind == "gaussian"

    q = get_q_model(cart.observation_space, cart.action_space)
    params = q.init(jax.random.PRNGKey(0))
    assert q.apply(params, np.zeros((5, 4), np.float32)).shape == (5, 2)


def test_policy_server_serves_external_episodes():
    """An external CartPole sim drives episodes through PolicyClient;
    the server accumulates SampleBatches and returns live actions."""
    env = CartPoleEnv()
    spec = get_actor_critic_model(env.observation_space,
                                  env.action_space)
    params = spec.init(jax.random.PRNGKey(0))
    server = PolicyServer(spec.apply, params, batch_size=64, seed=0)
    try:
        client = PolicyClient(server.address)
        total_steps = 0
        for ep in range(6):
            eid = client.start_episode()
            obs, _ = env.reset(seed=ep)
            for _ in range(40):
                a = client.get_action(eid, obs)
                assert a in (0, 1)
                obs, r, term, trunc, _ = env.step(a)
                client.log_returns(eid, r)
                total_steps += 1
                if term or trunc:
                    break
            client.end_episode(eid, obs)
        client.close()
        batch = server.get_samples(timeout=5)
        assert batch is not None
        n = len(batch["obs"])
        assert n >= 64
        assert batch["obs"].shape[1] == 4
        assert set(batch.keys()) >= {"obs", "actions", "rewards",
                                     "dones", "next_obs"}
        # terminal rows align with episode ends
        assert batch["dones"].sum() >= 1
        assert len(server.episode_returns) == 6
        # weight updates take effect on subsequent actions
        new_params = jax.tree.map(lambda p: p * 0.0, params)
        server.set_weights(new_params)
    finally:
        server.shutdown()


def test_tpe_searcher_converges_toward_optimum():
    from ray_tpu import tune
    from ray_tpu.tune import TuneConfig, Tuner
    from ray_tpu.tune.search import TPESearch

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    try:
        def trainable(config):
            # maximum at x=0.7, y="b"
            score = -(config["x"] - 0.7) ** 2 + \
                (0.5 if config["y"] == "b" else 0.0)
            tune.report({"score": score})

        searcher = TPESearch({"x": tune.uniform(0.0, 1.0),
                              "y": tune.choice(["a", "b", "c"])},
                             metric="score", mode="max",
                             n_startup=6, seed=0)
        tuner = Tuner(trainable,
                      tune_config=TuneConfig(metric="score", mode="max",
                                             search_alg=searcher,
                                             num_samples=40))
        grid = tuner.fit()
        best = grid.get_best_result("score", "max")
        assert abs(best.config["x"] - 0.7) < 0.15, best.config
        assert best.metrics["score"] > 0.3
        # TPE's model phase actually engaged — completed results fed
        # later suggestions (lazy suggestion; eager would leave this 0)
        assert len(searcher._observations) >= 30
        assert searcher.model_suggestions > 0
    finally:
        ray_tpu.shutdown()
