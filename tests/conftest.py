"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so sharding/multi-chip code
paths compile and execute without TPU hardware (the driver's
``dryrun_multichip`` does the same). Must run before any ``import jax``.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:  # `tools` (raylint/raysan) resolves from root
    sys.path.insert(0, REPO_ROOT)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Runtime sanitizers (opt-in: `pytest --sanitize=leaks,ambient ...`);
# registering unconditionally just adds the CLI options.
pytest_plugins = ("tools.raysan.pytest_plugin",)


@pytest.fixture(autouse=True)
def _global_state_baseline():
    """Snapshot/restore the process-global serve+health records around
    EVERY test.

    ``serve_request_seconds`` (fast-path dists) and ``health.tracker``
    (burn-rate history) are process-global by design; a test that
    records into them — a 5xx burst, an SLO fixture — used to poison
    every later healthz assertion unless it remembered the manual
    reset convention (the order-dependent flake documented in
    CHANGES.md PR 6). This fixture replaces that convention
    structurally: whatever a test records is rolled back at teardown
    via the runtime's own reset hooks, and the ambient sanitizer
    (``--sanitize=ambient``) independently verifies nothing escapes.
    Cost is two small dict snapshots per test."""
    from ray_tpu._private import (critical_path, flight_recorder, health,
                                  perf_stats)

    serve_snap = perf_stats.snapshot_records("serve_request_seconds")
    # The per-(job, route) request counter feeds job_summary()'s
    # serve_requests rows: same process-global class, same rollback —
    # a test's tagged traffic must not inflate a later test's exact
    # per-tenant counts.
    req_snap = perf_stats.snapshot_records("serve_requests")
    # The critical-path attribution vectors + waterfalls + flight rings
    # (PR 18) are the same process-global class: one test's serve
    # traffic must not leak stage records into another's
    # /api/slow_requests or flight-dump assertions.
    stage_snap = perf_stats.snapshot_records(critical_path.STAGE_METRIC)
    cp_snap = critical_path.snapshot_state()
    fr_snap = flight_recorder.snapshot_state()
    health_snap = health.snapshot_state()
    yield
    perf_stats.restore_records("serve_request_seconds", serve_snap)
    perf_stats.restore_records("serve_requests", req_snap)
    perf_stats.restore_records(critical_path.STAGE_METRIC, stage_snap)
    critical_path.restore_state(cp_snap)
    flight_recorder.restore_state(fr_snap)
    health.restore_state(health_snap)


@pytest.fixture
def ray_start_regular():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_2_cpus():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()
