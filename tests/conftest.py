"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so sharding/multi-chip code
paths compile and execute without TPU hardware (the driver's
``dryrun_multichip`` does the same). Must run before any ``import jax``.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def ray_start_regular():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_2_cpus():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()
