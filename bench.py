"""Flagship benchmark: Llama pretrain step throughput, tokens/sec/chip.

Run by the driver on real TPU hardware after every round; prints exactly
one JSON line. The metric is the BASELINE.json north star ("Train
tokens/sec/chip"); the reference publishes no number for it
(`BASELINE.json -> "published": {}`), so `vs_baseline` is reported against
the first value this repo establishes (stored in BENCH_BASELINE.json once
measured) or 1.0 until then.

On a single v5e chip (16G HBM) the largest Llama-3-family config that fits
a full AdamW train step is ~1B with bf16 optimizer moments; multi-chip runs
shard with the same code via MeshConfig (fsdp/tensor/seq axes).
"""

from __future__ import annotations

import json
import os
import time


def _measure_llama_train_step():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import (
        LlamaConfig,
        init_params_sharded,
        init_train_state,
        loss_fn,
        make_optimizer,
        make_train_step,
    )
    from ray_tpu.parallel import MeshConfig, create_mesh

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    n = len(devices)

    if on_tpu:
        import dataclasses

        # remat="gate" saves the silu(w1) MLP activation across the remat
        # boundary (the largest recompute the HBM budget allows next to
        # AdamW bf16 moments); fused CE (cfg default) keeps the [tokens,
        # vocab] logits unmaterialized. Sweep provenance:
        # benchmarks/sweep_step.py — batch 4 beat 2/8 per token on this
        # chip.
        cfg = dataclasses.replace(LlamaConfig.llama3_1b(), remat="gate")
        batch, seq = 4, 2048
        moment_dtype = jnp.bfloat16
        steps = 10
    else:  # CPU smoke path so the bench always emits a line
        cfg = LlamaConfig.debug()
        batch, seq = 8, 128
        moment_dtype = None
        steps = 3

    # One chip → trivial mesh; more chips → fsdp-shard the params.
    mesh = create_mesh(MeshConfig(data=-1, fsdp=min(n, 4) if n > 1 else 1))

    params = init_params_sharded(cfg, mesh, jax.random.PRNGKey(0))
    tx = make_optimizer(3e-4, warmup_steps=0, moment_dtype=moment_dtype)
    state = init_train_state(params, tx)
    step = make_train_step(
        lambda p, b: loss_fn(p, b, cfg, mesh=mesh), tx, mesh=mesh,
        batch_logical={"tokens": ("batch", "seq"),
                       "targets": ("batch", "seq")},
    )

    key = jax.random.PRNGKey(1)
    tokens = jax.random.randint(key, (batch, seq), 0, cfg.vocab_size)
    batch_data = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

    # Warmup (compile) then timed windows. Best-of-3 windows: the chip
    # is reached over a shared tunnel, and a transient stall in one
    # window must not be recorded as the framework's throughput (the
    # round-2 artifact showed 0.41x from exactly such a stall).
    #
    # NOTE: on the tunneled platform `jax.block_until_ready` can return
    # before the computation actually finishes (observed: a 10-step window
    # "completing" in 2.7ms). The only trustworthy barrier is fetching a
    # scalar value to the host, so every window ends with float(loss).
    state, metrics = step(state, batch_data)
    float(metrics["loss"])
    dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, batch_data)
        float(metrics["loss"])
        dt = min(dt, (time.perf_counter() - t0) / steps)

    tokens_per_sec = batch * seq / dt
    per_chip = tokens_per_sec / n

    # Model FLOPs utilization against v5e peak (197 TFLOP/s bf16) — and
    # against the MEASURED envelope of this tunneled chip
    # (BENCH_CALIBRATION.json: ~145 TF matmul, ~160 GB/s HBM → a
    # practical step floor of ~650 ms at these shapes). MFU vs nominal
    # saturates near ~50% here regardless of program quality; the
    # envelope utilization is the honest program-quality signal.
    flops_per_token = 6 * cfg.num_params() + 12 * cfg.n_layers * cfg.dim * seq
    mfu = None
    envelope_util = None
    if on_tpu:
        mfu = per_chip * flops_per_token / 197e12
        # Floor comes from the calibration artifact so recalibration and
        # reporting can't drift apart (absent key → no utilization).
        try:
            with open(os.path.join(os.path.dirname(__file__),
                                   "BENCH_CALIBRATION.json")) as f:
                floors = json.load(f).get("practical_step_floor_s", {})
            envelope_step_s = floors.get(
                "llama-1.24B_b4_s2048_remat-gate")
            if envelope_step_s:
                envelope_util = envelope_step_s / dt
        except (OSError, ValueError):
            pass

    return {
        "config": f"llama-{cfg.num_params() / 1e9:.2f}B" if on_tpu
        else "llama-debug-cpu",
        "value": per_chip,
        "mfu": mfu,
        "envelope_utilization": envelope_util,
        "batch": batch,
        "seq": seq,
        "n_chips": n,
        "step_ms": dt * 1e3,
    }


def main():
    result = _measure_llama_train_step()
    baseline_path = os.path.join(os.path.dirname(__file__),
                                 "BENCH_BASELINE.json")
    vs = 1.0
    try:
        with open(baseline_path) as f:
            recorded = json.load(f)
        if recorded.get("value"):
            vs = result["value"] / recorded["value"]
    except (OSError, ValueError):
        pass
    print(json.dumps({
        "metric": f"train_tokens_per_sec_per_chip[{result['config']}]",
        "value": round(result["value"], 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs, 3),
        "detail": {k: (round(v, 4) if isinstance(v, float) else v)
                   for k, v in result.items() if k != "value"},
    }))


if __name__ == "__main__":
    main()
