"""Tune sweeps and the RL stack.

Run: python examples/05_tune_and_rl.py
"""
import ray_tpu
from ray_tpu import tune
from ray_tpu.rl import PPOConfig

ray_tpu.init()

# Hyperparameter sweep with ASHA early stopping.
def objective(config):
    acc = 0.0
    for step in range(10):
        acc += config["lr"] * (1 - acc)
        tune.report({"acc": acc})

tuner = tune.Tuner(
    objective,
    param_space={"lr": tune.grid_search([0.05, 0.1, 0.3])},
    tune_config=tune.TuneConfig(metric="acc", mode="max",
                                scheduler=tune.ASHAScheduler()),
)
best = tuner.fit().get_best_result("acc", "max")
print("best lr:", best.config["lr"])

# PPO on the built-in vectorized CartPole.
algo = (PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, num_envs_per_worker=16,
                  rollout_fragment_length=64)
        .training(lr=3e-4)).build()
for i in range(3):
    r = algo.train()
    print(f"iter {i}: reward={r.get('episode_reward_mean', 0):.1f}")
print("greedy eval:", algo.evaluate(num_episodes=2)["evaluation"])
algo.cleanup()
ray_tpu.shutdown()
