"""Core API tour: tasks, actors, objects, placement groups.

Run: python examples/01_core_api.py
"""
import ray_tpu

ray_tpu.init()


@ray_tpu.remote
def square(x):
    return x * x


@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0

    def add(self, k):
        self.n += k
        return self.n


# Parallel tasks.
print("squares:", ray_tpu.get([square.remote(i) for i in range(8)]))

# Objects + nested refs.
big = ray_tpu.put(list(range(10_000)))


@ray_tpu.remote
def tail(xs, n=3):
    return xs[-n:]


print("tail:", ray_tpu.get(tail.remote(big)))

# Actors (ordered calls) + named actors.
c = Counter.options(name="demo").remote()
for _ in range(3):
    c.add.remote(2)
print("counter:", ray_tpu.get(ray_tpu.get_actor("demo").add.remote(0)))

# wait() for completion-order consumption.
refs = [square.remote(i) for i in range(4)]
ready, rest = ray_tpu.wait(refs, num_returns=2)
print("first two done:", ray_tpu.get(ready))

ray_tpu.shutdown()
