"""SPMD Llama training step over a device mesh.

Runs on whatever devices exist (a debug config on CPU; scale the config
and MeshConfig axes on real slices). For an 8-virtual-device run:

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/02_train_llama_spmd.py
"""
import dataclasses

import jax
import jax.numpy as jnp

from ray_tpu.models import (LlamaConfig, init_params_sharded,
                            init_train_state, loss_fn, make_optimizer,
                            make_train_step)
from ray_tpu.parallel import MeshConfig, create_mesh

n = len(jax.devices())
cfg = dataclasses.replace(LlamaConfig.debug(), vocab_size=512)
mesh = create_mesh(MeshConfig(data=-1, fsdp=min(n, 2)))
print("mesh:", dict(mesh.shape))

params = init_params_sharded(cfg, mesh, jax.random.PRNGKey(0))
tx = make_optimizer(1e-3, warmup_steps=0)
state = init_train_state(params, tx)
step = make_train_step(
    lambda p, b: loss_fn(p, b, cfg, mesh=mesh), tx, mesh=mesh,
    batch_logical={"tokens": ("batch", "seq"),
                   "targets": ("batch", "seq")})

tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 128), 0,
                            cfg.vocab_size)
batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
for i in range(5):
    state, metrics = step(state, batch)
    print(f"step {i}: loss={float(metrics['loss']):.4f}")
