"""The round-4 RL stack: async on-device learner, pixel env with the
conv torso, distributed replay, and external-environment serving.

Run: python examples/06_rl_learner_and_external.py
"""
import ray_tpu
from ray_tpu.rl import (
    ApexDQNConfig,
    IMPALAConfig,
    PolicyClient,
    PolicyServer,
    get_actor_critic_model,
)
from ray_tpu.rl.env import CartPoleEnv

ray_tpu.init()

# 1) IMPALA with the learner thread: rollout actors stream pixel
# fragments into a queue while the conv V-trace update runs
# continuously on the accelerator (sampling and learning overlap).
config = (IMPALAConfig()
          .environment("CatchPixels-v0")
          .rollouts(num_rollout_workers=2, num_envs_per_worker=8,
                    rollout_fragment_length=40)
          .training(lr=3e-4, updates_per_iter=4)
          .learners(use_learner_thread=True, num_sgd_iter=2))
algo = config.build()
for i in range(3):
    r = algo.train()
    print(f"IMPALA iter {i}: updates={r['learner_updates']} "
          f"busy={r['device_busy_fraction']:.2f} "
          f"sampled={r['num_env_steps_sampled_this_iter']}")
algo.cleanup()

# 2) Ape-X DQN: replay sharded across actors, per-worker epsilons.
apex = (ApexDQNConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, num_envs_per_worker=4,
                  rollout_fragment_length=32)
        .training(learning_starts=128, num_sgd_per_iter=8)).build()
for i in range(3):
    r = apex.train()
    print(f"ApexDQN iter {i}: shards={r['replay_shard_sizes']} "
          f"eps={r['worker_epsilons']}")
apex.cleanup()

# 3) External-env serving: a simulator YOU own drives episodes against
# a policy server (reference PolicyClient/PolicyServerInput).
import jax

env = CartPoleEnv()
spec = get_actor_critic_model(env.observation_space, env.action_space)
server = PolicyServer(spec.apply, spec.init(jax.random.PRNGKey(0)),
                      batch_size=128)
client = PolicyClient(server.address)
for ep in range(4):
    eid = client.start_episode()
    obs, _ = env.reset(seed=ep)
    for _ in range(60):
        action = client.get_action(eid, obs)
        obs, reward, term, trunc, _ = env.step(action)
        client.log_returns(eid, reward)
        if term or trunc:
            break
    client.end_episode(eid, obs)
print("external episodes:", server.episode_returns)
batch = server.get_samples(timeout=2)
if batch is not None:
    print("accumulated training batch:", len(batch["obs"]), "rows")
client.close()
server.shutdown()
ray_tpu.shutdown()
print("done")
