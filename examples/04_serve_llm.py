"""Serve: deployments, graphs, and the continuous-batching LLM engine.

Run: python examples/04_serve_llm.py
"""
import urllib.request

import ray_tpu
from ray_tpu import serve

ray_tpu.init()


@serve.deployment(num_replicas=2)
class Preprocess:
    def __call__(self, x):
        return x * 10


@serve.deployment
class Model:
    def __init__(self, upstream):
        self.upstream = upstream

    def __call__(self, x):
        return ray_tpu.get(self.upstream.remote(x)) + 1


# A two-stage deployment graph behind an HTTP route.
handle = serve.run(Model.bind(Preprocess.bind()), route_prefix="/model")
print("direct call:", ray_tpu.get(handle.remote(4)))  # 41

proxy = serve.start_http_proxy()
req = urllib.request.Request(
    f"http://{proxy.host}:{proxy.port}/model", data=b"4",
    headers={"Content-Type": "application/json"})
print("over HTTP:", urllib.request.urlopen(req).read())

serve.shutdown()
ray_tpu.shutdown()
