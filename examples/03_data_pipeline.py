"""Data: blocks, transforms, windowed pipelines, device ingest.

Run: python examples/03_data_pipeline.py
"""
import numpy as np

import ray_tpu
from ray_tpu import data as rd

ray_tpu.init()

ds = (rd.range(1000, parallelism=8)
      .map(lambda r: (r["id"] if isinstance(r, dict) else r))
      .map(lambda x: {"x": float(x), "y": 2.0 * x})
      .filter(lambda row: row["x"] % 3 == 0))
print("rows:", ds.count(), "| first:", ds.take(2))
print("mean y:", ds.mean("y"))

# Windowed pipeline: bounded memory, per-window shuffle, two epochs.
pipe = (rd.range(64, parallelism=8)
        .window(blocks_per_window=2)
        .random_shuffle_each_window(seed=0)
        .repeat(2))
print("pipeline:", pipe.stats(), "| total rows:", pipe.count())

# Torch-tensor ingest (iter_jax_batches is the TPU analog).
for batch in rd.from_numpy(
        np.arange(8, dtype=np.float32)).iter_torch_batches(batch_size=4):
    print("torch batch:", batch)
    break

ray_tpu.shutdown()
