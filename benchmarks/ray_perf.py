"""Core micro-op benchmark suite.

Reference: `python/ray/_private/ray_perf.py:93-305` (run nightly by
`release/microbenchmark/`): tasks/s (sync, 1:1, scatter), actor calls/s
(sync + async), put/get latency and bandwidth, `wait` on many refs.
Prints one JSON object with every metric; `python benchmarks/ray_perf.py`.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def timeit(name, fn, multiplier: int = 1, min_time: float = 1.0) -> float:
    # Warmup
    fn()
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < min_time:
        fn()
        count += 1
    dt = time.perf_counter() - start
    rate = count * multiplier / dt
    return rate


def main():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    results = {}

    @ray_tpu.remote
    def tiny():
        return b"ok"

    @ray_tpu.remote(num_cpus=0.001)
    def tiny_cheap():
        return b"ok"

    results["single_client_tasks_sync_per_s"] = timeit(
        "tasks sync", lambda: ray_tpu.get(tiny.remote()))

    def batch_submit():
        ray_tpu.get([tiny_cheap.remote() for _ in range(100)])

    results["single_client_tasks_async_per_s"] = timeit(
        "tasks async batch", batch_submit, multiplier=100)

    @ray_tpu.remote
    class Actor:
        def ping(self):
            return b"ok"

    actor = Actor.remote()
    results["actor_calls_sync_per_s"] = timeit(
        "actor sync", lambda: ray_tpu.get(actor.ping.remote()))

    def actor_batch():
        ray_tpu.get([actor.ping.remote() for _ in range(100)])

    results["actor_calls_async_per_s"] = timeit(
        "actor async", actor_batch, multiplier=100)

    small = np.zeros(1024, np.uint8)
    results["put_small_per_s"] = timeit(
        "put 1KB", lambda: ray_tpu.put(small))

    big = np.zeros(64 * 2**20, np.uint8)

    def put_get_big():
        ref = ray_tpu.put(big)
        ray_tpu.get(ref)

    rate = timeit("put+get 64MB", put_get_big)
    results["put_get_64MB_GBps"] = rate * 64 / 1024

    refs = [tiny_cheap.remote() for _ in range(1000)]
    ray_tpu.get(refs)
    results["wait_1k_refs_per_s"] = timeit(
        "wait 1k", lambda: ray_tpu.wait(refs, num_returns=1000,
                                        timeout=10))

    n_deep = 10

    @ray_tpu.remote(num_cpus=0.001)
    def fan(width):
        return 1

    def scatter_gather():
        ray_tpu.get([fan.remote(i) for i in range(n_deep)])

    results["scatter_gather_10_per_s"] = timeit(
        "1:n:1", scatter_gather)

    results = {k: round(v, 1) for k, v in results.items()}
    ray_tpu.shutdown()
    results.update(cluster_bench())
    print(json.dumps(results, indent=2))
    return results


def cluster_bench() -> dict:
    """Cross-process object-plane throughput (shm vs pickle RPC)."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    out = {}
    cluster = Cluster(head_node_args={"num_cpus": 1},
                      shm_capacity=2048 * 2**20)
    try:
        cluster.add_node(num_cpus=4)
        if cluster.shm_plane is not None:
            # Steady-state numbers: let the background page-populate
            # finish (a long-lived cluster runs fully populated).
            cluster.shm_plane.store.wait_prefault(60)
        mb = 64

        @ray_tpu.remote(num_cpus=2)
        def sync_node_prefault():
            from ray_tpu._private.worker import global_worker

            plane = getattr(global_worker(), "shm_plane", None)
            if plane is not None:
                plane.store.wait_prefault(60)
            return plane is not None

        ray_tpu.get(sync_node_prefault.remote())  # node-side PTEs too

        @ray_tpu.remote(num_cpus=2)
        def produce():
            # Steady-state producer: a warm source buffer (cached on a
            # process-persistent module, since each task deserializes
            # its own function globals) so the bench measures the OBJECT
            # PLANE — serialize + shm copy + fetch — not np.zeros' lazy
            # page allocation. Each call still makes a distinct object.
            import ray_tpu._private.worker as _w

            buf = getattr(_w, "_bench_buf", None)
            if buf is None:
                buf = _w._bench_buf = np.ones(mb * 2**20, np.uint8)
            return buf

        @ray_tpu.remote(num_cpus=2)
        def consume(x):
            return x.nbytes

        def node_to_driver():
            assert ray_tpu.get(produce.remote()).nbytes == mb * 2**20

        big = np.ones(mb * 2**20, np.uint8)  # warm driver-side source

        def driver_to_node():
            assert ray_tpu.get(consume.remote(ray_tpu.put(big))) \
                == mb * 2**20

        rate = timeit("node->driver 64MB", node_to_driver, min_time=3.0)
        out["xproc_get_64MB_GBps"] = round(rate * mb / 1024, 2)
        rate = timeit("driver->node 64MB", driver_to_node, min_time=3.0)
        out["xproc_put_arg_64MB_GBps"] = round(rate * mb / 1024, 2)
        if cluster.shm_plane is not None:
            out["shm_enabled"] = True
            out["shm_evictions"] = cluster.shm_plane.stats()["evictions"]
    finally:
        cluster.shutdown()
    return out


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None,
                        help="also write results JSON to this path")
    cli_args = parser.parse_args()
    res = main()
    if cli_args.out:
        with open(cli_args.out, "w") as f:
            json.dump(res, f, indent=2)
