"""Serve data-plane RPS benchmark: handle path, HTTP ingress, proxy
fleet, and replica-direct dispatch — same-run A/B legs (reference:
`release/serve_tests/workloads/serve_micro_benchmark.py`, the serving
control plane's overhead floor distinct from any model cost).

Legs (all in ONE process/run so ratios are host-independent):

- **handle**: in-process ServeHandle path (the ceiling);
- **http_single_routed**: one proxy, ``serve_replica_direct`` OFF —
  every request pays the router (PR 1..14 status quo);
- **http_single_direct**: one proxy, replica-direct ON — steady-state
  requests dispatch proxy→replica; the hop counters prove the router
  was skipped (``router_hops`` ≈ 0 while ``direct_hops`` ≈ requests);
- **http_fleet_direct**: ``--proxies N`` supervised fleet, clients
  spread across the proxies;
- **connection-per-request** (single proxy) for the naive-client
  floor.

``--chaos`` adds the chaos section (SCALE_SERVE_r15-style): sustained
fleet load while one proxy and one replica are killed — p99 across the
window, zero-double-dispatch check, healthz degraded→recovered
timeline.

Bench absolutes are NOT comparable across hosts/rounds — compare the
same-run ratios, and read ``host_calibration``. On a single-core host
the fleet cannot exceed one proxy's throughput (every leg is already
CPU-saturated: see ``cpu_saturation``); the fleet claim there is the
chaos/e2e behavior, not the multiplier.

Usage:
  python benchmarks/serve_rps_bench.py [--requests 300] [--proxies 2]
      [--replica-direct both|on|off] [--chaos]
      [--out BENCH_SERVE_RPS_r15.json --scale-out SCALE_SERVE_r15.json]

Writes one JSON doc to stdout (and to --out/--scale-out when given).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           max(0, math.ceil(len(sorted_vals) * q) - 1))]


def _stats(lat, wall):
    lat = sorted(lat)
    if not lat:
        return {"rps": 0.0, "p50_ms": 0.0, "p95_ms": 0.0,
                "p99_ms": 0.0, "requests": 0}
    return {
        "rps": round(len(lat) / wall, 1),
        "p50_ms": round(percentile(lat, 0.5) * 1e3, 2),
        "p95_ms": round(percentile(lat, 0.95) * 1e3, 2),
        "p99_ms": round(percentile(lat, 0.99) * 1e3, 2),
        "requests": len(lat),
    }


def _run_workers(worker, concurrency, per):
    threads = [threading.Thread(target=worker, args=(per, i))
               for i in range(concurrency)]
    t0 = time.perf_counter()
    cpu0 = time.process_time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    cpu = time.process_time() - cpu0
    return wall, cpu


def _request_bytes(path, i):
    body = json.dumps({"payload": i}).encode()
    return (b"POST " + path.encode() + b" HTTP/1.1\r\nHost: bench\r\n"
            b"Content-Type: application/json\r\nContent-Length: "
            + str(len(body)).encode() + b"\r\n\r\n" + body)


def _read_response(sock, buf):
    """Read one Content-Length-framed response; returns (status,
    headers_blob, leftover buf)."""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("server closed")
        buf += chunk
    head, buf = buf.split(b"\r\n\r\n", 1)
    status = int(head.split(b" ", 2)[1])
    clen = 0
    for ln in head.split(b"\r\n")[1:]:
        if ln.lower().startswith(b"content-length:"):
            clen = int(ln.split(b":", 1)[1])
    while len(buf) < clen:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("server closed mid-body")
        buf += chunk
    return status, head, buf[clen:]


def _connect(addr):
    sock = socket.create_connection(addr, timeout=30)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def _hops():
    from ray_tpu._private import perf_stats

    return {h: perf_stats.counter("serve_hops", {"hop": h}).value
            for h in ("router", "direct", "fallback")}


def _http_leg(addrs, path, n, concurrency, reuse=True):
    """Keep-alive (or connection-per-request) leg against one or more
    proxy addresses; returns (stats, hops_delta, cpu_saturation)."""
    lock = threading.Lock()
    latencies: list = []
    paths = {"direct": 0, "routed": 0, "fallback": 0}

    def worker(per, wid):
        addr = addrs[wid % len(addrs)]
        sock = None
        buf = b""
        for i in range(per):
            t0 = time.perf_counter()
            if sock is None or not reuse:
                sock = _connect(addr)
                buf = b""
            sock.sendall(_request_bytes(path, i))
            status, head, buf = _read_response(sock, buf)
            assert status == 200, status
            if not reuse:
                sock.close()
                sock = None
            dt = time.perf_counter() - t0
            taken = "routed"
            for ln in head.split(b"\r\n"):
                if ln.lower().startswith(b"x-serve-path:"):
                    taken = ln.split(b":", 1)[1].strip().decode()
            with lock:
                latencies.append(dt)
                paths[taken] = paths.get(taken, 0) + 1
        if sock is not None:
            sock.close()

    before = _hops()
    per = max(1, n // concurrency)
    wall, cpu = _run_workers(worker, concurrency, per)
    after = _hops()
    stats = _stats(latencies, wall)
    stats["dispatch_paths"] = paths
    hops = {k: after[k] - before[k] for k in after}
    saturation = round(cpu / max(wall, 1e-9) / (os.cpu_count() or 1), 3)
    return stats, hops, saturation


def _chaos_section(fleet, path, seconds, concurrency):
    """Sustained fleet load while one proxy and one replica are killed
    mid-window: p99 stays bounded, nothing double-executes (server-side
    counters — see the deployment below), healthz names the dead
    components and recovers."""
    import ray_tpu
    from ray_tpu._private import health

    addrs = fleet.addresses()
    stop = threading.Event()
    lock = threading.Lock()
    latencies: list = []
    statuses: dict = {}
    lost = [0]

    def worker(wid):
        addr = addrs[wid % len(addrs)]
        sock = None
        buf = b""
        i = 0
        while not stop.is_set():
            i += 1
            t0 = time.perf_counter()
            try:
                if sock is None:
                    sock = _connect(addr)
                    buf = b""
                sock.sendall(_request_bytes(path, f"c{wid}-{i}"))
                status, _head, buf = _read_response(sock, buf)
            except (OSError, ConnectionError):
                with lock:
                    lost[0] += 1
                try:
                    if sock is not None:
                        sock.close()
                except OSError:
                    pass
                sock = None
                time.sleep(0.05)
                continue
            with lock:
                latencies.append(time.perf_counter() - t0)
                statuses[status] = statuses.get(status, 0) + 1
        if sock is not None:
            sock.close()

    workers = [threading.Thread(target=worker, args=(i,))
               for i in range(concurrency)]
    t0 = time.monotonic()
    for t in workers:
        t.start()
    time.sleep(seconds * 0.3)

    # -- kill one replica and one proxy ------------------------------
    from ray_tpu._private.worker import global_worker

    names = [n for n in global_worker().gcs.list_named_actors()
             if str(n).startswith("SERVE_REPLICA::BenchNoop::")]
    kill_at = round(time.monotonic() - t0, 2)
    ray_tpu.kill(ray_tpu.get_actor(names[0]))
    ray_tpu.kill(fleet.actors()[-1])

    degraded_at = recovered_at = None
    degraded_reasons: set = set()
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        reasons = health.provider_reasons()
        if reasons:
            degraded_reasons.update(reasons)
            if degraded_at is None:
                degraded_at = round(time.monotonic() - t0, 2)
            recovered_at = None  # still (or again) degraded
        elif degraded_at is not None and recovered_at is None:
            recovered_at = round(time.monotonic() - t0, 2)
        time.sleep(0.01)
    stop.set()
    for t in workers:
        t.join(timeout=30)
    wall = time.monotonic() - t0
    stats = _stats(sorted(latencies), wall)
    return {
        "window_s": round(wall, 2),
        "kill_at_s": kill_at,
        "degraded_at_s": degraded_at,
        "degraded_reasons": sorted(degraded_reasons),
        "recovered_at_s": recovered_at,
        "statuses": statuses,
        "transport_errors": lost[0],
        **stats,
    }


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=300)
    parser.add_argument("--concurrency", type=int, default=8)
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--proxies", type=int, default=2)
    parser.add_argument("--replica-direct", choices=("on", "off", "both"),
                        default="both")
    parser.add_argument("--chaos", action="store_true")
    parser.add_argument("--chaos-seconds", type=float, default=6.0)
    parser.add_argument("--out", default="")
    parser.add_argument("--scale-out", default="")
    args = parser.parse_args()

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private.config import ray_config
    from benchmarks.perf_bench import host_calibration

    cal = host_calibration()
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=max(8, args.proxies + args.replicas + 2))

    counts_lock = threading.Lock()
    exec_counts: dict = {}

    @serve.deployment(name="BenchNoop", num_replicas=args.replicas,
                      max_concurrent_queries=32)
    class Noop:
        def __call__(self, payload):
            rid = payload.get("payload")
            if isinstance(rid, str):  # chaos ids: double-exec witness
                with counts_lock:
                    exec_counts[rid] = exec_counts.get(rid, 0) + 1
            return {"echo": rid}

    handle = serve.run(Noop.bind(), route_prefix="/noop")

    # -- handle path (the in-process ceiling) ----------------------------
    lat: list = []
    lock = threading.Lock()
    ray_tpu.get(handle.remote({"payload": -1}))

    def handle_worker(n, _wid):
        for i in range(n):
            t0 = time.perf_counter()
            out = ray_tpu.get(handle.remote({"payload": i}))
            dt = time.perf_counter() - t0
            assert out["echo"] == i
            with lock:
                lat.append(dt)

    per = max(1, args.requests // args.concurrency)
    wall, _cpu = _run_workers(handle_worker, args.concurrency, per)
    handle_stats = _stats(lat, wall)

    # -- single proxy: routed vs direct (same-run A/B) -------------------
    proxy = serve.start_http_proxy()
    single = [("127.0.0.1", proxy.port)]
    http_n = max(100, args.requests)
    legs = {}
    modes = {"both": ("off", "on"), "on": ("on",),
             "off": ("off",)}[args.replica_direct]
    # Fair warmup BEFORE the first measured leg: executor-pool growth,
    # connection machinery, and the direct table all reach steady
    # state under both modes, so leg ORDER doesn't hand the later leg
    # a warm-start advantage (an early revision showed a phantom 1.6x
    # from exactly this).
    for mode in ("off", "on"):
        ray_config.serve_replica_direct = mode == "on"
        _http_leg(single, "/noop", max(64, args.concurrency * 4),
                  args.concurrency)
    # Best-of-N per side with ALTERNATING order (the perf_bench A/B
    # discipline): this box is 1 core and noisily shared, so a single
    # leg per side swings ±30% run-to-run; the best attempt per side
    # under identical conditions is the comparable number. Hops are
    # summed across attempts (the router=0 claim must hold for every
    # direct attempt, not just the best one).
    attempts = 3 if len(modes) > 1 else 1
    for _ in range(attempts):
        for mode in modes:
            ray_config.serve_replica_direct = mode == "on"
            stats, hops, sat = _http_leg(single, "/noop", http_n,
                                         args.concurrency)
            stats["cpu_saturation"] = sat
            key = ("http_single_direct" if mode == "on"
                   else "http_single_routed")
            prev = legs.get(key)
            if prev is None:
                stats["hops"] = hops
                stats["attempts"] = 1
                legs[key] = stats
            else:
                merged_hops = {k: prev["hops"][k] + hops[k]
                               for k in hops}
                if stats["rps"] > prev["rps"]:
                    stats["hops"] = merged_hops
                    stats["attempts"] = prev["attempts"] + 1
                    legs[key] = stats
                else:
                    prev["hops"] = merged_hops
                    prev["attempts"] += 1
    ray_config.serve_replica_direct = True

    # -- connection-per-request floor ------------------------------------
    pc_stats, _hops_d, _sat = _http_leg(
        single, "/noop", max(100, args.requests // 3),
        args.concurrency, reuse=False)
    legs["http_per_connection"] = pc_stats

    # -- proxy fleet -----------------------------------------------------
    fleet = serve.ProxyFleet(num_proxies=args.proxies)
    try:
        # Warm every proxy's routes + direct table.
        for addr in fleet.addresses():
            s = _connect(addr)
            s.sendall(_request_bytes("/noop", 0))
            _read_response(s, b"")
            s.close()
        time.sleep(0.2)
        stats, hops, sat = _http_leg(fleet.addresses(), "/noop",
                                     http_n, args.concurrency)
        stats["hops"] = hops
        stats["cpu_saturation"] = sat
        legs["http_fleet_direct"] = stats

        chaos = None
        if args.chaos:
            chaos = _chaos_section(fleet, "/noop", args.chaos_seconds,
                                   args.concurrency)
            with counts_lock:
                chaos["double_executed"] = sum(
                    1 for v in exec_counts.values() if v > 1)
        fleet_stats = fleet.stats()
    finally:
        fleet.shutdown()

    proxy_stats = proxy.stats()
    serve.shutdown()
    ray_tpu.shutdown()

    single_ka = legs.get("http_single_direct") or \
        legs.get("http_single_routed")
    routed = legs.get("http_single_routed")
    fleet_leg = legs["http_fleet_direct"]
    doc = {
        "metric": "serve_noop_handle_rps",
        "value": handle_stats["rps"],
        "unit": "requests/s",
        "schema": "serve_rps_bench/r15",
        "host_calibration": cal,
        "detail": {
            "handle": handle_stats,
            **legs,
            "http_rps_pct_of_handle": round(
                100.0 * single_ka["rps"]
                / max(handle_stats["rps"], 1e-9), 1),
            "direct_vs_routed_rps": round(
                legs["http_single_direct"]["rps"] / routed["rps"], 3)
            if routed and "http_single_direct" in legs else None,
            "fleet_vs_single_rps": round(
                fleet_leg["rps"] / max(single_ka["rps"], 1e-9), 3),
            "proxy": proxy_stats,
            "fleet": fleet_stats,
            "replicas": args.replicas,
            "proxies": args.proxies,
            "concurrency": args.concurrency,
            "host_cpus": os.cpu_count(),
        },
    }
    if chaos is not None:
        doc["detail"]["chaos"] = chaos

    out = json.dumps(doc)
    print(out)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(json.dumps(doc, indent=2, sort_keys=True) + "\n")
    if args.scale_out:
        scale_doc = {
            "schema": "scale_serve/r15",
            "host_calibration": cal,
            "sections": {
                "saturation": {
                    "fleet": fleet_leg,
                    "single": single_ka,
                    "handle": handle_stats,
                },
                "chaos": chaos,
            },
        }
        with open(args.scale_out, "w", encoding="utf-8") as f:
            f.write(json.dumps(scale_doc, indent=2, sort_keys=True)
                    + "\n")


if __name__ == "__main__":
    main()
