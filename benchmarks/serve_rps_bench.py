"""Plain-deployment serving micro-benchmark: RPS + latency percentiles
for a noop deployment through the ServeHandle path, and through the HTTP
proxy (reference: `release/serve_tests/workloads/serve_micro_benchmark.py`
— handle/HTTP throughput on trivial deployments, the serving control
plane's overhead floor distinct from any model cost).

The HTTP path is measured two ways:

- **keep-alive**: each worker holds ONE persistent connection, like any
  real client/LB — the event-loop proxy's steady state;
- **connection-per-request**: a fresh TCP connect every request — what
  every streamed response used to cost when SSE forced
  ``Connection: close``, and the worst case for naive clients.

Headline comparability: ``http_rps_pct_of_handle`` normalizes the HTTP
ingress against the in-process handle path measured in the SAME run, so
the number survives host-speed changes between rounds.

Usage: python benchmarks/serve_rps_bench.py [--requests 300]
Writes one JSON line to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def percentile(sorted_vals, q):
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(len(sorted_vals) * q))]


def _stats(lat, wall):
    lat = sorted(lat)
    if not lat:
        return {"rps": 0.0, "p50_ms": 0.0, "p95_ms": 0.0, "requests": 0}
    return {
        "rps": round(len(lat) / wall, 1),
        "p50_ms": round(percentile(lat, 0.5) * 1e3, 2),
        "p95_ms": round(percentile(lat, 0.95) * 1e3, 2),
        "requests": len(lat),
    }


def _run_workers(worker, concurrency, per):
    threads = [threading.Thread(target=worker, args=(per,))
               for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return time.perf_counter() - t0


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=300)
    parser.add_argument("--concurrency", type=int, default=8)
    parser.add_argument("--replicas", type=int, default=2)
    args = parser.parse_args()

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)

    @serve.deployment(num_replicas=args.replicas,
                      max_concurrent_queries=32)
    class Noop:
        def __call__(self, payload):
            return {"echo": payload}

    handle = serve.run(Noop.bind(), route_prefix="/noop")

    # -- handle path ------------------------------------------------------
    lat = []
    lock = threading.Lock()
    # warmup
    ray_tpu.get(handle.remote("w"))

    def worker(n):
        for i in range(n):
            t0 = time.perf_counter()
            out = ray_tpu.get(handle.remote(i))
            dt = time.perf_counter() - t0
            assert out["echo"] == i
            with lock:
                lat.append(dt)

    per = max(1, args.requests // args.concurrency)
    wall = _run_workers(worker, args.concurrency, per)
    handle_stats = _stats(lat, wall)

    # -- HTTP proxy: keep-alive ------------------------------------------
    # Same concurrency as the handle path (one persistent connection per
    # worker) so the two headline numbers are comparable. Raw sockets —
    # a wrk-style minimal client — so the measurement is the SERVER's
    # throughput, not http.client's per-request parsing cost (which
    # would eat the same host CPUs the proxy needs).
    import json as _json
    import socket

    proxy = serve.start_http_proxy()

    def _request_bytes(i):
        body = _json.dumps({"payload": i}).encode()
        return (b"POST /noop HTTP/1.1\r\nHost: bench\r\n"
                b"Content-Type: application/json\r\nContent-Length: "
                + str(len(body)).encode() + b"\r\n\r\n" + body)

    def _read_response(sock, buf):
        """Read one Content-Length-framed response; returns (status,
        leftover buf)."""
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("server closed")
            buf += chunk
        head, buf = buf.split(b"\r\n\r\n", 1)
        status = int(head.split(b" ", 2)[1])
        clen = 0
        for ln in head.split(b"\r\n")[1:]:
            if ln.lower().startswith(b"content-length:"):
                clen = int(ln.split(b":", 1)[1])
        while len(buf) < clen:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("server closed mid-body")
            buf += chunk
        return status, buf[clen:]

    def _connect():
        sock = socket.create_connection(("127.0.0.1", proxy.port),
                                        timeout=30)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def make_http_worker(latencies, reuse_connection):
        def http_worker(n):
            sock = None
            buf = b""
            for i in range(n):
                t0 = time.perf_counter()
                if sock is None or not reuse_connection:
                    sock = _connect()
                    buf = b""
                sock.sendall(_request_bytes(i))
                status, buf = _read_response(sock, buf)
                assert status == 200, status
                if not reuse_connection:
                    sock.close()
                    sock = None
                with lock:
                    latencies.append(time.perf_counter() - t0)
            if sock is not None:
                sock.close()
        return http_worker

    http_n = max(100, args.requests)
    per = max(1, http_n // args.concurrency)
    ka_lat: list = []
    ka_wall = _run_workers(make_http_worker(ka_lat, True),
                           args.concurrency, per)
    ka_stats = _stats(ka_lat, ka_wall)

    # -- HTTP proxy: connection-per-request ------------------------------
    pc_n = max(100, args.requests // 3)
    per = max(1, pc_n // args.concurrency)
    pc_lat: list = []
    pc_wall = _run_workers(make_http_worker(pc_lat, False),
                           args.concurrency, per)
    pc_stats = _stats(pc_lat, pc_wall)

    proxy_stats = proxy.stats()
    serve.shutdown()
    ray_tpu.shutdown()

    print(json.dumps({
        "metric": "serve_noop_handle_rps",
        "value": handle_stats["rps"],
        "unit": "requests/s",
        "detail": {
            "handle": handle_stats,
            "http_keepalive": ka_stats,
            "http_per_connection": pc_stats,
            "http_rps_pct_of_handle": round(
                100.0 * ka_stats["rps"] / handle_stats["rps"], 1),
            "proxy": proxy_stats,
            "replicas": args.replicas,
            "concurrency": args.concurrency,
            "host_cpus": os.cpu_count(),
        },
    }))


if __name__ == "__main__":
    main()
