"""Plain-deployment serving micro-benchmark: RPS + latency percentiles
for a noop deployment through the ServeHandle path, and through the HTTP
proxy (reference: `release/serve_tests/workloads/serve_micro_benchmark.py`
— handle/HTTP throughput on trivial deployments, the serving control
plane's overhead floor distinct from any model cost).

Usage: python benchmarks/serve_rps_bench.py [--requests 300]
Writes one JSON line to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def percentile(sorted_vals, q):
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(len(sorted_vals) * q))]


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=300)
    parser.add_argument("--concurrency", type=int, default=8)
    parser.add_argument("--replicas", type=int, default=2)
    args = parser.parse_args()

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)

    @serve.deployment(num_replicas=args.replicas,
                      max_concurrent_queries=32)
    class Noop:
        def __call__(self, payload):
            return {"echo": payload}

    handle = serve.run(Noop.bind(), route_prefix="/noop")

    # -- handle path ------------------------------------------------------
    lat = []
    lock = threading.Lock()
    # warmup
    ray_tpu.get(handle.remote("w"))

    def worker(n):
        for i in range(n):
            t0 = time.perf_counter()
            out = ray_tpu.get(handle.remote(i))
            dt = time.perf_counter() - t0
            assert out["echo"] == i
            with lock:
                lat.append(dt)

    per = args.requests // args.concurrency
    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(per,))
               for _ in range(args.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lat.sort()
    handle_stats = {
        "rps": round(len(lat) / wall, 1),
        "p50_ms": round(percentile(lat, 0.5) * 1e3, 2),
        "p95_ms": round(percentile(lat, 0.95) * 1e3, 2),
        "requests": len(lat),
    }

    # -- HTTP proxy path --------------------------------------------------
    # Persistent connections (the proxy speaks HTTP/1.1 keep-alive):
    # each worker holds ONE connection, like any real client/LB would —
    # per-request TCP connects measured the handshake, not the proxy.
    import http.client
    import json as _json

    proxy = serve.start_http_proxy()
    http_lat = []

    def http_worker(n):
        conn = http.client.HTTPConnection("127.0.0.1", proxy.port,
                                          timeout=30)
        for i in range(n):
            t0 = time.perf_counter()
            body = _json.dumps({"payload": i}).encode()
            conn.request("POST", "/noop", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            payload = resp.read()
            assert resp.status == 200, (resp.status, payload[:200])
            with lock:
                http_lat.append(time.perf_counter() - t0)
        conn.close()

    http_n = max(100, args.requests // 3)
    per = http_n // 4
    t0 = time.perf_counter()
    threads = [threading.Thread(target=http_worker, args=(per,))
               for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    http_wall = time.perf_counter() - t0
    http_lat.sort()

    serve.shutdown()
    ray_tpu.shutdown()

    print(json.dumps({
        "metric": "serve_noop_handle_rps",
        "value": handle_stats["rps"],
        "unit": "requests/s",
        "detail": {
            "handle": handle_stats,
            "http": {
                "rps": round(len(http_lat) / http_wall, 1),
                "p50_ms": round(percentile(http_lat, 0.5) * 1e3, 2),
                "p95_ms": round(percentile(http_lat, 0.95) * 1e3, 2),
                "requests": len(http_lat),
            },
            "replicas": args.replicas,
            "concurrency": args.concurrency,
            "host_cpus": os.cpu_count(),
        },
    }))


if __name__ == "__main__":
    main()
