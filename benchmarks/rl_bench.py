"""RLlib north-star benchmark: environment samples/sec through the
rollout-worker fleet + PPO train throughput.

BASELINE.json lists "RLlib samples/sec" as a north star the reference
measures nightly without committing an absolute number; this records ours
for the CartPole PPO config the test suite learns with.

Usage: python benchmarks/rl_bench.py [--iters 6] [--workers 2]
Writes one JSON line to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--iters", type=int, default=6)
    parser.add_argument("--workers", type=int, default=2)
    parser.add_argument("--envs-per-worker", type=int, default=128)
    parser.add_argument("--fragment", type=int, default=64)
    args = parser.parse_args()

    import ray_tpu
    from ray_tpu.rl import PPOConfig

    ray_tpu.init(num_cpus=max(8, args.workers * 2),
                 ignore_reinit_error=True)
    config = (PPOConfig()
              .environment("CartPole-v1")
              .rollouts(num_rollout_workers=args.workers,
                        num_envs_per_worker=args.envs_per_worker,
                        rollout_fragment_length=args.fragment)
              .training(lr=3e-3, num_sgd_iter=8, sgd_minibatch_size=256)
              .debugging(seed=0))
    algo = config.build()

    algo.train()  # warm-up iteration: compiles the update program
    samples = 0
    t0 = time.perf_counter()
    for _ in range(args.iters):
        result = algo.train()
        samples += result.get("num_env_steps_sampled_this_iter",
                              args.workers * args.envs_per_worker *
                              args.fragment)
    wall = time.perf_counter() - t0
    reward = result.get("episode_reward_mean", 0.0)
    algo.cleanup()
    ray_tpu.shutdown()

    print(json.dumps({
        "metric": "rl_env_samples_per_s",
        "value": round(samples / wall, 1),
        "unit": "env_steps/s",
        "detail": {
            "algo": "PPO", "env": "CartPole-v1",
            "host_cpus": os.cpu_count(),
            "workers": args.workers,
            "envs_per_worker": args.envs_per_worker,
            "fragment": args.fragment,
            "iters": args.iters,
            "train_iters_per_s": round(args.iters / wall, 3),
            "episode_reward_mean": round(float(reward), 1),
        },
    }))


if __name__ == "__main__":
    main()
