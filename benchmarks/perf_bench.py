"""Schema-versioned core-perf bench emitter with host calibration.

Wraps the raw micro-op suite (`benchmarks/ray_perf.py`) in a stable,
machine-comparable envelope. PR 1 found a ~13x single-core speed gap
between bench hosts, which makes absolute numbers from different rounds
incomparable; every emission therefore carries:

- ``schema_version``: bump on any metric rename/semantic change so a
  reader never silently misparses an old file;
- ``host_calibration``: cpu count plus two single-thread reference
  rates measured in-process right before the suite (a pure-Python spin
  and a lock round-trip rate — the two costs the control plane is made
  of). Cross-host comparisons divide metrics by the calibration to
  compare RATIOS, not absolutes.

Usage: python benchmarks/perf_bench.py [--out BENCH_PERF_rNN.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCHEMA_VERSION = 2


def host_calibration(seconds: float = 0.25) -> dict:
    """Single-thread reference rates for cross-host ratio comparisons."""
    # Pure-Python spin: integer loop iterations per second.
    t0 = time.perf_counter()
    count = 0
    while time.perf_counter() - t0 < seconds:
        for _ in range(1000):
            count += 1
    spin_mops = count / (time.perf_counter() - t0) / 1e6

    # Lock round trips per second (the control plane's unit cost).
    lock = threading.Lock()
    t0 = time.perf_counter()
    locks = 0
    while time.perf_counter() - t0 < seconds:
        for _ in range(1000):
            with lock:
                pass
            locks += 1
    lock_mops = locks / (time.perf_counter() - t0) / 1e6

    # Same-host memcpy envelope: the hardware bound every cross-process
    # object-plane number is judged against (BENCH_OBJ acceptance:
    # xproc 64MB get within 5x of THIS, measured in the same run).
    memcpy_gbps = 0.0
    try:
        import numpy as np

        src = np.ones(64 * 2**20, np.uint8)
        dst = np.empty_like(src)
        dst[:] = src  # warm/populate both buffers
        for _ in range(5):
            t0 = time.perf_counter()
            dst[:] = src
            memcpy_gbps = max(memcpy_gbps,
                              64 / 1024 / (time.perf_counter() - t0))
    except Exception:
        pass

    return {
        "cpu_count": os.cpu_count(),
        "python_spin_mops_per_s": round(spin_mops, 3),
        "lock_roundtrip_mops_per_s": round(lock_mops, 3),
        "memcpy_GBps": round(memcpy_gbps, 2),
        "note": "compare cross-host metrics as ratios against these "
                "single-thread rates, not as absolutes",
    }


# -- observability A/B (instrumented vs. baseline) ---------------------------
#
# The observability plane must be free on the paths PR 2 optimized. This
# mode measures the submit and wait hot paths with the fast-path stats
# ENABLED (plus, in cluster mode, event/metric shipping running) against
# the same paths with instrumentation off, and asserts the overhead
# stays under OBS_OVERHEAD_BUDGET. Noise guard: best-of-R per side,
# interleaved (on/off/on/off...), with a bounded retry before failing.

OBS_OVERHEAD_BUDGET = 0.05  # <5% on submit and wait


def _measure_submit_wait(n_tasks: int = 5000, n_refs: int = 1000,
                         wait_rounds: int = 200) -> dict:
    """One sample of the two hot paths in the CURRENT process state.

    Both legs are pinned to the pure path under test — concurrent
    execution chaos (fast-dispatch bimodality, executor thread churn)
    would otherwise swamp a 5% effect on a 2-core box:

    - submit: tasks parked on an unresolved dependency, so each
      ``.remote()`` exercises spec construction + submit bookkeeping
      (where the monotonic stamp lives) with zero dispatch racing the
      timer; the gate then opens and everything drains off-clock.
    - wait: repeated ``wait`` over RESOLVED refs — the one-lock
      snapshot pass PR 2 built, where the wait counters live.

    GC is held across each timed region (re-enabled after) so a
    collection landing in one side's window doesn't masquerade as
    instrumentation overhead.
    """
    import gc
    import threading as _threading

    import ray_tpu

    @ray_tpu.remote(num_cpus=0, max_concurrency=2)
    class Gate:
        # max_concurrency=2: open() must run while block() holds the
        # other executor thread.
        def __init__(self):
            self._ev = _threading.Event()

        def open(self):
            self._ev.set()
            return True

        def block(self):
            self._ev.wait(600)
            return None

    gate = Gate.remote()
    blocker = gate.block.remote()

    @ray_tpu.remote(num_cpus=0)
    def tiny(dep):
        return None

    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        refs = [tiny.remote(blocker) for _ in range(n_tasks)]
        submit_s = time.perf_counter() - t0
    finally:
        gc.enable()
    ray_tpu.get(gate.open.remote(), timeout=60)
    ray_tpu.get(refs, timeout=300)

    pool = [ray_tpu.put(i) for i in range(n_refs)]
    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        for _ in range(wait_rounds):
            ready, _ = ray_tpu.wait(pool, num_returns=len(pool),
                                    timeout=30)
            assert len(ready) == len(pool)
        wait_s = time.perf_counter() - t0
    finally:
        gc.enable()
    del pool, refs
    return {"submit_per_s": n_tasks / submit_s,
            "wait_rounds_per_s": wait_rounds / wait_s}


def ab_observability(repeats: int = 5, attempts: int = 3) -> dict:
    """Instrumented-vs-baseline A/B over the submit/wait hot paths.
    Returns the envelope section including a pass/fail guard."""
    import ray_tpu
    from ray_tpu._private import perf_stats

    def side(enabled: bool) -> dict:
        perf_stats.set_enabled(enabled)
        try:
            sample = _measure_submit_wait()
        finally:
            perf_stats.set_enabled(True)
        # Keep per-sample process state flat: drain the event-buffer
        # delta so neither side accumulates a growing dirty set.
        from ray_tpu._private.worker import global_worker

        global_worker().task_events.drain_updates(10 ** 9)
        return sample

    result = None
    for attempt in range(attempts):
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=2)
        try:
            on = {"submit_per_s": 0.0, "wait_rounds_per_s": 0.0}
            off = {"submit_per_s": 0.0, "wait_rounds_per_s": 0.0}
            side(True)  # warm-up (executor pool, templates, JIT-ish)
            for i in range(repeats):
                # Alternate which side runs first: heap growth / GC
                # drift over the run must not systematically tax
                # whichever side happens to go second.
                pair = ((True, on), (False, off)) if i % 2 == 0 \
                    else ((False, off), (True, on))
                for flag, best in pair:
                    sample = side(flag)
                    for k in best:
                        best[k] = max(best[k], sample[k])
        finally:
            perf_stats.set_enabled(True)
            ray_tpu.shutdown()
        overhead = {
            "submit_overhead": 1.0 - on["submit_per_s"]
            / off["submit_per_s"],
            "wait_overhead": 1.0 - on["wait_rounds_per_s"]
            / off["wait_rounds_per_s"],
        }
        ok = all(v < OBS_OVERHEAD_BUDGET for v in overhead.values())
        result = {
            "budget": OBS_OVERHEAD_BUDGET,
            "repeats": repeats,
            "attempt": attempt + 1,
            "instrumented": on,
            "baseline": off,
            **{k: round(v, 4) for k, v in overhead.items()},
            "pass": ok,
        }
        if ok:
            return result
    return result


# -- compact-queue tax guard (--ab-sched) ------------------------------------
#
# The compact queued representation (QueuedTaskHeader, materialized at
# dispatch) exists for million-task backlogs; it must not tax the
# 1-task case. This mode measures the submit hot path (dep-parked
# submissions: header mint + park, zero dispatch racing the timer) and
# the single-task submit→get roundtrip (where the dispatch-time
# materialization cost lives) with sched_compact_queue on vs off.

SCHED_OVERHEAD_BUDGET = 0.05  # <5% on submit and 1-task roundtrip


def _measure_sched_paths(n_tasks: int = 4000,
                         n_roundtrips: int = 600) -> dict:
    """One sample of the compact-queue-sensitive paths in the CURRENT
    process state: parked submits (pure submit-side cost) and
    sequential 1-task roundtrips (submit + fast dispatch +
    materialization + result)."""
    import gc

    sample = _measure_submit_wait(n_tasks=n_tasks, n_refs=50,
                                  wait_rounds=10)

    import ray_tpu

    @ray_tpu.remote(num_cpus=0)
    def one(x):
        return x

    ray_tpu.get(one.remote(0), timeout=30)  # warm template + executor
    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        for i in range(n_roundtrips):
            ray_tpu.get(one.remote(i), timeout=30)
        rt_s = time.perf_counter() - t0
    finally:
        gc.enable()
    return {"submit_per_s": sample["submit_per_s"],
            "roundtrips_per_s": n_roundtrips / rt_s}


def ab_sched(repeats: int = 5, attempts: int = 3) -> dict:
    """Compact-queue on-vs-off A/B over the 1-task fast path. Same
    noise discipline as ab_observability: best-of-R per side,
    interleaved, bounded retry."""
    import ray_tpu
    from ray_tpu._private.config import ray_config

    def side(compact: bool) -> dict:
        ray_config.sched_compact_queue = compact
        try:
            return _measure_sched_paths()
        finally:
            ray_config.sched_compact_queue = True

    result = None
    for attempt in range(attempts):
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=2)
        try:
            on = {"submit_per_s": 0.0, "roundtrips_per_s": 0.0}
            off = {"submit_per_s": 0.0, "roundtrips_per_s": 0.0}
            side(True)  # warm-up
            for i in range(repeats):
                pair = ((True, on), (False, off)) if i % 2 == 0 \
                    else ((False, off), (True, on))
                for flag, best in pair:
                    sample = side(flag)
                    for k in best:
                        best[k] = max(best[k], sample[k])
        finally:
            ray_config.sched_compact_queue = True
            ray_tpu.shutdown()
        overhead = {
            "submit_overhead": 1.0 - on["submit_per_s"]
            / off["submit_per_s"],
            "roundtrip_overhead": 1.0 - on["roundtrips_per_s"]
            / off["roundtrips_per_s"],
        }
        ok = all(v < SCHED_OVERHEAD_BUDGET for v in overhead.values())
        result = {
            "budget": SCHED_OVERHEAD_BUDGET,
            "repeats": repeats,
            "attempt": attempt + 1,
            "compact": on,
            "full_spec": off,
            **{k: round(v, 4) for k, v in overhead.items()},
            "pass": ok,
        }
        if ok:
            return result
    return result


# -- multi-process head A/B (--ab-head) --------------------------------------
#
# PR 19: the head's row state shards across N head worker processes,
# each with its own group-commit window. Two claims to pin, same-run:
#
# 1. The sharded plane SCALES (or, on a single-core host, holds
#    GIL-bound parity): streaming M durable rows through N shard
#    processes vs 1 shard process — the bottleneck being each shard's
#    sqlite apply+commit, N shards absorb it in parallel when cores
#    exist. On one core the shard processes timeshare the same CPU, so
#    the honest expectation is PARITY (documented fallback arm), not
#    speedup; the floor catches the failure mode that matters there
#    (per-shard overhead making N shards *slower* than 1).
# 2. head_shards=1 (the default) costs NOTHING: the local submit/
#    roundtrip fast paths never touch shard code regardless of the
#    config value — a same-run knob-on-vs-off A/B within 5%.

HEAD_SCALING_MIN = 1.15    # multi-core: N shards beat 1 by >=15%
HEAD_PARITY_MIN = 0.70     # single-core floor: N shards >= 0.7x of 1
HEAD_CONTROL_BUDGET = 0.05  # default path: knob must be free (<5%)


def _head_router_side(n_shards: int, rows: int = 4000,
                      grants: int = 300) -> dict:
    """One arm: stream `rows` durable directory rows through a live
    N-shard router (real subprocesses, real sqlite group commit),
    flush to the acked boundary, then time the sync lease-decision
    path."""
    import shutil
    import tempfile

    from ray_tpu._private.head_shards import ShardRouter

    db_dir = tempfile.mkdtemp(prefix=f"ab_head_{n_shards}_")
    router = ShardRouter(n_shards, db_dir, commit_interval_s=0.005)
    try:
        t0 = time.perf_counter()
        for i in range(rows):
            router.put("objects", b"obj-%08d" % i, ("10.0.0.1", i))
        assert router.flush(), "shard flush failed"
        stream_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for i in range(grants):
            router.lease_register(b"lease-%06d" % i, "node-a", cap=1)
        grant_s = time.perf_counter() - t0
        return {"rows_per_s": round(rows / stream_s, 1),
                "grants_per_s": round(grants / grant_s, 1)}
    finally:
        router.close()
        shutil.rmtree(db_dir, ignore_errors=True)


def ab_head(repeats: int = 3, attempts: int = 3) -> dict:
    """1-shard vs N-shard same-run A/B over the sharded control plane,
    plus the head_shards=1 control guard. Same noise discipline as
    ab_sched: best-of-R per side, interleaved, bounded retry."""
    import ray_tpu
    from ray_tpu._private.config import ray_config

    cpus = os.cpu_count() or 1
    n_shards = min(4, max(2, cpus))
    single_core = cpus <= 1

    result = None
    for attempt in range(attempts):
        # -- router scaling arms (no ray runtime involved) -------------
        one = {"rows_per_s": 0.0, "grants_per_s": 0.0}
        many = {"rows_per_s": 0.0, "grants_per_s": 0.0}
        _head_router_side(1, rows=500, grants=50)  # warm-up (build/fs)
        for i in range(repeats):
            pair = ((1, one), (n_shards, many)) if i % 2 == 0 \
                else ((n_shards, many), (1, one))
            for shards, best in pair:
                sample = _head_router_side(shards)
                for k in best:
                    best[k] = max(best[k], sample[k])
        scaling = round(
            many["rows_per_s"] / max(one["rows_per_s"], 0.1), 3)
        floor = HEAD_PARITY_MIN if single_core else HEAD_SCALING_MIN
        scale_ok = scaling >= floor

        # -- head_shards=1 control: the knob must be free --------------
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=2)
        try:
            base = {"submit_per_s": 0.0, "roundtrips_per_s": 0.0}
            knob = {"submit_per_s": 0.0, "roundtrips_per_s": 0.0}
            _measure_sched_paths()  # warm-up
            for i in range(repeats):
                for value, best in (((1, base), (8, knob))
                                    if i % 2 == 0
                                    else ((8, knob), (1, base))):
                    ray_config.head_shards = value
                    try:
                        sample = _measure_sched_paths()
                    finally:
                        ray_config.head_shards = 1
                    for k in best:
                        best[k] = max(best[k], sample[k])
        finally:
            ray_config.head_shards = 1
            ray_tpu.shutdown()
        control_overhead = {
            "submit_overhead": 1.0 - knob["submit_per_s"]
            / max(base["submit_per_s"], 0.1),
            "roundtrip_overhead": 1.0 - knob["roundtrips_per_s"]
            / max(base["roundtrips_per_s"], 0.1),
        }
        control_ok = all(v < HEAD_CONTROL_BUDGET
                         for v in control_overhead.values())

        result = {
            "attempt": attempt + 1,
            "repeats": repeats,
            "n_shards": n_shards,
            "host_cpus": cpus,
            "router_1shard": one,
            "router_nshard": many,
            "scaling_x": scaling,
            "scaling_floor": floor,
            "single_core_parity_arm": single_core,
            "note": ("single-core host: shard processes timeshare one "
                     "CPU, so the documented expectation is GIL-bound "
                     "parity, not speedup; the floor rejects per-shard "
                     "overhead making N shards slower than 1"
                     if single_core else
                     f"multi-core host: {n_shards} shards must beat 1 "
                     f"by >={HEAD_SCALING_MIN}x"),
            "control": {"head_shards_1": base, "head_shards_8": knob,
                        **{k: round(v, 4)
                           for k, v in control_overhead.items()},
                        "budget": HEAD_CONTROL_BUDGET},
            "pass": scale_ok and control_ok,
        }
        if result["pass"]:
            return result
    return result


# -- yield-point hook tax guard (--ab-hooks) ---------------------------------
#
# raysan/raymc grow the sanitize_hooks yield-point map over time; each
# crossing costs one global load + None check when nothing is
# installed. A direct uninstalled-vs-uninstalled A/B cannot measure
# that (the crossing is compiled into the call sites), so the guard
# multiplies two robust numbers instead: the measured ns/crossing of an
# UNINSTALLED sched_point, and a census of crossings-per-op taken by
# installing a counting hook over the same dep-parked submit /
# resolved-wait workload the observability A/B pins. Their product
# bounds the hook tax on each hot path; the budget is <1%. The census
# itself is also pinned: a future PR that drops a crossing into a
# per-object hot loop trips the count ceiling even if this host is too
# noisy to see the time.

HOOKS_TAX_BUDGET = 0.01    # <1% of submit / wait op time, PER FAMILY
# Two seam families share the call sites' hot paths: sched/crash
# points (raysan/raymc) and rayspec's spec-op taps. Each family's tax
# is bounded by the budget INDEPENDENTLY — a regression in either
# trips its own line instead of hiding in the other's headroom; the
# combined worst case is 2x the budget by construction.
# Census ceiling: total crossings per workload unit (one unit = one
# task + one put + one wait round). Today the whole workload crosses
# ~1 per unit (store.put per completion/put, store.wait per round); a
# crossing added inside a per-object or per-poll hot loop multiplies
# this and trips the guard even when host noise hides the time.
HOOKS_MAX_PER_UNIT = 2.0
# rayspec spec-op taps (spec.<core>.<op>, two phases per op) have their
# own census + ceiling: the decision cores sit ON the submit path (WFQ
# put/pop, dep park/ready), so their steady-state rate is inherently
# higher than sched points' — but still bounded per unit. A tap added
# inside a per-object inner loop trips this the same way.
SPEC_HOOKS_MAX_PER_UNIT = 12.0


def ab_hooks(attempts: int = 3) -> dict:
    """Bounded noise retry (same contract as the observability A/Bs):
    the tax fractions divide a fixed analytic cost by a MEASURED op
    time, so a host hiccup on the base measurement inflates them 2-3x;
    re-measure up to ``attempts`` times before calling it a failure."""
    result = None
    for _ in range(attempts):
        result = _ab_hooks_once()
        if result["pass"]:
            return result
    return result


def _ab_hooks_once() -> dict:
    import ray_tpu
    from ray_tpu._private import sanitize_hooks

    # The production default must BE the uninstalled fast path.
    uninstalled = (sanitize_hooks._sched_point is None
                   and sanitize_hooks._crash_point is None
                   and sanitize_hooks._spec_op is None)

    # ns per uninstalled crossing, best-of-3 chunks.
    n = 200_000
    best_ns = float("inf")
    crossing = sanitize_hooks.sched_point
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            crossing("router.handoff")
        best_ns = min(best_ns,
                      (time.perf_counter() - t0) / n * 1e9)
    # ns per uninstalled SPEC tap. The per-dispatch hot taps (WFQ
    # put/pop, dep park/ready, table ops, actor-call invoke) sit
    # behind an inline `if sanitize_hooks.spec_taps_active:` guard —
    # uninstalled they pay ONE module-attr load + truth test, no call,
    # no payload construction. Measure that pattern; the rarer
    # unguarded taps (quota ops fire only for quota'd jobs, actor/
    # apply taps only on fault paths) pay the call form, measured
    # separately for the report.
    best_spec_ns = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            if sanitize_hooks.spec_taps_active:
                pass
        best_spec_ns = min(best_spec_ns,
                           (time.perf_counter() - t0) / n * 1e9)
    best_spec_call_ns = float("inf")
    spec_crossing = sanitize_hooks.spec_op
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            spec_crossing("spec.wfq.put", "call", None, None)
        best_spec_call_ns = min(best_spec_call_ns,
                                (time.perf_counter() - t0) / n * 1e9)

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    try:
        n_tasks, n_refs, wait_rounds = 5000, 1000, 200
        _measure_submit_wait(n_tasks, n_refs, wait_rounds)  # warm-up
        base = _measure_submit_wait(n_tasks, n_refs, wait_rounds)

        counts = {}
        spec_counts = {}
        counts_lock = threading.Lock()

        def census(name):
            # Crossings fire concurrently from driver + executor pool
            # threads; a bare dict increment would lose counts and let
            # the per-unit ceiling under-read.
            with counts_lock:
                counts[name] = counts.get(name, 0) + 1

        def spec_census(name, phase, _obj, _payload):
            with counts_lock:
                key = f"{name}:{phase}"
                spec_counts[key] = spec_counts.get(key, 0) + 1

        sanitize_hooks.install_sched_point(census)
        sanitize_hooks.install_crash_point(census)
        sanitize_hooks.install_spec_op(spec_census)
        try:
            _measure_submit_wait(n_tasks, n_refs, wait_rounds)
            # While a recorder is installed, spec taps ALSO forward
            # their call phase into the sched seam (Schedule-gating
            # support) — exclude those from the sched census so the
            # sched ceiling keeps measuring sched points only.
            counts = {k: v for k, v in counts.items()
                      if not k.startswith("spec.")}
            total = sum(counts.values())
            spec_total = sum(spec_counts.values())
        finally:
            sanitize_hooks.install_sched_point(None)
            sanitize_hooks.install_crash_point(None)
            sanitize_hooks.install_spec_op(None)
    finally:
        ray_tpu.shutdown()

    # Attribute the census to ops conservatively: every crossing the
    # whole workload made is charged to BOTH paths (puts, executor
    # drains and teardown crossings included), so each per-op tax is
    # an overcount — if the overcount passes the 1% budget, the true
    # tax certainly does.
    per_submit = total / n_tasks
    per_wait_round = total / wait_rounds
    units = n_tasks + n_refs + wait_rounds
    per_unit = total / units
    spec_per_unit = spec_total / units
    # Spec taps attribute to the path that EXECUTES them: put/park run
    # on the submitting thread, pop/ready/sweep on the dispatch/
    # completion side (which the wait path observes). Each path is
    # still charged every tap of its side the WHOLE workload made —
    # the same conservative per-path overcount as the sched census.
    submit_points = ("spec.wfq.put", "spec.dep.park", "spec.quota.admit",
                     "spec.quota.charge", "spec.quota.lease_acquire",
                     "spec.call.invoke", "spec.table.")
    spec_submit = sum(v for k, v in spec_counts.items()
                      if k.startswith(submit_points))
    spec_complete = spec_total - spec_submit
    submit_op_ns = 1e9 / base["submit_per_s"]
    wait_op_ns = 1e9 / base["wait_rounds_per_s"]
    submit_tax = per_submit * best_ns / submit_op_ns
    wait_tax = per_wait_round * best_ns / wait_op_ns
    spec_submit_tax = (spec_submit / n_tasks) * best_spec_ns \
        / submit_op_ns
    spec_wait_tax = (spec_complete / wait_rounds) * best_spec_ns \
        / wait_op_ns
    ok = (uninstalled
          and submit_tax < HOOKS_TAX_BUDGET
          and wait_tax < HOOKS_TAX_BUDGET
          and spec_submit_tax < HOOKS_TAX_BUDGET
          and spec_wait_tax < HOOKS_TAX_BUDGET
          and per_unit <= HOOKS_MAX_PER_UNIT
          and spec_per_unit <= SPEC_HOOKS_MAX_PER_UNIT)
    return {
        "budget": HOOKS_TAX_BUDGET,
        "uninstalled_by_default": uninstalled,
        "ns_per_crossing_uninstalled": round(best_ns, 1),
        "ns_per_spec_tap_uninstalled": round(best_spec_ns, 1),
        "ns_per_spec_call_uninstalled": round(best_spec_call_ns, 1),
        "crossings_total": total,
        "crossings_by_point": dict(sorted(counts.items())),
        "crossings_per_workload_unit": round(per_unit, 4),
        "per_unit_ceiling": HOOKS_MAX_PER_UNIT,
        "spec_taps_total": spec_total,
        "spec_taps_by_point": dict(sorted(spec_counts.items())),
        "spec_taps_per_workload_unit": round(spec_per_unit, 4),
        "spec_per_unit_ceiling": SPEC_HOOKS_MAX_PER_UNIT,
        "submit_tax_fraction": round(submit_tax, 6),
        "wait_tax_fraction": round(wait_tax, 6),
        "spec_submit_tax_fraction": round(spec_submit_tax, 6),
        "spec_wait_tax_fraction": round(spec_wait_tax, 6),
        "pass": ok,
    }


def ab_job_tagging(repeats: int = 5, attempts: int = 3) -> dict:
    """Job-tag propagation A/B over the same submit/wait hot paths:
    every spec/put carrying an ambient tenant tag (job_id_for_submit +
    the per-entry store accounting) vs. untagged. Same best-of-R
    interleaving, budget, and bounded noise retry as the
    instrumentation A/B."""
    import ray_tpu
    from ray_tpu._private.task_spec import set_ambient_job_id

    def side(tagged: bool) -> dict:
        # "" pins genuinely-untagged: None would fall back to the
        # process default (RAY_TPU_JOB_ID), silently tagging both
        # sides when the guard itself runs inside a submitted job.
        prev = set_ambient_job_id("bench-tenant" if tagged else "")
        try:
            sample = _measure_submit_wait()
        finally:
            set_ambient_job_id(prev)
        from ray_tpu._private.worker import global_worker

        global_worker().task_events.drain_updates(10 ** 9)
        return sample

    result = None
    for attempt in range(attempts):
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=2)
        try:
            on = {"submit_per_s": 0.0, "wait_rounds_per_s": 0.0}
            off = {"submit_per_s": 0.0, "wait_rounds_per_s": 0.0}
            side(True)  # warm-up
            for i in range(repeats):
                pair = ((True, on), (False, off)) if i % 2 == 0 \
                    else ((False, off), (True, on))
                for flag, best in pair:
                    sample = side(flag)
                    for k in best:
                        best[k] = max(best[k], sample[k])
        finally:
            ray_tpu.shutdown()
        overhead = {
            "submit_overhead": 1.0 - on["submit_per_s"]
            / off["submit_per_s"],
            "wait_overhead": 1.0 - on["wait_rounds_per_s"]
            / off["wait_rounds_per_s"],
        }
        ok = all(v < OBS_OVERHEAD_BUDGET for v in overhead.values())
        result = {
            "budget": OBS_OVERHEAD_BUDGET,
            "repeats": repeats,
            "attempt": attempt + 1,
            "tagged": on,
            "untagged": off,
            **{k: round(v, 4) for k, v in overhead.items()},
            "pass": ok,
        }
        if ok:
            return result
    return result


def _measure_keepalive_rps(port: int, n_requests: int,
                           job_header: bool) -> float:
    """One keep-alive RPS sample against a running proxy: a single
    persistent raw-socket connection (wrk-style) issuing
    Content-Length-framed POSTs, optionally tenant-tagged."""
    import json as _json
    import socket

    body = _json.dumps({"payload": 1}).encode()
    hdr = b"X-Job-Id: bench-tenant\r\n" if job_header else b""
    request = (b"POST /noop HTTP/1.1\r\nHost: bench\r\n"
               b"Content-Type: application/json\r\n" + hdr
               + b"Content-Length: " + str(len(body)).encode()
               + b"\r\n\r\n" + body)

    sock = socket.create_connection(("127.0.0.1", port), timeout=30)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    buf = b""

    def read_response(buf: bytes) -> bytes:
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("server closed")
            buf += chunk
        head, buf = buf.split(b"\r\n\r\n", 1)
        assert head.split(b" ", 2)[1] == b"200", head[:80]
        clen = 0
        for ln in head.split(b"\r\n")[1:]:
            if ln.lower().startswith(b"content-length:"):
                clen = int(ln.split(b":", 1)[1])
        while len(buf) < clen:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("server closed mid-body")
            buf += chunk
        return buf[clen:]

    try:
        for _ in range(50):  # warm the connection + route + replica
            sock.sendall(request)
            buf = read_response(buf)
        t0 = time.perf_counter()
        for _ in range(n_requests):
            sock.sendall(request)
            buf = read_response(buf)
        return n_requests / (time.perf_counter() - t0)
    finally:
        sock.close()


def ab_serve_keepalive(repeats: int = 4, attempts: int = 3,
                       n_requests: int = 1500) -> dict:
    """Serve keep-alive fast-path A/B: requests tenant-tagged with the
    event-loop lag sampler running (this PR's health + attribution
    additions) vs. untagged with the sampler disabled. Each side gets
    its own proxy (the sampler installs at proxy start); best-of-R
    batches per side, side ORDER alternating across attempts."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private.config import ray_config

    def run_side(instrumented: bool) -> float:
        """One fresh setup (init + deployment + proxy, so the lag
        sampler's presence is decided at proxy start) and one timed
        batch; teardown before returning."""
        ray_tpu.shutdown()
        prev = ray_config.loop_lag_sample_period_s
        ray_config.loop_lag_sample_period_s = 0.25 if instrumented \
            else 0.0
        try:
            ray_tpu.init(num_cpus=2)

            @serve.deployment(max_concurrent_queries=8)
            class Noop:
                def __call__(self, payload):
                    return {"ok": True}

            serve.run(Noop.bind(), route_prefix="/noop")
            proxy = serve.start_http_proxy()
            return _measure_keepalive_rps(
                proxy.port, n_requests, job_header=instrumented)
        finally:
            try:
                serve.shutdown()
            except Exception:
                pass
            ray_tpu.shutdown()
            ray_config.loop_lag_sample_period_s = prev

    result = None
    for attempt in range(attempts):
        # Interleave side SETUPS (on/off/on/off…, order flipping each
        # repeat): process-state drift across the run — dead replica
        # threads, heap growth — must not systematically tax whichever
        # side runs later, which a measure-side-A-then-side-B shape
        # does.
        sides = {True: 0.0, False: 0.0}
        run_side(True)  # warm-up setup/teardown cycle
        for i in range(repeats):
            order = (True, False) if (attempt + i) % 2 == 0 \
                else (False, True)
            for instrumented in order:
                sides[instrumented] = max(sides[instrumented],
                                          run_side(instrumented))
        overhead = 1.0 - sides[True] / sides[False]
        ok = overhead < OBS_OVERHEAD_BUDGET
        result = {
            "budget": OBS_OVERHEAD_BUDGET,
            "repeats": repeats,
            "attempt": attempt + 1,
            "keepalive_rps_tagged_sampled": round(sides[True], 1),
            "keepalive_rps_baseline": round(sides[False], 1),
            "keepalive_overhead": round(overhead, 4),
            "pass": ok,
        }
        if ok:
            return result
    return result


def ab_serve_stage_spans(repeats: int = 8, attempts: int = 4,
                         n_requests: int = 800) -> dict:
    """Critical-path recorder A/B (PR 18): the serve keep-alive path
    with stage spans + flight rings recording at every hop vs. both
    engines disabled. Unlike the lag-sampler leg — whose
    instrumentation installs at proxy start, forcing a fresh setup per
    side — the recorder flips live, so both sides share ONE setup and
    the timed batches interleave on/off with order flipping. That
    matters: a long-lived serve process speeds up over its first
    minutes (allocator state, heap shape, branch history), and with
    per-side setups that drift systematically taxes whichever side
    ran earlier; interleaved on one setup, both sides sample the same
    drift envelope and best-of-R converges to plateau throughput."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private import critical_path, flight_recorder
    from ray_tpu._private.config import ray_config

    def set_on(recording: bool) -> None:
        ray_config.stage_spans_enabled = recording
        critical_path.set_enabled(recording)
        flight_recorder.set_enabled(recording)

    prev = ray_config.stage_spans_enabled
    ray_tpu.shutdown()
    try:
        ray_tpu.init(num_cpus=2)

        @serve.deployment(max_concurrent_queries=8)
        class Noop:
            def __call__(self, payload):
                return {"ok": True}

        serve.run(Noop.bind(), route_prefix="/noop")
        proxy = serve.start_http_proxy()
        # Warm with the recorder ON: route resolution, replica loop,
        # the folder thread, and the JIT-warm paths all exist before
        # the first timed batch.
        set_on(True)
        _measure_keepalive_rps(proxy.port, 2000, job_header=False)

        result = None
        for attempt in range(attempts):
            sides = {True: 0.0, False: 0.0}
            for i in range(repeats):
                order = (True, False) if (attempt + i) % 2 == 0 \
                    else (False, True)
                for recording in order:
                    set_on(recording)
                    sides[recording] = max(
                        sides[recording],
                        _measure_keepalive_rps(
                            proxy.port, n_requests, job_header=False))
            overhead = 1.0 - sides[True] / sides[False]
            ok = overhead < OBS_OVERHEAD_BUDGET
            result = {
                "budget": OBS_OVERHEAD_BUDGET,
                "repeats": repeats,
                "attempt": attempt + 1,
                "keepalive_rps_recording": round(sides[True], 1),
                "keepalive_rps_baseline": round(sides[False], 1),
                "stage_span_overhead": round(overhead, 4),
                "pass": ok,
            }
            if ok:
                return result
        return result
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
        ray_config.stage_spans_enabled = prev
        critical_path.set_enabled(True)
        flight_recorder.set_enabled(True)
        critical_path.reset()
        flight_recorder.reset()


def ab_observability_cluster(repeats: int = 3) -> dict:
    """Cluster leg: driver submit rate into a lease-batched node WITH
    the shipping plane running vs. with it disabled — proves shipping
    rides the flush cadence instead of taxing dispatch."""
    import ray_tpu
    from ray_tpu._private.config import ray_config

    def run_side(ship: bool) -> float:
        ray_tpu.shutdown()
        prev = ray_config.obs_ship_period_s
        ray_config.obs_ship_period_s = 0.5 if ship else 0.0
        from ray_tpu.cluster_utils import Cluster

        cluster = Cluster(head_node_args={"num_cpus": 1})
        try:
            cluster.add_node(num_cpus=2)

            @ray_tpu.remote(num_cpus=2)
            def remote_tiny():
                return None

            best = 0.0
            ray_tpu.get([remote_tiny.remote() for _ in range(50)],
                        timeout=300)  # warm lease + template
            for _ in range(repeats):
                t0 = time.perf_counter()
                refs = [remote_tiny.remote() for _ in range(1000)]
                dt = time.perf_counter() - t0
                ray_tpu.get(refs, timeout=300)
                best = max(best, 1000 / dt)
            return best
        finally:
            cluster.shutdown()
            ray_config.obs_ship_period_s = prev

    with_ship = run_side(True)
    without = run_side(False)
    overhead = 1.0 - with_ship / without
    return {"cluster_submit_per_s_shipping": round(with_ship, 1),
            "cluster_submit_per_s_no_shipping": round(without, 1),
            "cluster_submit_overhead": round(overhead, 4),
            # Cross-process noise on a shared box dwarfs the effect;
            # the guard is informational here, binding on the local leg.
            "pass": overhead < 3 * OBS_OVERHEAD_BUDGET}


# -- object-plane A/B (--ab-objects) -----------------------------------------
#
# The bandwidth overhaul's acceptance harness: interleaved same-host
# measurements of the cross-process object plane at several payload
# sizes, judged against the memcpy envelope measured in the SAME run,
# plus a locality-on vs locality-off placement A/B (the 64MB-argument
# task either follows its bytes or pulls them), and a quick control-
# plane guard (put_small / wait_1k must not regress).

OBJ_MEMCPY_FACTOR = 5.0  # xproc 64MB get must be within 5x of memcpy


def _xproc_leg(mb: int, min_time: float = 2.0) -> dict:
    """Same-segment cluster get/put-arg bandwidth at one payload size."""
    import numpy as np

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cap_mb = max(1024, 6 * mb)
    cluster = Cluster(head_node_args={"num_cpus": 1},
                      shm_capacity=cap_mb * 2**20)
    try:
        cluster.add_node(num_cpus=4)
        if cluster.shm_plane is not None:
            cluster.shm_plane.store.wait_prefault(60)

        @ray_tpu.remote(num_cpus=2)
        def sync_node_prefault():
            from ray_tpu._private.worker import global_worker

            plane = getattr(global_worker(), "shm_plane", None)
            if plane is not None:
                plane.store.wait_prefault(60)
            return plane is not None

        ray_tpu.get(sync_node_prefault.remote())

        @ray_tpu.remote(num_cpus=2)
        def produce(nbytes):
            import ray_tpu._private.worker as _w

            buf = getattr(_w, "_bench_buf", None)
            if buf is None or buf.nbytes != nbytes:
                buf = _w._bench_buf = np.ones(nbytes, np.uint8)
            return buf

        @ray_tpu.remote(num_cpus=2)
        def consume(x):
            return x.nbytes

        nbytes = mb * 2**20

        def node_to_driver():
            assert ray_tpu.get(produce.remote(nbytes),
                               timeout=300).nbytes == nbytes

        big = np.ones(nbytes, np.uint8)

        def driver_to_node():
            assert ray_tpu.get(consume.remote(ray_tpu.put(big)),
                               timeout=300) == nbytes

        from benchmarks.ray_perf import timeit

        get_rate = timeit(f"get {mb}MB", node_to_driver,
                          min_time=min_time)
        put_rate = timeit(f"put-arg {mb}MB", driver_to_node,
                          min_time=min_time)
        return {
            "object_mb": mb,
            "xproc_get_GBps": round(get_rate * mb / 1024, 2),
            "xproc_put_arg_GBps": round(put_rate * mb / 1024, 2),
            "shm_stats": cluster.shm_plane.stats()
            if cluster.shm_plane else None,
        }
    finally:
        cluster.shutdown()


def _locality_leg(mb: int = 64, rounds: int = 4,
                  fanout: int = 3) -> dict:
    """Locality-on vs locality-off placement A/B: two remote-simulated
    nodes (own segments — a wrong-node placement really pulls the
    bytes), the argument resident on node A, interleaved rounds with
    the scheduling knob toggled on the driver/head."""
    import numpy as np

    import ray_tpu
    from ray_tpu._private.config import ray_config
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    cluster = Cluster(head_node_args={"num_cpus": 1},
                      shm_capacity=max(1024, 6 * mb) * 2**20)
    prev = ray_config.locality_aware_scheduling
    try:
        # Node B gets MORE cpus than the owner A: the least-loaded
        # policy genuinely prefers B, so locality-off places the
        # consumer away from the bytes (and pays the pull) while
        # locality-on overrides the pack to follow them.
        node_a = cluster.add_node(num_cpus=4,
                                  simulate_remote_host=True)
        cluster.add_node(num_cpus=8, simulate_remote_host=True)

        @ray_tpu.remote(num_cpus=2)
        def produce(nbytes):
            import os as _os

            return _os.getpid(), np.ones(nbytes, np.uint8)

        @ray_tpu.remote(num_cpus=2)
        def consume(payload):
            import os as _os

            return _os.getpid(), payload[1].nbytes

        sides = {True: {"best_s": float("inf"), "owner_hits": 0,
                        "tasks": 0},
                 False: {"best_s": float("inf"), "owner_hits": 0,
                         "tasks": 0}}
        nbytes = mb * 2**20
        from ray_tpu._private.worker import global_worker

        backend = global_worker().backend
        for i in range(rounds):
            order = (True, False) if i % 2 == 0 else (False, True)
            for locality_on in order:
                ray_config.locality_aware_scheduling = locality_on
                # Drop held shape leases so each side makes a FRESH
                # placement decision (a lease granted by the other
                # side would otherwise pin placement for ~2s).
                with backend._lease_lock:
                    backend._leases.clear()
                ref = produce.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_a, soft=False)).remote(nbytes)
                owner_pid = ray_tpu.get(ref, timeout=300)[0]
                t0 = time.perf_counter()
                outs = ray_tpu.get(
                    [consume.remote(ref) for _ in range(fanout)],
                    timeout=600)
                dt = time.perf_counter() - t0
                side = sides[locality_on]
                side["best_s"] = min(side["best_s"], dt)
                side["tasks"] += len(outs)
                side["owner_hits"] += sum(
                    1 for pid, nb in outs
                    if pid == owner_pid and nb == nbytes)
                del ref, outs
                time.sleep(0.2)  # let frees land before the next round
        on, off = sides[True], sides[False]
        return {
            "object_mb": mb, "rounds": rounds, "fanout": fanout,
            "locality_on": {
                "best_s": round(on["best_s"], 3),
                "owner_hit_fraction": round(
                    on["owner_hits"] / max(1, on["tasks"]), 3)},
            "locality_off": {
                "best_s": round(off["best_s"], 3),
                "owner_hit_fraction": round(
                    off["owner_hits"] / max(1, off["tasks"]), 3)},
            "speedup": round(off["best_s"] / on["best_s"], 2)
            if on["best_s"] > 0 else None,
        }
    finally:
        ray_config.locality_aware_scheduling = prev
        cluster.shutdown()


def _control_plane_guard() -> dict:
    """put_small / wait_1k spot check: the object-plane rework must not
    tax the small-object and wait hot paths."""
    import numpy as np

    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    try:
        from benchmarks.ray_perf import timeit

        small = np.zeros(1024, np.uint8)
        put_rate = timeit("put 1KB", lambda: ray_tpu.put(small))
        pool = [ray_tpu.put(i) for i in range(1000)]
        wait_rate = timeit(
            "wait 1k", lambda: ray_tpu.wait(pool, num_returns=1000,
                                            timeout=10))
        return {"put_small_per_s": round(put_rate, 1),
                "wait_1k_refs_per_s": round(wait_rate, 1)}
    finally:
        ray_tpu.shutdown()


def ab_objects(cal: dict, sizes_mb=(4, 64, 256)) -> dict:
    import ray_tpu

    ray_tpu.shutdown()
    legs = [_xproc_leg(mb) for mb in sizes_mb]
    locality = _locality_leg()
    guard = _control_plane_guard()
    memcpy = cal.get("memcpy_GBps") or 0.0
    get64 = next((l["xproc_get_GBps"] for l in legs
                  if l["object_mb"] == 64), 0.0)
    ok = memcpy > 0 and get64 * OBJ_MEMCPY_FACTOR >= memcpy
    return {
        "memcpy_GBps": memcpy,
        "memcpy_factor_budget": OBJ_MEMCPY_FACTOR,
        "xproc": legs,
        "xproc_get_64MB_vs_memcpy": round(memcpy / get64, 2)
        if get64 else None,
        "locality_ab": locality,
        "control_plane_guard": guard,
        "pass": ok,
    }


def main() -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None,
                        help="also write the JSON envelope to this path")
    parser.add_argument("--skip-cluster", action="store_true",
                        help="skip the multiprocess cluster section")
    parser.add_argument("--ab-observability", action="store_true",
                        help="run ONLY the observability overhead A/B "
                             "guard (submit/wait hot paths, "
                             "instrumented vs baseline)")
    parser.add_argument("--ab-hooks", action="store_true",
                        help="run ONLY the sanitize_hooks yield-point "
                             "tax guard (uninstalled crossing cost x "
                             "per-op crossing census, <1% budget)")
    parser.add_argument("--ab-sched", action="store_true",
                        help="run ONLY the compact-queue tax guard "
                             "(submit + 1-task roundtrip, header vs "
                             "full-spec queueing, <5% budget)")
    parser.add_argument("--ab-head", action="store_true",
                        help="run ONLY the multi-process head A/B: "
                             "1-shard vs N-shard durable row stream + "
                             "lease decisions, plus the head_shards=1 "
                             "knob-is-free control guard (<5%)")
    parser.add_argument("--ab-objects", action="store_true",
                        help="run ONLY the object-plane A/B: xproc "
                             "get/put-arg at 4/64/256MB vs the same-"
                             "run memcpy envelope, locality-on vs "
                             "locality-off placement, control-plane "
                             "guard")
    args = parser.parse_args()

    cal = host_calibration()

    if args.ab_objects:
        obj = ab_objects(cal)
        envelope = {
            "schema_version": SCHEMA_VERSION,
            "suite": "objects_ab",
            "harness": "benchmarks/perf_bench.py --ab-objects",
            "host_calibration": cal,
            "metrics": {"objects": obj},
        }
        print(json.dumps(envelope, indent=2))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(envelope, f, indent=2)
        if not obj["pass"]:
            sys.exit(f"object-plane memcpy-envelope guard FAILED: "
                     f"get64={obj['xproc_get_64MB_vs_memcpy']}x off "
                     f"the envelope (budget {OBJ_MEMCPY_FACTOR}x)")
        return envelope

    if args.ab_head:
        head = ab_head()
        envelope = {
            "schema_version": SCHEMA_VERSION,
            "suite": "head_ab",
            "harness": "benchmarks/perf_bench.py --ab-head",
            "host_calibration": cal,
            "metrics": {"head": head},
        }
        print(json.dumps(envelope, indent=2))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(envelope, f, indent=2)
        if not head["pass"]:
            sys.exit(f"multi-process head A/B guard FAILED: {head}")
        return envelope

    if args.ab_sched:
        sched = ab_sched()
        envelope = {
            "schema_version": SCHEMA_VERSION,
            "suite": "sched_ab",
            "harness": "benchmarks/perf_bench.py --ab-sched",
            "host_calibration": cal,
            "metrics": {"sched": sched},
        }
        print(json.dumps(envelope, indent=2))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(envelope, f, indent=2)
        if not sched["pass"]:
            sys.exit(f"compact-queue tax guard FAILED: {sched}")
        return envelope

    if args.ab_hooks:
        hooks = ab_hooks()
        envelope = {
            "schema_version": SCHEMA_VERSION,
            "suite": "hooks_ab",
            "harness": "benchmarks/perf_bench.py --ab-hooks",
            "host_calibration": cal,
            "metrics": {"hooks": hooks},
        }
        print(json.dumps(envelope, indent=2))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(envelope, f, indent=2)
        if not hooks["pass"]:
            sys.exit(f"yield-point hook tax guard FAILED: {hooks}")
        return envelope

    if args.ab_observability:
        ab = ab_observability()
        job_ab = ab_job_tagging()
        serve_ab = ab_serve_keepalive()
        stage_ab = ab_serve_stage_spans()
        cluster_ab = {} if args.skip_cluster \
            else ab_observability_cluster()
        envelope = {
            "schema_version": SCHEMA_VERSION,
            "suite": "observability_ab",
            "harness": "benchmarks/perf_bench.py --ab-observability",
            "host_calibration": cal,
            "metrics": {"local": ab, "job_tagging": job_ab,
                        "serve_keepalive": serve_ab,
                        "stage_spans": stage_ab,
                        "cluster": cluster_ab},
        }
        print(json.dumps(envelope, indent=2))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(envelope, f, indent=2)
        for leg_name, leg in (("local", ab), ("job_tagging", job_ab),
                              ("serve_keepalive", serve_ab),
                              ("stage_spans", stage_ab)):
            if not leg["pass"]:
                sys.exit("observability overhead guard FAILED "
                         f"({leg_name}): {leg}")
        return envelope

    from benchmarks import ray_perf

    if args.skip_cluster:
        orig = ray_perf.cluster_bench
        ray_perf.cluster_bench = lambda: {}
        try:
            metrics = ray_perf.main()
        finally:
            ray_perf.cluster_bench = orig
    else:
        metrics = ray_perf.main()

    envelope = {
        "schema_version": SCHEMA_VERSION,
        "suite": "core_micro",
        "harness": "benchmarks/perf_bench.py wrapping benchmarks/ray_perf.py",
        "host_calibration": cal,
        "metrics": metrics,
    }
    print(json.dumps(envelope, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(envelope, f, indent=2)
    return envelope


if __name__ == "__main__":
    main()
