"""Schema-versioned core-perf bench emitter with host calibration.

Wraps the raw micro-op suite (`benchmarks/ray_perf.py`) in a stable,
machine-comparable envelope. PR 1 found a ~13x single-core speed gap
between bench hosts, which makes absolute numbers from different rounds
incomparable; every emission therefore carries:

- ``schema_version``: bump on any metric rename/semantic change so a
  reader never silently misparses an old file;
- ``host_calibration``: cpu count plus two single-thread reference
  rates measured in-process right before the suite (a pure-Python spin
  and a lock round-trip rate — the two costs the control plane is made
  of). Cross-host comparisons divide metrics by the calibration to
  compare RATIOS, not absolutes.

Usage: python benchmarks/perf_bench.py [--out BENCH_PERF_rNN.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCHEMA_VERSION = 2


def host_calibration(seconds: float = 0.25) -> dict:
    """Single-thread reference rates for cross-host ratio comparisons."""
    # Pure-Python spin: integer loop iterations per second.
    t0 = time.perf_counter()
    count = 0
    while time.perf_counter() - t0 < seconds:
        for _ in range(1000):
            count += 1
    spin_mops = count / (time.perf_counter() - t0) / 1e6

    # Lock round trips per second (the control plane's unit cost).
    lock = threading.Lock()
    t0 = time.perf_counter()
    locks = 0
    while time.perf_counter() - t0 < seconds:
        for _ in range(1000):
            with lock:
                pass
            locks += 1
    lock_mops = locks / (time.perf_counter() - t0) / 1e6

    return {
        "cpu_count": os.cpu_count(),
        "python_spin_mops_per_s": round(spin_mops, 3),
        "lock_roundtrip_mops_per_s": round(lock_mops, 3),
        "note": "compare cross-host metrics as ratios against these "
                "single-thread rates, not as absolutes",
    }


# -- observability A/B (instrumented vs. baseline) ---------------------------
#
# The observability plane must be free on the paths PR 2 optimized. This
# mode measures the submit and wait hot paths with the fast-path stats
# ENABLED (plus, in cluster mode, event/metric shipping running) against
# the same paths with instrumentation off, and asserts the overhead
# stays under OBS_OVERHEAD_BUDGET. Noise guard: best-of-R per side,
# interleaved (on/off/on/off...), with a bounded retry before failing.

OBS_OVERHEAD_BUDGET = 0.05  # <5% on submit and wait


def _measure_submit_wait(n_tasks: int = 5000, n_refs: int = 1000,
                         wait_rounds: int = 200) -> dict:
    """One sample of the two hot paths in the CURRENT process state.

    Both legs are pinned to the pure path under test — concurrent
    execution chaos (fast-dispatch bimodality, executor thread churn)
    would otherwise swamp a 5% effect on a 2-core box:

    - submit: tasks parked on an unresolved dependency, so each
      ``.remote()`` exercises spec construction + submit bookkeeping
      (where the monotonic stamp lives) with zero dispatch racing the
      timer; the gate then opens and everything drains off-clock.
    - wait: repeated ``wait`` over RESOLVED refs — the one-lock
      snapshot pass PR 2 built, where the wait counters live.

    GC is held across each timed region (re-enabled after) so a
    collection landing in one side's window doesn't masquerade as
    instrumentation overhead.
    """
    import gc
    import threading as _threading

    import ray_tpu

    @ray_tpu.remote(num_cpus=0, max_concurrency=2)
    class Gate:
        # max_concurrency=2: open() must run while block() holds the
        # other executor thread.
        def __init__(self):
            self._ev = _threading.Event()

        def open(self):
            self._ev.set()
            return True

        def block(self):
            self._ev.wait(600)
            return None

    gate = Gate.remote()
    blocker = gate.block.remote()

    @ray_tpu.remote(num_cpus=0)
    def tiny(dep):
        return None

    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        refs = [tiny.remote(blocker) for _ in range(n_tasks)]
        submit_s = time.perf_counter() - t0
    finally:
        gc.enable()
    ray_tpu.get(gate.open.remote(), timeout=60)
    ray_tpu.get(refs, timeout=300)

    pool = [ray_tpu.put(i) for i in range(n_refs)]
    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        for _ in range(wait_rounds):
            ready, _ = ray_tpu.wait(pool, num_returns=len(pool),
                                    timeout=30)
            assert len(ready) == len(pool)
        wait_s = time.perf_counter() - t0
    finally:
        gc.enable()
    del pool, refs
    return {"submit_per_s": n_tasks / submit_s,
            "wait_rounds_per_s": wait_rounds / wait_s}


def ab_observability(repeats: int = 5, attempts: int = 3) -> dict:
    """Instrumented-vs-baseline A/B over the submit/wait hot paths.
    Returns the envelope section including a pass/fail guard."""
    import ray_tpu
    from ray_tpu._private import perf_stats

    def side(enabled: bool) -> dict:
        perf_stats.set_enabled(enabled)
        try:
            sample = _measure_submit_wait()
        finally:
            perf_stats.set_enabled(True)
        # Keep per-sample process state flat: drain the event-buffer
        # delta so neither side accumulates a growing dirty set.
        from ray_tpu._private.worker import global_worker

        global_worker().task_events.drain_updates(10 ** 9)
        return sample

    result = None
    for attempt in range(attempts):
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=2)
        try:
            on = {"submit_per_s": 0.0, "wait_rounds_per_s": 0.0}
            off = {"submit_per_s": 0.0, "wait_rounds_per_s": 0.0}
            side(True)  # warm-up (executor pool, templates, JIT-ish)
            for i in range(repeats):
                # Alternate which side runs first: heap growth / GC
                # drift over the run must not systematically tax
                # whichever side happens to go second.
                pair = ((True, on), (False, off)) if i % 2 == 0 \
                    else ((False, off), (True, on))
                for flag, best in pair:
                    sample = side(flag)
                    for k in best:
                        best[k] = max(best[k], sample[k])
        finally:
            perf_stats.set_enabled(True)
            ray_tpu.shutdown()
        overhead = {
            "submit_overhead": 1.0 - on["submit_per_s"]
            / off["submit_per_s"],
            "wait_overhead": 1.0 - on["wait_rounds_per_s"]
            / off["wait_rounds_per_s"],
        }
        ok = all(v < OBS_OVERHEAD_BUDGET for v in overhead.values())
        result = {
            "budget": OBS_OVERHEAD_BUDGET,
            "repeats": repeats,
            "attempt": attempt + 1,
            "instrumented": on,
            "baseline": off,
            **{k: round(v, 4) for k, v in overhead.items()},
            "pass": ok,
        }
        if ok:
            return result
    return result


# -- yield-point hook tax guard (--ab-hooks) ---------------------------------
#
# raysan/raymc grow the sanitize_hooks yield-point map over time; each
# crossing costs one global load + None check when nothing is
# installed. A direct uninstalled-vs-uninstalled A/B cannot measure
# that (the crossing is compiled into the call sites), so the guard
# multiplies two robust numbers instead: the measured ns/crossing of an
# UNINSTALLED sched_point, and a census of crossings-per-op taken by
# installing a counting hook over the same dep-parked submit /
# resolved-wait workload the observability A/B pins. Their product
# bounds the hook tax on each hot path; the budget is <1%. The census
# itself is also pinned: a future PR that drops a crossing into a
# per-object hot loop trips the count ceiling even if this host is too
# noisy to see the time.

HOOKS_TAX_BUDGET = 0.01    # <1% of submit / wait op time
# Census ceiling: total crossings per workload unit (one unit = one
# task + one put + one wait round). Today the whole workload crosses
# ~1 per unit (store.put per completion/put, store.wait per round); a
# crossing added inside a per-object or per-poll hot loop multiplies
# this and trips the guard even when host noise hides the time.
HOOKS_MAX_PER_UNIT = 2.0


def ab_hooks() -> dict:
    import ray_tpu
    from ray_tpu._private import sanitize_hooks

    # The production default must BE the uninstalled fast path.
    uninstalled = (sanitize_hooks._sched_point is None
                   and sanitize_hooks._crash_point is None)

    # ns per uninstalled crossing, best-of-3 chunks.
    n = 200_000
    best_ns = float("inf")
    crossing = sanitize_hooks.sched_point
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            crossing("router.handoff")
        best_ns = min(best_ns,
                      (time.perf_counter() - t0) / n * 1e9)

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    try:
        n_tasks, n_refs, wait_rounds = 5000, 1000, 200
        _measure_submit_wait(n_tasks, n_refs, wait_rounds)  # warm-up
        base = _measure_submit_wait(n_tasks, n_refs, wait_rounds)

        counts = {}
        counts_lock = threading.Lock()

        def census(name):
            # Crossings fire concurrently from driver + executor pool
            # threads; a bare dict increment would lose counts and let
            # the per-unit ceiling under-read.
            with counts_lock:
                counts[name] = counts.get(name, 0) + 1

        sanitize_hooks.install_sched_point(census)
        sanitize_hooks.install_crash_point(census)
        try:
            _measure_submit_wait(n_tasks, n_refs, wait_rounds)
            total = sum(counts.values())
        finally:
            sanitize_hooks.install_sched_point(None)
            sanitize_hooks.install_crash_point(None)
    finally:
        ray_tpu.shutdown()

    # Attribute the census to ops conservatively: every crossing the
    # whole workload made is charged to BOTH paths (puts, executor
    # drains and teardown crossings included), so each per-op tax is
    # an overcount — if the overcount passes the 1% budget, the true
    # tax certainly does.
    per_submit = total / n_tasks
    per_wait_round = total / wait_rounds
    units = n_tasks + n_refs + wait_rounds
    per_unit = total / units
    submit_op_ns = 1e9 / base["submit_per_s"]
    wait_op_ns = 1e9 / base["wait_rounds_per_s"]
    submit_tax = per_submit * best_ns / submit_op_ns
    wait_tax = per_wait_round * best_ns / wait_op_ns
    ok = (uninstalled
          and submit_tax < HOOKS_TAX_BUDGET
          and wait_tax < HOOKS_TAX_BUDGET
          and per_unit <= HOOKS_MAX_PER_UNIT)
    return {
        "budget": HOOKS_TAX_BUDGET,
        "uninstalled_by_default": uninstalled,
        "ns_per_crossing_uninstalled": round(best_ns, 1),
        "crossings_total": total,
        "crossings_by_point": dict(sorted(counts.items())),
        "crossings_per_workload_unit": round(per_unit, 4),
        "per_unit_ceiling": HOOKS_MAX_PER_UNIT,
        "submit_tax_fraction": round(submit_tax, 6),
        "wait_tax_fraction": round(wait_tax, 6),
        "pass": ok,
    }


def ab_job_tagging(repeats: int = 5, attempts: int = 3) -> dict:
    """Job-tag propagation A/B over the same submit/wait hot paths:
    every spec/put carrying an ambient tenant tag (job_id_for_submit +
    the per-entry store accounting) vs. untagged. Same best-of-R
    interleaving, budget, and bounded noise retry as the
    instrumentation A/B."""
    import ray_tpu
    from ray_tpu._private.task_spec import set_ambient_job_id

    def side(tagged: bool) -> dict:
        # "" pins genuinely-untagged: None would fall back to the
        # process default (RAY_TPU_JOB_ID), silently tagging both
        # sides when the guard itself runs inside a submitted job.
        prev = set_ambient_job_id("bench-tenant" if tagged else "")
        try:
            sample = _measure_submit_wait()
        finally:
            set_ambient_job_id(prev)
        from ray_tpu._private.worker import global_worker

        global_worker().task_events.drain_updates(10 ** 9)
        return sample

    result = None
    for attempt in range(attempts):
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=2)
        try:
            on = {"submit_per_s": 0.0, "wait_rounds_per_s": 0.0}
            off = {"submit_per_s": 0.0, "wait_rounds_per_s": 0.0}
            side(True)  # warm-up
            for i in range(repeats):
                pair = ((True, on), (False, off)) if i % 2 == 0 \
                    else ((False, off), (True, on))
                for flag, best in pair:
                    sample = side(flag)
                    for k in best:
                        best[k] = max(best[k], sample[k])
        finally:
            ray_tpu.shutdown()
        overhead = {
            "submit_overhead": 1.0 - on["submit_per_s"]
            / off["submit_per_s"],
            "wait_overhead": 1.0 - on["wait_rounds_per_s"]
            / off["wait_rounds_per_s"],
        }
        ok = all(v < OBS_OVERHEAD_BUDGET for v in overhead.values())
        result = {
            "budget": OBS_OVERHEAD_BUDGET,
            "repeats": repeats,
            "attempt": attempt + 1,
            "tagged": on,
            "untagged": off,
            **{k: round(v, 4) for k, v in overhead.items()},
            "pass": ok,
        }
        if ok:
            return result
    return result


def _measure_keepalive_rps(port: int, n_requests: int,
                           job_header: bool) -> float:
    """One keep-alive RPS sample against a running proxy: a single
    persistent raw-socket connection (wrk-style) issuing
    Content-Length-framed POSTs, optionally tenant-tagged."""
    import json as _json
    import socket

    body = _json.dumps({"payload": 1}).encode()
    hdr = b"X-Job-Id: bench-tenant\r\n" if job_header else b""
    request = (b"POST /noop HTTP/1.1\r\nHost: bench\r\n"
               b"Content-Type: application/json\r\n" + hdr
               + b"Content-Length: " + str(len(body)).encode()
               + b"\r\n\r\n" + body)

    sock = socket.create_connection(("127.0.0.1", port), timeout=30)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    buf = b""

    def read_response(buf: bytes) -> bytes:
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("server closed")
            buf += chunk
        head, buf = buf.split(b"\r\n\r\n", 1)
        assert head.split(b" ", 2)[1] == b"200", head[:80]
        clen = 0
        for ln in head.split(b"\r\n")[1:]:
            if ln.lower().startswith(b"content-length:"):
                clen = int(ln.split(b":", 1)[1])
        while len(buf) < clen:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("server closed mid-body")
            buf += chunk
        return buf[clen:]

    try:
        for _ in range(50):  # warm the connection + route + replica
            sock.sendall(request)
            buf = read_response(buf)
        t0 = time.perf_counter()
        for _ in range(n_requests):
            sock.sendall(request)
            buf = read_response(buf)
        return n_requests / (time.perf_counter() - t0)
    finally:
        sock.close()


def ab_serve_keepalive(repeats: int = 4, attempts: int = 3,
                       n_requests: int = 1500) -> dict:
    """Serve keep-alive fast-path A/B: requests tenant-tagged with the
    event-loop lag sampler running (this PR's health + attribution
    additions) vs. untagged with the sampler disabled. Each side gets
    its own proxy (the sampler installs at proxy start); best-of-R
    batches per side, side ORDER alternating across attempts."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private.config import ray_config

    def run_side(instrumented: bool) -> float:
        """One fresh setup (init + deployment + proxy, so the lag
        sampler's presence is decided at proxy start) and one timed
        batch; teardown before returning."""
        ray_tpu.shutdown()
        prev = ray_config.loop_lag_sample_period_s
        ray_config.loop_lag_sample_period_s = 0.25 if instrumented \
            else 0.0
        try:
            ray_tpu.init(num_cpus=2)

            @serve.deployment(max_concurrent_queries=8)
            class Noop:
                def __call__(self, payload):
                    return {"ok": True}

            serve.run(Noop.bind(), route_prefix="/noop")
            proxy = serve.start_http_proxy()
            return _measure_keepalive_rps(
                proxy.port, n_requests, job_header=instrumented)
        finally:
            try:
                serve.shutdown()
            except Exception:
                pass
            ray_tpu.shutdown()
            ray_config.loop_lag_sample_period_s = prev

    result = None
    for attempt in range(attempts):
        # Interleave side SETUPS (on/off/on/off…, order flipping each
        # repeat): process-state drift across the run — dead replica
        # threads, heap growth — must not systematically tax whichever
        # side runs later, which a measure-side-A-then-side-B shape
        # does.
        sides = {True: 0.0, False: 0.0}
        run_side(True)  # warm-up setup/teardown cycle
        for i in range(repeats):
            order = (True, False) if (attempt + i) % 2 == 0 \
                else (False, True)
            for instrumented in order:
                sides[instrumented] = max(sides[instrumented],
                                          run_side(instrumented))
        overhead = 1.0 - sides[True] / sides[False]
        ok = overhead < OBS_OVERHEAD_BUDGET
        result = {
            "budget": OBS_OVERHEAD_BUDGET,
            "repeats": repeats,
            "attempt": attempt + 1,
            "keepalive_rps_tagged_sampled": round(sides[True], 1),
            "keepalive_rps_baseline": round(sides[False], 1),
            "keepalive_overhead": round(overhead, 4),
            "pass": ok,
        }
        if ok:
            return result
    return result


def ab_observability_cluster(repeats: int = 3) -> dict:
    """Cluster leg: driver submit rate into a lease-batched node WITH
    the shipping plane running vs. with it disabled — proves shipping
    rides the flush cadence instead of taxing dispatch."""
    import ray_tpu
    from ray_tpu._private.config import ray_config

    def run_side(ship: bool) -> float:
        ray_tpu.shutdown()
        prev = ray_config.obs_ship_period_s
        ray_config.obs_ship_period_s = 0.5 if ship else 0.0
        from ray_tpu.cluster_utils import Cluster

        cluster = Cluster(head_node_args={"num_cpus": 1})
        try:
            cluster.add_node(num_cpus=2)

            @ray_tpu.remote(num_cpus=2)
            def remote_tiny():
                return None

            best = 0.0
            ray_tpu.get([remote_tiny.remote() for _ in range(50)],
                        timeout=300)  # warm lease + template
            for _ in range(repeats):
                t0 = time.perf_counter()
                refs = [remote_tiny.remote() for _ in range(1000)]
                dt = time.perf_counter() - t0
                ray_tpu.get(refs, timeout=300)
                best = max(best, 1000 / dt)
            return best
        finally:
            cluster.shutdown()
            ray_config.obs_ship_period_s = prev

    with_ship = run_side(True)
    without = run_side(False)
    overhead = 1.0 - with_ship / without
    return {"cluster_submit_per_s_shipping": round(with_ship, 1),
            "cluster_submit_per_s_no_shipping": round(without, 1),
            "cluster_submit_overhead": round(overhead, 4),
            # Cross-process noise on a shared box dwarfs the effect;
            # the guard is informational here, binding on the local leg.
            "pass": overhead < 3 * OBS_OVERHEAD_BUDGET}


def main() -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None,
                        help="also write the JSON envelope to this path")
    parser.add_argument("--skip-cluster", action="store_true",
                        help="skip the multiprocess cluster section")
    parser.add_argument("--ab-observability", action="store_true",
                        help="run ONLY the observability overhead A/B "
                             "guard (submit/wait hot paths, "
                             "instrumented vs baseline)")
    parser.add_argument("--ab-hooks", action="store_true",
                        help="run ONLY the sanitize_hooks yield-point "
                             "tax guard (uninstalled crossing cost x "
                             "per-op crossing census, <1% budget)")
    args = parser.parse_args()

    cal = host_calibration()

    if args.ab_hooks:
        hooks = ab_hooks()
        envelope = {
            "schema_version": SCHEMA_VERSION,
            "suite": "hooks_ab",
            "harness": "benchmarks/perf_bench.py --ab-hooks",
            "host_calibration": cal,
            "metrics": {"hooks": hooks},
        }
        print(json.dumps(envelope, indent=2))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(envelope, f, indent=2)
        if not hooks["pass"]:
            sys.exit(f"yield-point hook tax guard FAILED: {hooks}")
        return envelope

    if args.ab_observability:
        ab = ab_observability()
        job_ab = ab_job_tagging()
        serve_ab = ab_serve_keepalive()
        cluster_ab = {} if args.skip_cluster \
            else ab_observability_cluster()
        envelope = {
            "schema_version": SCHEMA_VERSION,
            "suite": "observability_ab",
            "harness": "benchmarks/perf_bench.py --ab-observability",
            "host_calibration": cal,
            "metrics": {"local": ab, "job_tagging": job_ab,
                        "serve_keepalive": serve_ab,
                        "cluster": cluster_ab},
        }
        print(json.dumps(envelope, indent=2))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(envelope, f, indent=2)
        for leg_name, leg in (("local", ab), ("job_tagging", job_ab),
                              ("serve_keepalive", serve_ab)):
            if not leg["pass"]:
                sys.exit("observability overhead guard FAILED "
                         f"({leg_name}): {leg}")
        return envelope

    from benchmarks import ray_perf

    if args.skip_cluster:
        orig = ray_perf.cluster_bench
        ray_perf.cluster_bench = lambda: {}
        try:
            metrics = ray_perf.main()
        finally:
            ray_perf.cluster_bench = orig
    else:
        metrics = ray_perf.main()

    envelope = {
        "schema_version": SCHEMA_VERSION,
        "suite": "core_micro",
        "harness": "benchmarks/perf_bench.py wrapping benchmarks/ray_perf.py",
        "host_calibration": cal,
        "metrics": metrics,
    }
    print(json.dumps(envelope, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(envelope, f, indent=2)
    return envelope


if __name__ == "__main__":
    main()
