"""Schema-versioned core-perf bench emitter with host calibration.

Wraps the raw micro-op suite (`benchmarks/ray_perf.py`) in a stable,
machine-comparable envelope. PR 1 found a ~13x single-core speed gap
between bench hosts, which makes absolute numbers from different rounds
incomparable; every emission therefore carries:

- ``schema_version``: bump on any metric rename/semantic change so a
  reader never silently misparses an old file;
- ``host_calibration``: cpu count plus two single-thread reference
  rates measured in-process right before the suite (a pure-Python spin
  and a lock round-trip rate — the two costs the control plane is made
  of). Cross-host comparisons divide metrics by the calibration to
  compare RATIOS, not absolutes.

Usage: python benchmarks/perf_bench.py [--out BENCH_PERF_rNN.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCHEMA_VERSION = 2


def host_calibration(seconds: float = 0.25) -> dict:
    """Single-thread reference rates for cross-host ratio comparisons."""
    # Pure-Python spin: integer loop iterations per second.
    t0 = time.perf_counter()
    count = 0
    while time.perf_counter() - t0 < seconds:
        for _ in range(1000):
            count += 1
    spin_mops = count / (time.perf_counter() - t0) / 1e6

    # Lock round trips per second (the control plane's unit cost).
    lock = threading.Lock()
    t0 = time.perf_counter()
    locks = 0
    while time.perf_counter() - t0 < seconds:
        for _ in range(1000):
            with lock:
                pass
            locks += 1
    lock_mops = locks / (time.perf_counter() - t0) / 1e6

    return {
        "cpu_count": os.cpu_count(),
        "python_spin_mops_per_s": round(spin_mops, 3),
        "lock_roundtrip_mops_per_s": round(lock_mops, 3),
        "note": "compare cross-host metrics as ratios against these "
                "single-thread rates, not as absolutes",
    }


def main() -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None,
                        help="also write the JSON envelope to this path")
    parser.add_argument("--skip-cluster", action="store_true",
                        help="skip the multiprocess cluster section")
    args = parser.parse_args()

    cal = host_calibration()

    from benchmarks import ray_perf

    if args.skip_cluster:
        orig = ray_perf.cluster_bench
        ray_perf.cluster_bench = lambda: {}
        try:
            metrics = ray_perf.main()
        finally:
            ray_perf.cluster_bench = orig
    else:
        metrics = ray_perf.main()

    envelope = {
        "schema_version": SCHEMA_VERSION,
        "suite": "core_micro",
        "harness": "benchmarks/perf_bench.py wrapping benchmarks/ray_perf.py",
        "host_calibration": cal,
        "metrics": metrics,
    }
    print(json.dumps(envelope, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(envelope, f, indent=2)
    return envelope


if __name__ == "__main__":
    main()
