"""Component-level timing of the flagship train step on the real chip.

Times each piece with a host value fetch as the barrier (the only
trustworthy barrier on the tunneled platform — see BENCH_BASELINE.json).
Not part of the test suite; run manually to find the MFU bottleneck.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp


def _fetch(out):
    """Host value fetch — the only trustworthy barrier on the tunnel.
    Reduce to a scalar on-device first: fetching a big array would time
    the tunnel's transfer bandwidth, not the computation."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(jnp.sum(leaf.astype(jnp.float32)))


def timeit(fn, *args, iters=5, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    _fetch(out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        _fetch(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e3


def main():
    from ray_tpu.models import (LlamaConfig, init_params_sharded,
                                init_train_state, loss_fn, make_optimizer,
                                make_train_step)
    from ray_tpu.ops.attention import flash_attention
    from ray_tpu.ops.cross_entropy import softmax_cross_entropy
    from ray_tpu.parallel import MeshConfig, create_mesh

    cfg = LlamaConfig.llama3_1b()
    batch, seq = 4, 2048
    mesh = create_mesh(MeshConfig(data=-1, fsdp=1))
    key = jax.random.PRNGKey(1)

    # -- small isolated kernels first (low memory) ---------------------
    hd = cfg.head_dim
    q = jax.random.normal(key, (batch, seq, cfg.n_heads, hd), jnp.bfloat16)
    k = jax.random.normal(key, (batch, seq, cfg.n_kv_heads, hd), jnp.bfloat16)
    v = jax.random.normal(key, (batch, seq, cfg.n_kv_heads, hd), jnp.bfloat16)
    fa = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    t = timeit(lambda: fa(q, k, v))
    print(f"flash fwd  (1 layer): {t:8.2f} ms  x{cfg.n_layers} = "
          f"{t * cfg.n_layers:.1f}")

    fab = jax.jit(jax.grad(
        lambda q, k, v: flash_attention(q, k, v, causal=True)
        .astype(jnp.float32).sum(), argnums=(0, 1, 2)))
    t = timeit(lambda: fab(q, k, v))
    print(f"flash f+b  (1 layer): {t:8.2f} ms  x{cfg.n_layers} = "
          f"{t * cfg.n_layers:.1f}")

    # final projection + CE at bench shapes
    x = jax.random.normal(key, (batch * seq, cfg.dim), jnp.bfloat16)
    w = jax.random.normal(key, (cfg.dim, cfg.vocab_size), jnp.bfloat16)
    lbl = jax.random.randint(key, (batch * seq,), 0, cfg.vocab_size)

    proj = jax.jit(lambda x, w: x @ w)
    t = timeit(lambda: proj(x, w))
    print(f"vocab proj fwd:       {t:8.2f} ms")

    ce = jax.jit(lambda x, w, l: softmax_cross_entropy(x @ w, l).mean())
    t = timeit(lambda: ce(x, w, lbl))
    print(f"proj+CE fwd:          {t:8.2f} ms")

    ceb = jax.jit(jax.grad(
        lambda x, w, l: softmax_cross_entropy(x @ w, l).mean(),
        argnums=(0, 1)))
    t = timeit(lambda: ceb(x, w, lbl))
    print(f"proj+CE fwd+bwd:      {t:8.2f} ms")

    # one transformer layer fwd at bench shapes (no vocab proj)
    from ray_tpu.models.llama import DEFAULT_RULES, _init_layer, layer_fn
    from ray_tpu.ops.rope import rope_frequencies
    lp = _init_layer(cfg, key)
    cos, sin = rope_frequencies(cfg.head_dim, seq, cfg.rope_theta)
    xact = jax.random.normal(key, (batch, seq, cfg.dim), jnp.bfloat16)
    layer_f = jax.jit(lambda x, lp: layer_fn(
        cfg, None, DEFAULT_RULES, cos, sin, x, lp, None))
    t = timeit(lambda: layer_f(xact, lp))
    print(f"layer fwd (1 layer):  {t:8.2f} ms  x{cfg.n_layers} = "
          f"{t * cfg.n_layers:.1f}")

    layer_b = jax.jit(jax.grad(lambda x, lp: layer_fn(
        cfg, None, DEFAULT_RULES, cos, sin, x, lp, None)
        .astype(jnp.float32).sum(), argnums=(0, 1)))
    t = timeit(lambda: layer_b(xact, lp))
    print(f"layer f+b (1 layer):  {t:8.2f} ms  x{cfg.n_layers} = "
          f"{t * cfg.n_layers:.1f}")
    del lp, xact, q, k, v, x, w

    # -- full model ----------------------------------------------------
    params = init_params_sharded(cfg, mesh, jax.random.PRNGKey(0))
    tokens = jax.random.randint(key, (batch, seq), 0, cfg.vocab_size)
    bd = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

    lf = jax.jit(lambda p, b: loss_fn(p, b, cfg, mesh=mesh)[0])
    fwd = timeit(lambda: lf(params, bd))
    print(f"forward (loss only):  {fwd:8.1f} ms")

    gf = jax.jit(jax.grad(lambda p, b: loss_fn(p, b, cfg, mesh=mesh)[0]))
    bwd = timeit(lambda: gf(params, bd))
    print(f"fwd+bwd (grads):      {bwd:8.1f} ms")
    gf.clear_cache()
    lf.clear_cache()
    jax.clear_caches()

    tx = make_optimizer(3e-4, warmup_steps=0, moment_dtype=jnp.bfloat16)
    state = init_train_state(params, tx)
    del params
    step = make_train_step(
        lambda p, b: loss_fn(p, b, cfg, mesh=mesh), tx, mesh=mesh,
        batch_logical={"tokens": ("batch", "seq"),
                       "targets": ("batch", "seq")})
    # The train step donates `state`, so time it with rebinding (the
    # generic timeit would reuse a donated/deleted buffer).
    state, m = step(state, bd)
    float(m["loss"])
    full = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(5):
            state, m = step(state, bd)
        float(m["loss"])
        full = min(full, (time.perf_counter() - t0) / 5)
    full *= 1e3
    print(f"full step:            {full:8.1f} ms")


if __name__ == "__main__":
    main()
