"""Data-engine scale benchmark: GB-class random_shuffle (both paths)
and sort (reference: `release/nightly_tests/dataset/` shuffle suites —
theirs run 100 TB on fleets; this records the single-host engine's
throughput so regressions and the pull-vs-push task-graph difference
are visible).

Usage: python benchmarks/data_bench.py [--gb 2]
Writes one JSON line to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--gb", type=float, default=2.0)
    parser.add_argument("--blocks", type=int, default=64)
    args = parser.parse_args()

    import numpy as np

    import ray_tpu
    from ray_tpu import data as rt_data

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)

    total_bytes = int(args.gb * 2**30)
    rows_per_block = total_bytes // (args.blocks * 1024)  # 1KB rows

    def gen_block(i):
        rng = np.random.RandomState(i)
        return {
            "key": rng.randint(0, 1 << 30, rows_per_block),
            "payload": rng.randint(0, 255,
                                   (rows_per_block, 1016)).astype(
                                       np.uint8),
        }

    ds = rt_data.range(args.blocks, parallelism=args.blocks) \
        .map_batches(lambda b: gen_block(int(b["id"][0])),
                     batch_size=None)
    ds = ds.materialize() if hasattr(ds, "materialize") else ds
    # Force materialization so shuffles don't re-time generation.
    n_rows = ds.count()
    assert n_rows == rows_per_block * args.blocks

    out = {"gb": round(total_bytes / 2**30, 2), "blocks": args.blocks,
           "rows": n_rows, "host_cpus": os.cpu_count()}

    for label, kwargs in (("shuffle_pull", {"push_based": False}),
                          ("shuffle_push", {"push_based": True})):
        t0 = time.perf_counter()
        shuffled = ds.random_shuffle(seed=0, **kwargs)
        got = shuffled.count()  # drives execution to completion
        dt = time.perf_counter() - t0
        assert got == n_rows
        out[f"{label}_s"] = round(dt, 2)
        out[f"{label}_MBps"] = round(total_bytes / 2**20 / dt, 1)

    t0 = time.perf_counter()
    sorted_ds = ds.sort("key")
    got = sorted_ds.count()
    dt = time.perf_counter() - t0
    assert got == n_rows
    out["sort_s"] = round(dt, 2)
    out["sort_MBps"] = round(total_bytes / 2**20 / dt, 1)

    ray_tpu.shutdown()
    print(json.dumps({
        "metric": "data_shuffle_push_MBps",
        "value": out["shuffle_push_MBps"],
        "unit": "MB/s",
        "detail": out,
    }))


if __name__ == "__main__":
    main()
