"""Serve/LLM north-star benchmark: p50 TTFT + decode throughput.

Runs the continuous-batching engine (ray_tpu.serve.llm.LLMEngine) on the
local chip with Llama-3.2-1B-shaped random weights and measures, over a
set of concurrent streaming requests:

- TTFT: request arrival -> first streamed token (p50/p95), covering
  queueing + bucketed prefill (the BASELINE.json "Serve TTFT" north star
  the reference leaves unpublished).
- decode throughput: generated tokens/sec across the whole run.

Usage: python benchmarks/serve_bench.py [--requests 16] [--max-tokens 32]
Writes one JSON line to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

# Runnable from anywhere without PYTHONPATH (which can shadow the
# platform plugin discovery on some images).
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=16)
    parser.add_argument("--max-tokens", type=int, default=32)
    parser.add_argument("--prompt-len", type=int, default=128)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--decode-steps", type=int, default=8)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import LlamaConfig, init_params_sharded
    from ray_tpu.parallel import MeshConfig, create_mesh
    from ray_tpu.serve.llm import LLMEngine, SamplingParams

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = LlamaConfig.llama3_1b() if on_tpu else LlamaConfig.debug()
    mesh = create_mesh(MeshConfig(data=-1))
    params = init_params_sharded(cfg, mesh, jax.random.PRNGKey(0))
    engine = LLMEngine(cfg, params, max_batch_size=args.batch_size,
                       max_seq_len=min(cfg.max_seq_len, 1024),
                       decode_steps=args.decode_steps)
    # Deploy-time AOT warmup (what LLMDeployment does): compiles every
    # prefill bucket + decode BEFORE traffic, off the request path. With
    # the persistent XLA compilation cache this is expensive only the
    # FIRST time a config is ever deployed on a machine.
    warmup_s = engine.warmup()
    engine.start()

    rng = np.random.default_rng(0)
    prompt_len = min(args.prompt_len, 96) if not on_tpu else args.prompt_len
    prompts = [rng.integers(0, cfg.vocab_size, prompt_len).tolist()
               for _ in range(args.requests)]

    ttfts = []
    total_tokens = [0]
    first_times = []
    last_times = [0.0]
    lock = threading.Lock()

    def one_request(prompt):
        t0 = time.perf_counter()
        first = None
        count = 0
        for _tok in engine.generate(
                prompt, SamplingParams(max_tokens=args.max_tokens,
                                       temperature=0.0), stream=True):
            now = time.perf_counter()
            if first is None:
                first = now - t0
                with lock:
                    first_times.append(now)
            count += 1
            with lock:
                last_times[0] = max(last_times[0], now)
        with lock:
            ttfts.append(first)
            total_tokens[0] += count

    def run_wave(wave_prompts):
        """Run one wave; resets the accumulators on entry and returns a
        per-wave snapshot (no shared state to save/restore between
        waves)."""
        ttfts.clear()
        total_tokens[0] = 0
        first_times.clear()
        last_times[0] = 0.0
        t_start = time.perf_counter()
        threads = [threading.Thread(target=one_request, args=(p,))
                   for p in wave_prompts]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return {
            "wall": time.perf_counter() - t_start,
            "ttfts": sorted(ttfts),
            "tokens": total_tokens[0],
            "first_times": list(first_times),
            "last_time": last_times[0],
        }

    # Wave 1 absorbs the platform's idle-restart stall (the tunneled
    # chip's first dispatch after an idle gap blocks for seconds —
    # measured ~3.5s on a program that runs in ~60ms warm; see
    # BENCH_CALIBRATION.json). Wave 2 is the steady-state serving number
    # a loaded server sees; wave-1 numbers ride along as cold-start.
    cold = run_wave(prompts)
    cold_p50 = cold["ttfts"][len(cold["ttfts"]) // 2]
    steady = run_wave(prompts)
    # Decode-rate wave: exactly batch_slots concurrent requests so the
    # post-first-token window is pure continuous-batching decode (a
    # multi-wave run interleaves wave N's decode with wave N+1's
    # prefills and would misattribute the time).
    dec_prompts = prompts[:args.batch_size]
    dec = run_wave(dec_prompts)
    decode_window = max(dec["last_time"] - max(dec["first_times"]), 1e-9)
    decode_tokens = dec["tokens"] - len(dec_prompts)
    decode_rate = round(decode_tokens / decode_window, 1)
    engine.stop()

    wall = steady["wall"]
    cold_wall = cold["wall"]
    total_tokens[0] = steady["tokens"]
    sorted_ttfts = steady["ttfts"]
    p50 = sorted_ttfts[len(sorted_ttfts) // 2]
    p95 = sorted_ttfts[min(len(sorted_ttfts) - 1,
                           int(len(sorted_ttfts) * 0.95))]
    print(json.dumps({
        "metric": "serve_ttft_p50_ms",
        "value": round(p50 * 1e3, 1),
        "unit": "ms",
        "detail": {
            "config": "llama-1.24B" if on_tpu else "llama-debug-cpu",
            "ttft_p95_ms": round(p95 * 1e3, 1),
            "cold_start_ttft_p50_ms": round(cold_p50 * 1e3, 1),
            "cold_start_wall_s": round(cold_wall, 2),
            "deploy_warmup_s": round(warmup_s, 2),
            "decode_tokens_per_s": decode_rate,
            "end_to_end_tokens_per_s": round(total_tokens[0] / wall, 1),
            "requests": args.requests,
            "prompt_len": prompt_len,
            "max_tokens": args.max_tokens,
            "batch_slots": args.batch_size,
            "decode_steps": args.decode_steps,
        },
    }))


if __name__ == "__main__":
    main()
