"""Serve/LLM north-star benchmark: p50 TTFT + decode throughput.

Runs the continuous-batching engine (ray_tpu.serve.llm.LLMEngine) on the
local chip with Llama-3.2-1B-shaped random weights and measures, over a
set of concurrent streaming requests:

- TTFT: request arrival -> first streamed token (p50/p95), covering
  queueing + bucketed prefill (the BASELINE.json "Serve TTFT" north star
  the reference leaves unpublished).
- decode throughput: generated tokens/sec across the whole run.

Usage: python benchmarks/serve_bench.py [--requests 16] [--max-tokens 32]
Writes one JSON line to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

# Runnable from anywhere without PYTHONPATH (which can shadow the
# platform plugin discovery on some images).
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=16)
    parser.add_argument("--max-tokens", type=int, default=32)
    parser.add_argument("--prompt-len", type=int, default=128)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--decode-steps", type=int, default=8)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import LlamaConfig, init_params_sharded
    from ray_tpu.parallel import MeshConfig, create_mesh
    from ray_tpu.serve.llm import LLMEngine, SamplingParams

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = LlamaConfig.llama3_1b() if on_tpu else LlamaConfig.debug()
    mesh = create_mesh(MeshConfig(data=-1))
    params = init_params_sharded(cfg, mesh, jax.random.PRNGKey(0))
    engine = LLMEngine(cfg, params, max_batch_size=args.batch_size,
                       max_seq_len=min(cfg.max_seq_len, 1024),
                       decode_steps=args.decode_steps)
    # Deploy-time AOT warmup (what LLMDeployment does): compiles every
    # prefill bucket + decode BEFORE traffic, off the request path. With
    # the persistent XLA compilation cache this is expensive only the
    # FIRST time a config is ever deployed on a machine.
    warmup_s = engine.warmup()
    engine.start()

    rng = np.random.default_rng(0)
    prompt_len = min(args.prompt_len, 96) if not on_tpu else args.prompt_len
    prompts = [rng.integers(0, cfg.vocab_size, prompt_len).tolist()
               for _ in range(args.requests)]

    ttfts = []
    total_tokens = [0]
    first_times = []
    last_times = [0.0]
    lock = threading.Lock()

    def one_request(prompt):
        t0 = time.perf_counter()
        first = None
        count = 0
        for _tok in engine.generate(
                prompt, SamplingParams(max_tokens=args.max_tokens,
                                       temperature=0.0), stream=True):
            now = time.perf_counter()
            if first is None:
                first = now - t0
                with lock:
                    first_times.append(now)
            count += 1
            with lock:
                last_times[0] = max(last_times[0], now)
        with lock:
            ttfts.append(first)
            total_tokens[0] += count

    def run_wave(wave_prompts):
        t_start = time.perf_counter()
        threads = [threading.Thread(target=one_request, args=(p,))
                   for p in wave_prompts]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t_start

    # Wave 1 absorbs the platform's idle-restart stall (the tunneled
    # chip's first dispatch after an idle gap blocks for seconds —
    # measured ~3.5s on a program that runs in ~60ms warm; see
    # BENCH_CALIBRATION.json). Wave 2 is the steady-state serving number
    # a loaded server sees; wave-1 numbers ride along as cold-start.
    cold_wall = run_wave(prompts)
    cold_ttfts = sorted(ttfts)
    cold_p50 = cold_ttfts[len(cold_ttfts) // 2]
    ttfts.clear()
    total_tokens[0] = 0
    first_times.clear()
    last_times[0] = 0.0
    wall = run_wave(prompts)
    engine.stop()

    ttfts.sort()
    p50 = ttfts[len(ttfts) // 2]
    p95 = ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.95))]
    # Decode-phase rate: once every request has its first token, the
    # remaining tokens are pure continuous-batching decode (prefill cost
    # is what TTFT measures). Only meaningful when every request fits in
    # one wave (requests <= slots); in multi-wave runs the first wave
    # decodes before the last wave's first token, which would inflate
    # the figure — report null there.
    one_wave = args.requests <= args.batch_size
    decode_window = max(last_times[0] - max(first_times), 1e-9)
    decode_tokens = total_tokens[0] - len(prompts)
    print(json.dumps({
        "metric": "serve_ttft_p50_ms",
        "value": round(p50 * 1e3, 1),
        "unit": "ms",
        "detail": {
            "config": "llama-1.24B" if on_tpu else "llama-debug-cpu",
            "ttft_p95_ms": round(p95 * 1e3, 1),
            "cold_start_ttft_p50_ms": round(cold_p50 * 1e3, 1),
            "cold_start_wall_s": round(cold_wall, 2),
            "deploy_warmup_s": round(warmup_s, 2),
            "decode_tokens_per_s": round(decode_tokens / decode_window, 1) if one_wave else None,
            "end_to_end_tokens_per_s": round(total_tokens[0] / wall, 1),
            "requests": args.requests,
            "prompt_len": prompt_len,
            "max_tokens": args.max_tokens,
            "batch_slots": args.batch_size,
            "decode_steps": args.decode_steps,
        },
    }))


if __name__ == "__main__":
    main()
