"""RLlib learner north star: learner samples/sec with sampling and
learning OVERLAPPED (the round-3 verdict's missing number).

IMPALA + LearnerThread on the pixel Catch env: CPU rollout actors stream
[N, T, 40, 40, 1] fragments into the learner queue; the conv-torso
V-trace update runs continuously on the device. Reports
`learner_samples_per_s` (transitions consumed by updates / wall) and
`device_busy_fraction` (update-window time minus queue starvation, with
every window closed by a host-scalar fetch — the only trustworthy
barrier on the tunneled chip).

Reference analog: `rllib/execution/learner_thread.py` feeding the IMPALA
learner, measured by the nightly `rllib_tests` sample-throughput suites.

Usage: python benchmarks/rl_learner_bench.py [--seconds 60]
Writes one JSON line to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--seconds", type=float, default=60.0)
    parser.add_argument("--workers", type=int, default=3)
    parser.add_argument("--envs-per-worker", type=int, default=16)
    parser.add_argument("--fragment", type=int, default=40)
    parser.add_argument("--num-sgd-iter", type=int, default=4)
    parser.add_argument("--env", default="CatchPixels-v0")
    args = parser.parse_args()

    import numpy as np

    import ray_tpu
    from ray_tpu.rl import IMPALAConfig

    ray_tpu.init(num_cpus=max(8, args.workers * 2),
                 ignore_reinit_error=True)
    config = (IMPALAConfig()
              .environment(args.env)
              .rollouts(num_rollout_workers=args.workers,
                        num_envs_per_worker=args.envs_per_worker,
                        rollout_fragment_length=args.fragment)
              .training(lr=3e-4, updates_per_iter=8)
              .learners(use_learner_thread=True,
                        num_sgd_iter=args.num_sgd_iter,
                        learner_queue_size=4)
              .debugging(seed=0))
    algo = config.build()

    algo.train()  # warm-up: compiles the update + absorbs platform stall
    thread = algo.learner_thread
    base_busy = thread.busy_s
    base_updates = thread.updates
    base_samples = thread.samples_consumed

    t0 = time.perf_counter()
    env_steps = 0
    while time.perf_counter() - t0 < args.seconds:
        result = algo.train()
        env_steps += result["num_env_steps_sampled_this_iter"]
    wall = time.perf_counter() - t0

    import jax

    platform = jax.devices()[0].platform
    updates = thread.updates - base_updates
    samples = thread.samples_consumed - base_samples
    busy = thread.busy_s - base_busy
    algo.cleanup()
    ray_tpu.shutdown()

    print(json.dumps({
        "metric": "rl_learner_samples_per_s",
        "value": round(samples / wall, 1),
        "unit": "transitions/s",
        "detail": {
            "algo": "IMPALA+LearnerThread", "env": args.env,
            "model": "nature-cnn(40x40x1)"
            if "Pixels" in args.env else "mlp",
            "device": platform,
            "device_busy_fraction": round(busy / wall, 4),
            "learner_updates_per_s": round(updates / wall, 2),
            "env_steps_sampled_per_s": round(env_steps / wall, 1),
            "num_sgd_iter": args.num_sgd_iter,
            "workers": args.workers,
            "envs_per_worker": args.envs_per_worker,
            "fragment": args.fragment,
            "batch_transitions": args.envs_per_worker * args.fragment,
            "window_s": round(wall, 1),
            "host_cpus": os.cpu_count(),
            "overlap": "sampling continues while the learner thread "
                       "updates on-device; busy excludes queue-starved "
                       "time",
        },
    }))


if __name__ == "__main__":
    main()
