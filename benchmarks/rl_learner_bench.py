"""RLlib learner north star: sampling and learning OVERLAPPED, reported
HONESTLY — fresh environment throughput and learner consumption are
CO-EQUAL headline metrics (round-4 verdict: burying fresh env_steps/s
under a reuse-multiplied "transitions/s" headline hid the scaling
signal that matters on a real pod).

IMPALA + LearnerThread on the pixel Catch env: CPU rollout actors stream
[N, T, 40, 40, 1] uint8 fragments into the learner queue; the conv-torso
V-trace update runs continuously on the device, reusing each queued
batch `num_sgd_iter` times (the reference's minibatch buffer).

Metrics per run:
- fresh_env_steps_per_s     new transitions entering the system
- reused_transitions_per_s  transitions consumed by updates (fresh x
                            reuse when the learner keeps up)
- device_busy_fraction      update wall minus queue starvation, every
                            window closed by a host-scalar fetch (the
                            only trustworthy barrier on the tunnel chip)

`--sweep` additionally runs a rollout-worker sweep to locate the
fresh-sample knee (where adding workers stops adding fresh samples on
this 1-CPU host) and where the learner starves (busy fraction < 1).

Reference analog: `rllib/execution/learner_thread.py` feeding the
IMPALA learner, measured by the nightly sample-throughput suites.

Usage: python benchmarks/rl_learner_bench.py [--seconds 60] [--sweep]
Writes one JSON line to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_point(args, workers: int, seconds: float) -> dict:
    from ray_tpu.rl import IMPALAConfig

    config = (IMPALAConfig()
              .environment(args.env)
              .rollouts(num_rollout_workers=workers,
                        num_envs_per_worker=args.envs_per_worker,
                        rollout_fragment_length=args.fragment)
              .training(lr=3e-4, updates_per_iter=8)
              .learners(use_learner_thread=True,
                        num_sgd_iter=args.num_sgd_iter,
                        learner_queue_size=4)
              .debugging(seed=0))
    algo = config.build()
    algo.train()  # warm-up: compiles the update + absorbs platform stall
    thread = algo.learner_thread
    # Align busy-accounting windows with the measurement boundaries:
    # without the flush, a window opened during warm-up banks its whole
    # span (compile included) inside the measurement and the busy delta
    # can exceed the wall (the round-5 `device_busy_fraction: 1.49`).
    thread.flush_windows()
    base_busy = thread.busy_s
    base_updates = thread.updates
    base_samples = thread.samples_consumed

    t0 = time.perf_counter()
    env_steps = 0
    while time.perf_counter() - t0 < seconds:
        result = algo.train()
        env_steps += result["num_env_steps_sampled_this_iter"]
    thread.flush_windows()  # bank the tail inside the measured wall
    wall = time.perf_counter() - t0
    busy_fraction = (thread.busy_s - base_busy) / wall
    assert 0.0 <= busy_fraction <= 1.0, (
        f"device_busy_fraction out of bounds: {busy_fraction} "
        f"(busy delta {thread.busy_s - base_busy:.3f}s over "
        f"{wall:.3f}s wall)")
    out = {
        "workers": workers,
        "fresh_env_steps_per_s": round(env_steps / wall, 1),
        "reused_transitions_per_s": round(
            (thread.samples_consumed - base_samples) / wall, 1),
        "device_busy_fraction": round(busy_fraction, 4),
        "learner_updates_per_s": round(
            (thread.updates - base_updates) / wall, 2),
        "window_s": round(wall, 1),
    }
    algo.cleanup()
    return out


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--seconds", type=float, default=60.0)
    parser.add_argument("--workers", type=int, default=3)
    parser.add_argument("--envs-per-worker", type=int, default=16)
    parser.add_argument("--fragment", type=int, default=40)
    parser.add_argument("--num-sgd-iter", type=int, default=4)
    parser.add_argument("--sweep", action="store_true",
                        help="also sweep rollout workers for the "
                             "fresh-sample knee")
    parser.add_argument("--sweep-seconds", type=float, default=None,
                        help="per-point sweep window; defaults to "
                             "--seconds so sweep and headline numbers "
                             "are measured over EQUAL windows and stay "
                             "comparable")
    parser.add_argument("--env", default="CatchPixels-v0")
    args = parser.parse_args()

    import ray_tpu

    ray_tpu.init(num_cpus=max(16, args.workers * 2),
                 ignore_reinit_error=True)

    headline = run_point(args, args.workers, args.seconds)

    sweep = []
    if args.sweep:
        sweep_seconds = args.sweep_seconds if args.sweep_seconds \
            else args.seconds
        for w in (1, 2, 4, 8):
            sweep.append(run_point(args, w, sweep_seconds))

    import jax

    platform = jax.devices()[0].platform
    ray_tpu.shutdown()

    detail = {
        "algo": "IMPALA+LearnerThread", "env": args.env,
        "model": "nature-cnn(40x40x1), uint8 frames dequantized "
                 "on device" if "Pixels" in args.env else "mlp",
        "device": platform,
        "device_busy_fraction": headline["device_busy_fraction"],
        "learner_updates_per_s": headline["learner_updates_per_s"],
        "num_sgd_iter": args.num_sgd_iter,
        "workers": args.workers,
        "envs_per_worker": args.envs_per_worker,
        "fragment": args.fragment,
        "batch_transitions": args.envs_per_worker * args.fragment,
        "window_s": headline["window_s"],
        "host_cpus": os.cpu_count(),
        "reuse_note": "reused = fresh x num_sgd_iter when the learner "
                      "keeps pace; the two are CO-EQUAL headline "
                      "numbers — fresh is what scales a real pod, "
                      "reused is what the device consumed",
    }
    if sweep:
        detail["worker_sweep"] = sweep
        fresh = [p["fresh_env_steps_per_s"] for p in sweep]
        knee = next((sweep[i]["workers"]
                     for i in range(1, len(fresh))
                     if fresh[i] < 1.15 * fresh[i - 1]),
                    sweep[-1]["workers"])
        detail["fresh_sample_knee_workers"] = knee
        detail["sweep_note"] = (
            "knee = first worker count adding <15% fresh throughput; "
            "on this 1-CPU host env stepping and the learner share one "
            "core, so the knee is a host-CPU ceiling, not an ICI/HBM "
            "one")
    print(json.dumps({
        "metric": "rl_learner_fresh_env_steps_per_s",
        "value": headline["fresh_env_steps_per_s"],
        "co_headline": {
            "fresh_env_steps_per_s":
                headline["fresh_env_steps_per_s"],
            "reused_transitions_per_s":
                headline["reused_transitions_per_s"],
        },
        "unit": "env_steps/s",
        "detail": detail,
    }))


if __name__ == "__main__":
    main()
