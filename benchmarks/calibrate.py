"""Chip calibration: peak achievable matmul FLOPs and HBM bandwidth on
this device, measured inside one jit program (scan-amortized, so tunnel
dispatch overhead is negligible). Establishes the real MFU denominator."""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
from jax import lax


def _time(fn, *args, reps=3):
    out = fn(*args)
    jnp.sum(out.astype(jnp.float32)).block_until_ready()
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        float(jnp.sum(out.astype(jnp.float32)))
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    dev = jax.devices()[0]
    print(f"device: platform={dev.platform} kind={dev.device_kind}")

    key = jax.random.PRNGKey(0)
    n = 8192
    k_iters = 50
    x = jax.random.normal(key, (n, n), jnp.bfloat16)
    w = jax.random.normal(key, (n, n), jnp.bfloat16) * 0.01

    @jax.jit
    def chain(x, w):
        def body(c, _):
            c = jnp.dot(c, w)
            return c, None

        c, _ = lax.scan(body, x, None, length=k_iters)
        return c

    dt = _time(chain, x, w)
    flops = 2 * n * n * n * k_iters
    print(f"matmul {n}x{n}x{n} x{k_iters}: {dt * 1e3:.1f} ms "
          f"-> {flops / dt / 1e12:.1f} TFLOP/s bf16")

    # Train-step-shaped matmul: [8192, 2048] @ [2048, 8192]
    m, kk, nn = 8192, 2048, 8192
    a = jax.random.normal(key, (m, kk), jnp.bfloat16)
    b = jax.random.normal(key, (kk, nn), jnp.bfloat16) * 0.01

    @jax.jit
    def chain2(a, b):
        def body(c, _):
            out = jnp.dot(c, b)        # [m, nn]
            c = jnp.dot(out, b.T)      # back to [m, kk]
            return c, None

        c, _ = lax.scan(body, a, None, length=k_iters)
        return c

    dt = _time(chain2, a, b)
    flops = 2 * m * kk * nn * 2 * k_iters
    print(f"matmul {m}x{kk}x{nn} pair x{k_iters}: {dt * 1e3:.1f} ms "
          f"-> {flops / dt / 1e12:.1f} TFLOP/s bf16")

    # Transposed-operand dots at train shapes (the bwd/CE patterns).
    # Data-dependent scan so XLA can't CSE the repeated dots.
    a0 = jax.random.normal(key, (8192, 2048), jnp.bfloat16)
    wn = jax.random.normal(key, (2048, 8192), jnp.bfloat16) * 0.01
    wt = jax.random.normal(key, (8192, 2048), jnp.bfloat16) * 0.01
    cases = [
        ("x@w  ", wn, lambda a, w: jnp.dot(a, w), 1),
        ("x@w.T", wt, lambda a, w: jnp.dot(a, w.T), 1),
        ("pair ", wn, lambda a, w: jnp.dot(jnp.dot(a, w), w.T), 2),
    ]
    for name, wv, fn, nd in cases:
        @jax.jit
        def rep(a, w, fn=fn):
            def body(c, _):
                out = fn(c, w)
                # fold the output back into the carry (keeps dependence)
                c = c + out[:, :2048].astype(jnp.bfloat16) * 1e-6
                return c, None

            c, _ = lax.scan(body, a, None, length=30)
            return c

        dt = _time(rep, a0, wv)
        fl = 2 * 8192 * 2048 * 8192 * 30 * nd
        print(f"{name}: {dt * 1e3:7.1f} ms -> {fl / dt / 1e12:6.1f} "
              "TFLOP/s")

    # HBM bandwidth: big copy-add chain.
    big = jax.random.normal(key, (256, 1024, 1024), jnp.bfloat16)  # 512MB

    @jax.jit
    def bwchain(z):
        def body(c, _):
            return c + 1.0, None

        c, _ = lax.scan(body, z, None, length=20)
        return c

    dt = _time(bwchain, big)
    traffic = big.size * 2 * 2 * 20  # rd + wr per iter
    print(f"elementwise chain: {dt * 1e3:.1f} ms -> "
          f"{traffic / dt / 1e9:.0f} GB/s")


if __name__ == "__main__":
    main()
