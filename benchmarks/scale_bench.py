"""Control-plane scale envelope — scaled-down analog of the reference's
release scalability suite (`release/benchmarks/README.md:5-31`: 2k nodes,
40k actors, 1M queued tasks, 1 GiB broadcast to 50 nodes).

This host is one throttled CPU core, so the absolute numbers are small;
what matters is that each dimension completes, the rates are recorded,
and collapses (timeouts, non-linear slowdowns) are visible. Sections run
independently — one dimension failing doesn't hide the others.

Dimensions (vs the reference's):
  many_actors        1,000 actors created + one call each  (ref: 40k+)
  queued_tasks       100,000 tasks queued on one node      (ref: 1M+)
  concurrent_tasks   10,000 tasks in flight at once        (ref: 10k+)
  broadcast          256 MB object fetched by every node   (ref: 1 GiB x 50)
  placement_groups   100 PGs of 4 bundles, 2PC + removal   (ref: 1k+)
  many_args          1,000 object args into one task       (ref: 10k+)
  many_returns       1,000 returns from one task           (ref: 3k+)

Usage: python benchmarks/scale_bench.py [--out SCALE_r04.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def section(name, fn, out):
    t0 = time.perf_counter()
    try:
        res = fn()
        res["wall_s"] = round(time.perf_counter() - t0, 2)
        res["ok"] = True
    except Exception as e:  # noqa: BLE001 — recorded, not fatal
        res = {"ok": False, "error": f"{type(e).__name__}: {e}",
               "wall_s": round(time.perf_counter() - t0, 2)}
        traceback.print_exc()
    out[name] = res
    print(f"[scale] {name}: {res}", flush=True)


def many_actors(n=1000):
    import ray_tpu

    @ray_tpu.remote(num_cpus=0.001)
    class A:
        def __init__(self, i):
            self.i = i

        def ping(self):
            return self.i

    t0 = time.perf_counter()
    actors = [A.remote(i) for i in range(n)]
    t_submit = time.perf_counter() - t0
    out = ray_tpu.get([a.ping.remote() for a in actors])
    t_all = time.perf_counter() - t0
    assert out == list(range(n))
    for a in actors:
        ray_tpu.kill(a)
    return {
        "actors": n,
        "create_submit_per_s": round(n / t_submit, 1),
        "create_plus_call_per_s": round(n / t_all, 1),
    }


def queued_tasks(n=100_000, concurrency_target=10_000):
    """Queue depth: submit far more cheap tasks than can run, then drain.
    Covers both the 1M-queued and 10k-concurrent reference dimensions
    (at 0.001 CPU each, ``concurrency_target`` of the queued tasks are
    runnable at once on a ``concurrency_target/1000``-CPU head — the
    ceiling is a CLI knob now, not a constant)."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=0.001)
    def noop(i):
        return i

    t0 = time.perf_counter()
    refs = [noop.remote(i) for i in range(n)]
    t_submit = time.perf_counter() - t0
    got = ray_tpu.get(refs, timeout=1200)
    t_drain = time.perf_counter() - t0
    assert got[::10_000] == list(range(0, n, 10_000))
    from ray_tpu._private.worker import global_worker

    manager = global_worker().memory_store.spill_manager
    return {
        "queued": n,
        "submit_per_s": round(n / t_submit, 1),
        "end_to_end_per_s": round(n / t_drain, 1),
        "max_concurrent_runnable": concurrency_target,
        # Spilling enabled (default budget/threshold config): the
        # memory ceiling is disk-backed, not a hard wall.
        "spilling_enabled": manager is not None,
        "spill_stats": manager.stats() if manager is not None else None,
    }


# -- scheduler-scale leg (--sections sched): SCALE_r13 -----------------------


def _rss_bytes() -> int:
    with open("/proc/self/statm") as f:
        return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")


def _sched_init(concurrency_target: int):
    import ray_tpu

    ray_tpu.shutdown()
    # 0.001-CPU tasks: the runnable ceiling IS the CPU count x1000.
    ray_tpu.init(num_cpus=max(1.0, concurrency_target / 1000.0))


def _sched_tasks_side(n: int, compact: bool,
                      concurrency_target: int) -> dict:
    import ray_tpu
    from ray_tpu._private.config import ray_config

    ray_config.sched_compact_queue = compact
    _sched_init(concurrency_target)

    @ray_tpu.remote(num_cpus=0.001)
    def noop(i):
        return i

    rss0 = _rss_bytes()
    t0 = time.perf_counter()
    refs = [noop.remote(i) for i in range(n)]
    t_submit = time.perf_counter() - t0
    rss_peak = _rss_bytes()  # deepest queue: right after the last submit
    checks = []
    chunk = 100_000
    for i in range(0, len(refs), chunk):
        vals = ray_tpu.get(refs[i:i + chunk], timeout=1800)
        checks.append(vals[0] == i and vals[-1] == i + len(vals) - 1)
        refs[i:i + chunk] = [None] * len(vals)  # release as we drain
    t_drain = time.perf_counter() - t0
    assert all(checks), "wrong values in the queued-task drain"
    ray_config.sched_compact_queue = True
    ray_tpu.shutdown()
    return {
        "compact_queue": compact,
        "queued": n,
        "submit_per_s": round(n / t_submit, 1),
        "end_to_end_per_s": round(n / t_drain, 1),
        "peak_queued_rss_mb": round((rss_peak - rss0) / 2**20, 1),
        "queued_bytes_per_task": round((rss_peak - rss0) / n, 1),
    }


def _sched_actors_side(n: int, pooled: bool) -> dict:
    import ray_tpu
    from ray_tpu._private.config import ray_config

    ray_config.sched_actor_executor_pool = pooled
    ray_config.sched_group_actor_creation = pooled
    _sched_init(max(1000, 2 * n))

    @ray_tpu.remote(num_cpus=0.001)
    class A:
        def __init__(self, i):
            self.i = i

        def ping(self):
            return self.i

    import threading as _threading

    t0 = time.perf_counter()
    actors = [A.remote(i) for i in range(n)]
    t_submit = time.perf_counter() - t0
    out = ray_tpu.get([a.ping.remote() for a in actors], timeout=1800)
    t_all = time.perf_counter() - t0
    assert out == list(range(n))
    threads = _threading.active_count()
    for a in actors:
        ray_tpu.kill(a)
    ray_config.sched_actor_executor_pool = True
    ray_config.sched_group_actor_creation = True
    ray_tpu.shutdown()
    return {
        "executor_pool": pooled,
        "actors": n,
        "create_submit_per_s": round(n / t_submit, 1),
        "create_plus_call_per_s": round(n / t_all, 1),
        "process_threads_at_peak": threads,
    }


def sched(n_tasks=1_000_000, n_actors=10_000, ab_tasks=150_000,
          ab_actors=4000, concurrency_target=100_000,
          rss_budget_mb=2048):
    """Scheduler-scale headline (ROADMAP item 2): same-run before/after
    A/B — compact headers vs full-spec queueing, pooled vs
    thread-per-actor serving — then the 1M-queued-task and 10k-actor
    dimensions with the new path on. Absolutes across rounds are not
    comparable (hosts differ wildly); the off/on contrast and the
    memory-budget check are the result."""
    def best_of(side_fn, *args, rounds=2):
        """Best submit rate of N fresh runs per side (same noise
        discipline as perf_bench: single-run wall rates on a loaded
        1-core host swing +-10%, which would drown a few-percent
        representation delta). Memory fields come from the FIRST run
        — later same-process runs inherit allocator growth and
        under-read the RSS delta."""
        runs = [side_fn(*args) for _ in range(rounds)]
        best = dict(runs[0])
        for r in runs[1:]:
            for k in ("submit_per_s", "end_to_end_per_s",
                      "create_submit_per_s", "create_plus_call_per_s"):
                if k in best and r[k] > best[k]:
                    best[k] = r[k]
        return best

    tasks_off = best_of(_sched_tasks_side, ab_tasks, False,
                        concurrency_target)
    tasks_on = best_of(_sched_tasks_side, ab_tasks, True,
                       concurrency_target)
    actors_off = _sched_actors_side(ab_actors, False)
    actors_on = _sched_actors_side(ab_actors, True)
    big = _sched_tasks_side(n_tasks, True, concurrency_target)
    big_actors = _sched_actors_side(n_actors, True)
    within_budget = big["peak_queued_rss_mb"] <= rss_budget_mb
    assert within_budget, (
        f"1M queued tasks held {big['peak_queued_rss_mb']}MB — over "
        f"the {rss_budget_mb}MB budget")
    # What the full-spec representation WOULD hold at the same depth
    # (its measured per-task queued bytes x n): the off side is not
    # run at 1M — the projection is the point, it does not fit.
    projected_off_mb = round(
        tasks_off["queued_bytes_per_task"] * n_tasks / 2**20, 1)
    # O(small) per-task control-plane cost: the submit rate must be
    # ~flat in queue depth (an O(queue-length) scan on submit or
    # dispatch would collapse it between the A/B depth and 1M).
    depth_flatness = round(
        big["submit_per_s"] / max(tasks_on["submit_per_s"], 0.1), 3)
    return {
        "tasks_ab": {"off": tasks_off, "on": tasks_on,
                     "submit_speedup_x": round(
                         tasks_on["submit_per_s"]
                         / max(tasks_off["submit_per_s"], 0.1), 2),
                     "end_to_end_speedup_x": round(
                         tasks_on["end_to_end_per_s"]
                         / max(tasks_off["end_to_end_per_s"], 0.1), 2),
                     "queued_bytes_per_task_ratio": round(
                         tasks_off["queued_bytes_per_task"]
                         / max(tasks_on["queued_bytes_per_task"], 0.1),
                         2),
                     "projected_full_spec_rss_mb_at_big": projected_off_mb,
                     "submit_rate_flatness_at_depth": depth_flatness},
        "actors_ab": {"off": actors_off, "on": actors_on,
                      "create_plus_call_speedup_x": round(
                          actors_on["create_plus_call_per_s"]
                          / max(actors_off["create_plus_call_per_s"],
                                0.1), 2)},
        "queued_1m": {**big, "rss_budget_mb": rss_budget_mb,
                      "within_memory_budget": within_budget,
                      "max_concurrent_runnable": concurrency_target},
        "actors_10k": big_actors,
    }


def broadcast(mb=256, nodes=4):
    """One big object fetched by a task on every node. Same-host nodes
    share the head's segment (zero-copy); one simulated-remote node
    exercises the native transfer plane's pull path."""
    import numpy as np

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 1},
                      shm_capacity=2048 * 2**20)
    try:
        for i in range(nodes - 1):
            cluster.add_node(num_cpus=2)
        cluster.add_node(num_cpus=2, simulate_remote_host=True)
        if cluster.shm_plane is not None:
            cluster.shm_plane.store.wait_prefault(60)

        @ray_tpu.remote(num_cpus=1)
        def touch(x):
            return int(x[::4096].sum())

        big = np.ones(mb * 2**20, np.uint8)
        ref = ray_tpu.put(big)
        expect = int(big[::4096].sum())
        t0 = time.perf_counter()
        outs = ray_tpu.get([touch.remote(ref) for _ in range(nodes * 2)],
                           timeout=600)
        dt = time.perf_counter() - t0
        assert all(o == expect for o in outs)
        return {
            "object_mb": mb,
            "nodes": nodes,
            "fetches": nodes * 2,
            "aggregate_GBps": round(nodes * 2 * mb / 1024 / dt, 2),
        }
    finally:
        cluster.shutdown()


def placement_groups(n=100):
    import ray_tpu
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)

    t0 = time.perf_counter()
    pgs = [placement_group([{"CPU": 0.01}] * 4, strategy="PACK")
           for _ in range(n)]
    ray_tpu.get([pg.ready() for pg in pgs], timeout=600)
    t_ready = time.perf_counter() - t0
    for pg in pgs:
        remove_placement_group(pg)
    t_all = time.perf_counter() - t0
    return {
        "placement_groups": n,
        "bundles_per_pg": 4,
        "create_ready_per_s": round(n / t_ready, 1),
        "create_remove_per_s": round(n / t_all, 1),
    }


def many_args(n=1000):
    import ray_tpu

    @ray_tpu.remote
    def consume(*args):
        return len(args)

    refs = [ray_tpu.put(i) for i in range(n)]
    t0 = time.perf_counter()
    assert ray_tpu.get(consume.remote(*refs), timeout=300) == n
    dt = time.perf_counter() - t0
    return {"args": n, "args_per_s": round(n / dt, 1)}


def many_returns(n=1000):
    import ray_tpu

    @ray_tpu.remote(num_returns=n)
    def produce():
        return list(range(n))

    t0 = time.perf_counter()
    refs = produce.remote()
    vals = ray_tpu.get(refs, timeout=300)
    dt = time.perf_counter() - t0
    assert vals == list(range(n))
    return {"returns": n, "returns_per_s": round(n / dt, 1)}


def cluster_actors_and_tasks(n_actors=500, n_tasks=20_000, nodes=2):
    """The same actor/task dimensions THROUGH the cluster control plane:
    head RPC dispatch to node subprocesses (the path the reference's
    envelope actually measures), not the in-process local backend."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        for _ in range(nodes):
            cluster.add_node(num_cpus=8)

        @ray_tpu.remote(num_cpus=0.001)
        class A:
            def ping(self):
                return 1

        t0 = time.perf_counter()
        actors = [A.remote() for _ in range(n_actors)]
        assert sum(ray_tpu.get([a.ping.remote() for a in actors],
                               timeout=600)) == n_actors
        t_actors = time.perf_counter() - t0
        for a in actors:
            ray_tpu.kill(a)

        @ray_tpu.remote(num_cpus=0.001)
        def noop(i):
            return i

        t0 = time.perf_counter()
        refs = [noop.remote(i) for i in range(n_tasks)]
        t_submit = time.perf_counter() - t0
        got = ray_tpu.get(refs, timeout=1200)
        t_drain = time.perf_counter() - t0
        assert got[::5000] == list(range(0, n_tasks, 5000))
        return {
            "nodes": nodes,
            "actors": n_actors,
            "actor_create_call_per_s": round(n_actors / t_actors, 1),
            "tasks": n_tasks,
            "task_submit_per_s": round(n_tasks / t_submit, 1),
            "task_end_to_end_per_s": round(n_tasks / t_drain, 1),
        }
    finally:
        cluster.shutdown()


def cluster_remote_tasks(n_tasks=3000, nodes=2):
    """The HONEST cross-process path: 1-CPU tasks that can never run on
    the 1-CPU head, so every one rides lease-pipelined dispatch to a
    node subprocess and its result crosses back. (The milli-cpu
    dimension above mostly executes head-locally.)"""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        for _ in range(nodes):
            cluster.add_node(num_cpus=4)

        @ray_tpu.remote(num_cpus=1)
        def sq(x):
            return x * x

        assert ray_tpu.get(sq.remote(3), timeout=60) == 9  # warm export
        t0 = time.perf_counter()
        refs = [sq.remote(i) for i in range(n_tasks)]
        t_submit = time.perf_counter() - t0
        got = ray_tpu.get(refs, timeout=600)
        t_drain = time.perf_counter() - t0
        assert got == [i * i for i in range(n_tasks)]
        return {
            "nodes": nodes,
            "tasks": n_tasks,
            "remote_submit_per_s": round(n_tasks / t_submit, 1),
            "remote_end_to_end_per_s": round(n_tasks / t_drain, 1),
        }
    finally:
        cluster.shutdown()


def cluster_scale_chaos(nodes=4, n_actors=200, n_tasks=8000):
    """≥4 real node processes under combined load (actors + task fan-out
    + a broadcast + PGs) with a chaos kill MID-DRAIN: one node dies
    while its share of the fan-out is queued; everything still
    completes through resubmission."""
    import numpy as np

    import ray_tpu
    from ray_tpu._private.config import ray_config
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)

    old_period = ray_config.health_check_period_s
    ray_config.health_check_period_s = 0.3
    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        node_ids = [cluster.add_node(num_cpus=4) for _ in range(nodes)]

        @ray_tpu.remote(num_cpus=0.05)
        class A:
            def ping(self):
                return 1

        t0 = time.perf_counter()
        actors = [A.remote() for _ in range(n_actors)]
        assert sum(ray_tpu.get([a.ping.remote() for a in actors],
                               timeout=600)) == n_actors
        t_actors = time.perf_counter() - t0

        # Broadcast: one 64 MB object read by a task on every node.
        blob = ray_tpu.put(np.zeros(8 * 1024 * 1024, np.float64))

        @ray_tpu.remote(num_cpus=1)
        def touch(b):
            return int(b.nbytes)

        t0 = time.perf_counter()
        sizes = ray_tpu.get([touch.remote(blob) for _ in range(nodes)],
                            timeout=300)
        t_bcast = time.perf_counter() - t0
        assert all(s == 64 * 1024 * 1024 for s in sizes)

        # 200 actors hold 10 of the 17 CPUs; 4 one-CPU bundles fit the
        # remainder alongside the broadcast tasks.
        pgs = [placement_group([{"CPU": 1}], strategy="PACK")
               for _ in range(4)]
        for pg in pgs:
            assert pg.wait(timeout=60), "PG reservation stalled"
        for pg in pgs:
            remove_placement_group(pg)

        @ray_tpu.remote(num_cpus=1, max_retries=5)
        def work(i):
            time.sleep(0.001)
            return i

        t0 = time.perf_counter()
        refs = [work.remote(i) for i in range(n_tasks)]
        # chaos: kill a node while the fan-out drains
        time.sleep(0.5)
        cluster.kill_node(node_ids[-1])
        got = ray_tpu.get(refs, timeout=900)
        t_drain = time.perf_counter() - t0
        # Tasks killed mid-run resubmit; every result must be right.
        assert got == list(range(n_tasks))
        return {
            "nodes": nodes,
            "actors": n_actors,
            "actor_create_call_per_s": round(n_actors / t_actors, 1),
            "broadcast_mb_per_s": round(64 * nodes / t_bcast, 1),
            "placement_groups": 4,
            "tasks": n_tasks,
            "chaos": "node killed 0.5s into drain",
            "task_end_to_end_per_s": round(n_tasks / t_drain, 1),
        }
    finally:
        ray_config.health_check_period_s = old_period
        cluster.shutdown()


def chaos(broadcast_mb=256, n_consumers=200):
    """Fault-tolerance headline (ROADMAP item 3): kill a node holding
    ~256MB of broadcast objects MID-JOB. The job completes through
    lineage reconstruction + actor restart, and the recovery is
    visible in the fault counters, not just in "it didn't hang"."""
    import numpy as np

    import ray_tpu
    from ray_tpu._private import perf_stats
    from ray_tpu._private.config import ray_config
    from ray_tpu._private.task_spec import NodeAffinitySchedulingStrategy
    from ray_tpu.cluster_utils import Cluster

    def counter(name, outcome=None):
        # counter() is create-or-get on the process-global registry:
        # reading .value is the public lookup.
        return perf_stats.counter(
            name, {"outcome": outcome} if outcome else None).value

    FAULT_COUNTERS = {
        "node_deaths": ("node_deaths", None),
        "node_death_lost_bytes": ("node_death_lost_bytes", None),
        "reconstructions_reexecute": ("reconstructions", "reexecute"),
        "reconstructions_from_spill": ("reconstructions", "from_spill"),
        "actor_restarts_restarted": ("actor_restarts", "restarted"),
        "actor_calls_replayed": ("actor_restarts", "call_replayed"),
        "actor_calls_rejected": ("actor_restarts", "call_rejected"),
    }
    # Deltas, not absolutes: earlier sections in a full sweep (e.g.
    # cluster_scale_chaos) leave their own recovery activity in the
    # process-global counters.
    base = {k: counter(*v) for k, v in FAULT_COUNTERS.items()}

    old_period = ray_config.health_check_period_s
    ray_config.health_check_period_s = 0.3
    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        # simulate_remote_host: each node owns its own segment, so the
        # kill genuinely loses the victim's bytes.
        victim = cluster.add_node(num_cpus=4,
                                  simulate_remote_host=True)
        survivor = cluster.add_node(num_cpus=4,
                                    simulate_remote_host=True)
        assert survivor
        chunk_mb = 64
        n_chunks = max(1, broadcast_mb // chunk_mb)

        # soft NodeAffinity: the broadcast chunks are PRODUCED on the
        # victim (they die with it), but the reconstruction resubmit of
        # the same spec may fall back to any live node.
        @ray_tpu.remote(num_cpus=1,
                        scheduling_strategy=NodeAffinitySchedulingStrategy(
                            node_id=victim, soft=True))
        def produce(i):
            return np.full(chunk_mb * 1024 * 1024 // 8, float(i))

        chunks = [produce.remote(i) for i in range(n_chunks)]
        head = cluster.head
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if all(c.id.binary() in head.object_locations
                   for c in chunks):
                break
            time.sleep(0.1)
        else:
            raise TimeoutError("broadcast chunks never landed")

        # A couple of actors on the victim with restart + retry budget:
        # their calls must ride the restart, not die with the node.
        @ray_tpu.remote(num_cpus=0.05, max_restarts=1,
                        max_task_retries=2,
                        scheduling_strategy=NodeAffinitySchedulingStrategy(
                            node_id=victim, soft=True))
        class Counter:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        actors = [Counter.remote() for _ in range(2)]
        assert all(ray_tpu.get([a.bump.remote() for a in actors],
                               timeout=120))

        @ray_tpu.remote(num_cpus=1, max_retries=5)
        def consume(part, j):
            return float(part[j % 1000])

        t0 = time.perf_counter()
        refs = [consume.remote(chunks[j % n_chunks], j)
                for j in range(n_consumers)]
        actor_refs = [a.bump.remote() for a in actors for _ in range(4)]
        time.sleep(1.0)  # mid-drain, with the victim's bytes in play
        cluster.kill_node(victim)
        got = ray_tpu.get(refs, timeout=900)
        assert all(got[j] == float(j % n_chunks)
                   for j in range(n_consumers)), "wrong values after kill"
        actor_got = ray_tpu.get(actor_refs, timeout=300)
        assert all(v >= 1 for v in actor_got)
        t_drain = time.perf_counter() - t0

        counters = {k: counter(*v) - base[k]
                    for k, v in FAULT_COUNTERS.items()}
        assert counters["node_deaths"] >= 1
        assert counters["reconstructions_reexecute"] >= 1, \
            "job completed without any visible reconstruction"
        return {
            "broadcast_mb": chunk_mb * n_chunks,
            "chunks": n_chunks,
            "consumers": n_consumers,
            "chaos": "node holding the broadcast killed 1.0s into "
                     "the drain",
            "drain_s": round(t_drain, 2),
            "consume_per_s": round(n_consumers / t_drain, 1),
            "counters": counters,
        }
    finally:
        ray_config.health_check_period_s = old_period
        cluster.shutdown()


def tenancy(n_flood=40, n_serve=60, hog_chunks=4):
    """Tenancy enforcement A/B (ROADMAP item 4): a submit flood, an
    object hog, and a latency-sensitive serve job run concurrently,
    once with enforcement OFF (the control: the flood takes every CPU
    it can, nothing is shed or charged) and once ON (flood capped at
    cpus:1, overflow rejected typed, hog's arena spills charged to the
    hog, serve p99 protected). Same-run A/B — absolutes across hosts
    are not comparable, the off/on contrast is the result."""
    import threading as _threading

    import numpy as np

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private import perf_stats
    from ray_tpu._private.config import ray_config
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.shm_plane import (SharedPlane,
                                            publish_task_output)
    from ray_tpu.exceptions import JobQuotaExceededError

    track_lock = _threading.Lock()
    track = {"running": 0, "peak": 0}

    def flood_body():
        with track_lock:
            track["running"] += 1
            track["peak"] = max(track["peak"], track["running"])
        time.sleep(0.1)
        with track_lock:
            track["running"] -= 1
        return 1

    def one_side(enforce: bool) -> dict:
        ray_config.tenancy_enforcement = enforce
        # Ceiling at half the flood: the overflow must fail TYPED on
        # the enforced side, not queue without bound.
        ray_config.job_quotas = \
            "job-flood=cpus:1,queued:%d" % (n_flood // 2)
        ray_config.job_weights = "job-serve=8,job-flood=1"
        ray_config.job_arena_budgets = "job-hog=4m"
        with track_lock:
            track["running"] = track["peak"] = 0
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=4)
        from ray_tpu._private.task_spec import set_ambient_job_id
        from ray_tpu._private.worker import global_worker

        w = global_worker()
        plane = SharedPlane(f"/rt_scale_ten_{os.getpid()}_{enforce}",
                            create=True, capacity=24 * 2**20)
        plane.install(w)
        spill_base = perf_stats.counter(
            "job_arena_spill_bytes", {"job": "job-hog"}).value
        rej_base = perf_stats.counter(
            "job_quota_rejections", {"job": "job-flood"}).value
        try:
            @serve.deployment
            class Api:
                def __call__(self, request):
                    return {"out": 1}

            handle = serve.run(Api.bind(), route_prefix="/api")
            ray_tpu.get(handle.remote({}), timeout=60)  # warm

            flood = ray_tpu.remote(num_cpus=1)(flood_body)
            prev = set_ambient_job_id("job-flood")
            try:
                flood_refs = [flood.remote() for _ in range(n_flood)]
            finally:
                set_ambient_job_id(prev)

            # The hog, mid-flood.
            for i in range(hog_chunks):
                oid = ObjectID.from_random()
                value = np.full(1_000_000, float(i))  # 8 MB
                w.memory_store.put(oid, value, job_id="job-hog")
                publish_task_output(w, oid, value)

            # The SLO job, mid-flood: sequential keep-pressure
            # requests, each timed.
            lat = []
            for _ in range(n_serve):
                t0 = time.perf_counter()
                ray_tpu.get(handle.remote({}, _job="job-serve"),
                            timeout=60)
                lat.append(time.perf_counter() - t0)
            lat.sort()

            ok = rejected = 0
            for ref in flood_refs:
                try:
                    ray_tpu.get(ref, timeout=300)
                    ok += 1
                except JobQuotaExceededError:
                    rejected += 1
            with track_lock:
                peak = track["peak"]
            return {
                "enforcement": enforce,
                "flood_submitted": n_flood,
                "flood_completed": ok,
                "flood_rejected_typed": rejected,
                "flood_peak_concurrency": peak,
                "serve_requests": n_serve,
                "serve_p50_ms": round(lat[len(lat) // 2] * 1e3, 2),
                # ceil-based rank: int(n*0.99)-1 picks the p98 sample
                # at n=60.
                "serve_p99_ms": round(
                    lat[min(len(lat) - 1,
                            -(-len(lat) * 99 // 100) - 1)] * 1e3, 2),
                "hog_published_mb": hog_chunks * 8,
                "hog_arena_spill_bytes": perf_stats.counter(
                    "job_arena_spill_bytes",
                    {"job": "job-hog"}).value - spill_base,
                "quota_rejections_metered": perf_stats.counter(
                    "job_quota_rejections",
                    {"job": "job-flood"}).value - rej_base,
            }
        finally:
            try:
                serve.shutdown()
            except Exception:
                pass
            plane.destroy()
            ray_tpu.shutdown()
            ray_config.reset()

    off = one_side(False)
    on = one_side(True)
    assert on["flood_peak_concurrency"] <= 1, on
    assert off["flood_peak_concurrency"] > 1, off
    return {
        "off": off,
        "on": on,
        "serve_p99_protection_x": round(
            max(off["serve_p99_ms"], 0.01)
            / max(on["serve_p99_ms"], 0.01), 2),
    }


# -- multi-process head leg (--sections head): SCALE_r19 ---------------------


def head_leg(n_tasks=240, router_rows=4000):
    """PR 19 control-plane dimension: a real cluster's remote task
    flood at head_shards=1 vs =2 SAME-RUN (the lease + inflight +
    directory mutation path riding the shard stream), an isolated
    1-vs-2 durable-row flood, and a mid-run shard hard-kill with
    supervised recovery — the failover path at scale-bench weight."""
    import shutil
    import tempfile

    import ray_tpu
    from ray_tpu._private.config import ray_config
    from ray_tpu.cluster_utils import Cluster

    def cluster_side(shards):
        old_shards = ray_config.head_shards
        old_dir = ray_config.head_shard_db_dir
        tmp = tempfile.mkdtemp(prefix="scale_head_")
        ray_config.head_shards = shards
        ray_config.head_shard_db_dir = tmp
        # Zero-CPU head: every task rides lease dispatch to the node
        # subprocess, so the control plane is ON the measured path.
        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 0})
        try:
            cluster.add_node(num_cpus=2)

            @ray_tpu.remote(num_cpus=1)
            def sq(x):
                return x * x

            assert ray_tpu.get(sq.remote(3), timeout=120) == 9  # warm
            t0 = time.perf_counter()
            got = ray_tpu.get([sq.remote(i) for i in range(n_tasks)],
                              timeout=600)
            dt = time.perf_counter() - t0
            assert got == [i * i for i in range(n_tasks)]
            row = {"tasks_per_s": round(n_tasks / dt, 2)}
            router = cluster.head.shard_router
            if router is not None:
                router.flush()
                row["shard_rows"] = {
                    t: len(router.fold_items(t))
                    for t in ("objects", "sizes", "lease")}
                # Chaos: hard-kill one shard, supervisor restarts it,
                # the cluster keeps completing tasks end to end.
                router.kill_shard(0)
                restarted = cluster.head.poll_shards()
                row["restarted_shards"] = restarted
                got = ray_tpu.get(
                    [sq.remote(i) for i in range(10)], timeout=300)
                assert got == [i * i for i in range(10)]
                row["post_failover_tasks_ok"] = True
            return row
        finally:
            cluster.shutdown()
            ray_config.head_shards = old_shards
            ray_config.head_shard_db_dir = old_dir
            shutil.rmtree(tmp, ignore_errors=True)

    from benchmarks.perf_bench import _head_router_side

    single = cluster_side(1)
    sharded = cluster_side(2)
    router_1 = _head_router_side(1, rows=router_rows)
    router_2 = _head_router_side(2, rows=router_rows)
    return {
        "cluster_head_shards_1": single,
        "cluster_head_shards_2": sharded,
        "cluster_parity_x": round(
            sharded["tasks_per_s"] / max(single["tasks_per_s"], 0.01),
            3),
        "router_1shard": router_1,
        "router_2shard": router_2,
        "router_scaling_x": round(
            router_2["rows_per_s"] / max(router_1["rows_per_s"], 0.1),
            3),
        "note": "single-core host: parity, not speedup, is the "
                "honest expectation (see BENCH_HEAD_r19 fallback arm)",
    }


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None)
    parser.add_argument("--actors", type=int, default=1000)
    parser.add_argument("--tasks", type=int, default=100_000)
    parser.add_argument("--broadcast-mb", type=int, default=256)
    parser.add_argument("--pgs", type=int, default=100)
    parser.add_argument("--concurrency-target", type=int,
                        default=10_000,
                        help="max concurrently-runnable 0.001-CPU "
                             "tasks (sets the head CPU count; the old "
                             "10k ceiling, now a knob)")
    parser.add_argument("--sched-tasks", type=int, default=1_000_000)
    parser.add_argument("--sched-actors", type=int, default=10_000)
    parser.add_argument("--sections", default="",
                        help="comma-separated section names to run "
                             "(default: all)")
    args = parser.parse_args()

    import ray_tpu

    wanted = {s.strip() for s in args.sections.split(",") if s.strip()}

    def want(name):
        return not wanted or name in wanted

    from benchmarks.perf_bench import host_calibration

    out = {"host_cpus": os.cpu_count(),
           "host_calibration": host_calibration(),
           "note": "single-core host; reference envelope runs on a 64+"
                   "-node AWS fleet (release/benchmarks/README.md)"}

    ray_tpu.shutdown()
    # The old hard-coded 10-CPU head pinned max_concurrent_runnable at
    # 10k (0.001-CPU tasks); the ceiling is CLI-configurable now.
    ray_tpu.init(num_cpus=max(1.0, args.concurrency_target / 1000.0))
    if want("many_actors"):
        section("many_actors", lambda: many_actors(args.actors), out)
    if want("queued_tasks"):
        section("queued_tasks",
                lambda: queued_tasks(args.tasks,
                                     args.concurrency_target), out)
    if want("many_args"):
        section("many_args", many_args, out)
    if want("many_returns"):
        section("many_returns", many_returns, out)
    if want("placement_groups"):
        section("placement_groups",
                lambda: placement_groups(args.pgs), out)
    ray_tpu.shutdown()
    # these bring up their own multi-node clusters
    if want("broadcast"):
        section("broadcast", lambda: broadcast(args.broadcast_mb), out)
    if want("cluster_actors_and_tasks"):
        section("cluster_actors_and_tasks", cluster_actors_and_tasks,
                out)
    if want("cluster_remote_tasks"):
        section("cluster_remote_tasks", cluster_remote_tasks, out)
    if want("cluster_scale_chaos"):
        section("cluster_scale_chaos", cluster_scale_chaos, out)
    if want("chaos"):
        section("chaos",
                lambda: chaos(broadcast_mb=args.broadcast_mb), out)
    if want("tenancy"):
        section("tenancy", tenancy, out)
    if want("head"):
        section("head", lambda: head_leg(), out)
    if want("sched"):
        section("sched",
                lambda: sched(
                    n_tasks=args.sched_tasks,
                    n_actors=args.sched_actors,
                    concurrency_target=max(args.concurrency_target,
                                           100_000)), out)

    print(json.dumps(out, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)


if __name__ == "__main__":
    main()
