"""LLM serving benchmark: prefix/KV-cache A/B + TTFT curves — the
PR 16 proof artifact (reference: vLLM's shared-prefix benchmarks; the
claim here is the SERVING-plane win, measured same-run so ratios are
host-independent).

Legs (all in ONE process/run):

- **engine A/B**: a shared-prompt-head workload through ``LLMEngine``
  with the prefix cache OFF vs ON — alternating best-of-3 per side
  (the serve_rps_bench discipline: this box is noisily shared, one leg
  per side swings run-to-run). The cache-on side skips prefill for the
  shared head, so TTFT p50 must drop while tok/s holds; greedy outputs
  are asserted token-identical across the legs (the cache is a pure
  latency optimization, never a behavior change).
- **hit-rate vs concurrency**: cache on, cold start, the same workload
  at rising client concurrency. Same-wave admissions all miss (the
  chain is admitted after the wave), so the hit rate dilutes as
  concurrency approaches the request count — the curve quantifies it.
- **proxy SSE**: the workload through the REAL keep-alive proxy →
  replica path with per-request TTFT measured at the first SSE chunk,
  proving the cache + streaming hold end-to-end, not just in-process.

Bench absolutes are NOT comparable across hosts — compare the same-run
ratios and read ``host_calibration``.

Usage:
  python benchmarks/llm_bench.py [--requests 24] [--attempts 3]
      [--max-tokens 16] [--out benchmarks/BENCH_LLM_r16.json]

Writes one JSON doc to stdout (and to --out when given).
"""

from __future__ import annotations

import argparse
import http.client
import json
import math
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           max(0, math.ceil(len(sorted_vals) * q) - 1))]


def _ttft_stats(ttfts, n_tokens, wall):
    lat = sorted(ttfts)
    return {
        "requests": len(lat),
        "ttft_p50_ms": round(percentile(lat, 0.5) * 1e3, 2),
        "ttft_p99_ms": round(percentile(lat, 0.99) * 1e3, 2),
        "tok_s": round(n_tokens / max(wall, 1e-9), 1),
    }


def _bench_config():
    """Big enough that prefill COMPUTE dominates dispatch overhead —
    the regime the prefix cache targets (a dispatch-bound toy model
    under-states the win: skipping a trivial prefill saves less than
    the block-copy dispatches cost)."""
    import jax.numpy as jnp

    from ray_tpu.models.llama import LlamaConfig

    return LlamaConfig(vocab_size=2048, dim=256, n_layers=4, n_heads=8,
                       n_kv_heads=4, hidden_dim=512, max_seq_len=256,
                       dtype=jnp.float32, remat=False)


def _workload(shared_head, requests):
    """Shared-prompt-head workload: one long common head (the system
    prompt / few-shot block of a real serving mix), distinct 4-token
    tails so every request is a different generation."""
    head = [(7 * i + 3) % 500 + 1 for i in range(shared_head)]
    return [head + [(13 * j + k) % 500 + 1 for k in range(4)]
            for j in range(requests)]


def _run_engine_leg(cfg, params, prompts, max_tokens, concurrency,
                    cache_on, prime):
    """One engine attempt: fresh engine (fresh cache state), optional
    sequential priming request, then the workload at `concurrency`.
    Returns (ttft_stats + hit stats, {prompt_index: tokens})."""
    from ray_tpu._private.config import ray_config
    from ray_tpu.serve.llm import LLMEngine, SamplingParams

    ray_config.llm_prefix_cache = cache_on
    engine = LLMEngine(cfg, params, max_batch_size=8,
                       max_seq_len=cfg.max_seq_len, model="bench")
    engine.warmup(max_prompt_len=len(prompts[0]))
    lock = threading.Lock()
    ttfts: list = []
    outs: dict = {}

    def one(j, record=True):
        t0 = time.perf_counter()
        it = engine.generate(prompts[j], SamplingParams(
            max_tokens=max_tokens), stream=True)
        first = next(it)
        ttft = time.perf_counter() - t0
        toks = [first] + list(it)
        with lock:
            if record:
                ttfts.append(ttft)
            outs[j] = toks

    if prime:
        # Cold request runs alone on BOTH sides (identical schedule),
        # so the A/B p50 compares warm-path against warm-path.
        one(0, record=False)
    rest = [j for j in range(len(prompts)) if not (prime and j == 0)]
    chunks = [rest[i::concurrency] for i in range(concurrency)]

    def worker(chunk):
        for j in chunk:
            one(j)

    threads = [threading.Thread(target=worker, args=(c,))
               for c in chunks if c]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    stats = _ttft_stats(ttfts, sum(len(v) for j, v in outs.items()
                                   if j in set(x for c in chunks
                                               for x in c)), wall)
    if engine.prefix_cache is not None:
        cs = engine.prefix_cache.stats()
        total = cs["hits"] + cs["misses"]
        stats["kv_hits"] = cs["hits"]
        stats["kv_misses"] = cs["misses"]
        stats["hit_rate"] = round(cs["hits"] / total, 3) if total else 0.0
    engine.stop()
    return stats, outs


def _proxy_sse_leg(cfg, params, prompts, max_tokens, concurrency):
    """The workload through a real proxy → replica path over keep-alive
    connections, TTFT at the first SSE data chunk."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private import perf_stats
    from ray_tpu.serve.llm import LLMDeployment

    ray_tpu.shutdown()
    # The bench model's warmup compile (tens of seconds on CPU) would
    # blow the default ~4s replica-health window and get the replica
    # struck mid-warmup; widen supervision for the bench only.
    from ray_tpu._private.config import ray_config

    ray_config.serve_replica_health_timeout_s = 10.0  # bench-only
    ray_config.serve_replica_health_failures = 30
    ray_tpu.init(num_cpus=4)
    serve.run(
        serve.deployment(LLMDeployment).bind(
            cfg, lambda: params, max_batch_size=8,
            max_seq_len=cfg.max_seq_len,
            warmup_max_prompt_len=len(prompts[0])),
        route_prefix="/llm")
    proxy = serve.start_http_proxy()
    hits0 = perf_stats.counter("llm_kv_cache_hits").value
    miss0 = perf_stats.counter("llm_kv_cache_misses").value

    lock = threading.Lock()
    ttfts: list = []
    n_tokens = [0]
    errors: list = []

    def worker(chunk):
        conn = http.client.HTTPConnection(proxy.host, proxy.port,
                                          timeout=120)
        for j in chunk:
            t0 = time.perf_counter()
            conn.request(
                "POST", "/llm",
                body=json.dumps({"prompt_ids": prompts[j],
                                 "max_tokens": max_tokens,
                                 "stream": True}),
                headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200, resp.status
            buf = b""
            ttft = None
            toks = 0
            while True:
                chunk_b = resp.read1(65536)
                if not chunk_b:
                    break
                buf += chunk_b
                done = False
                while b"\n\n" in buf:
                    line, buf = buf.split(b"\n\n", 1)
                    if not line.startswith(b"data: "):
                        continue
                    if line[6:] == b"[DONE]":
                        done = True
                        break
                    if ttft is None:
                        ttft = time.perf_counter() - t0
                    toks += 1
                if done:
                    break
            resp.read()  # chunk terminator; keep-alive intact
            with lock:
                ttfts.append(ttft if ttft is not None else
                             time.perf_counter() - t0)
                n_tokens[0] += toks
        conn.close()

    def guarded(chunk):
        try:
            worker(chunk)
        except BaseException as e:  # noqa: BLE001 - reported below
            import traceback

            with lock:
                errors.append(traceback.format_exc())
                del e

    chunks = [list(range(len(prompts)))[i::concurrency]
              for i in range(concurrency)]
    threads = [threading.Thread(target=guarded, args=(c,))
               for c in chunks if c]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise RuntimeError("proxy SSE leg failed:\n" + errors[0])
    stats = _ttft_stats(ttfts, n_tokens[0], wall)
    stats["kv_hits"] = perf_stats.counter(
        "llm_kv_cache_hits").value - hits0
    stats["kv_misses"] = perf_stats.counter(
        "llm_kv_cache_misses").value - miss0
    total = stats["kv_hits"] + stats["kv_misses"]
    stats["hit_rate"] = round(stats["kv_hits"] / total, 3) if total \
        else 0.0
    serve.shutdown()
    ray_tpu.shutdown()
    return stats


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=24)
    parser.add_argument("--shared-head", type=int, default=192)
    parser.add_argument("--max-tokens", type=int, default=12)
    parser.add_argument("--attempts", type=int, default=3)
    parser.add_argument("--concurrency", type=int, default=4)
    parser.add_argument("--curve", default="1,4,8",
                        help="comma list of concurrency levels for the "
                             "hit-rate curve")
    parser.add_argument("--skip-proxy", action="store_true")
    parser.add_argument("--out", default="")
    args = parser.parse_args()

    import jax

    from ray_tpu._private.config import ray_config
    from ray_tpu.models.llama import init_params
    from benchmarks.perf_bench import host_calibration

    cal = host_calibration()
    cfg = _bench_config()
    params = init_params(cfg, jax.random.PRNGKey(0))
    ray_config.llm_kv_block_tokens = 32
    ray_config.llm_prefix_shm_tier = False  # engine-local legs

    prompts = _workload(args.shared_head, args.requests)
    workload = {
        "requests": args.requests,
        "shared_head_tokens": args.shared_head,
        "tail_tokens": 4,
        "max_tokens": args.max_tokens,
        "block_tokens": ray_config.llm_kv_block_tokens,
        "model": f"{cfg.n_layers}L/{cfg.dim}d float32 CPU "
                 f"(vocab {cfg.vocab_size}, max_seq "
                 f"{cfg.max_seq_len})",
    }

    # -- engine A/B: alternating best-of-N per side ----------------------
    sides = {"cache_off": [], "cache_on": []}
    outputs = {"cache_off": None, "cache_on": None}
    order = []
    for i in range(args.attempts):
        order += ["cache_off", "cache_on"] if i % 2 == 0 else \
            ["cache_on", "cache_off"]
    for side in order:
        stats, outs = _run_engine_leg(
            cfg, params, prompts, args.max_tokens, args.concurrency,
            cache_on=(side == "cache_on"), prime=True)
        sides[side].append(stats)
        # Greedy determinism across EVERY leg, both sides: the prefix
        # cache must never change a single sampled token.
        if outputs[side] is None:
            outputs[side] = outs
        assert outs == outputs[side], f"non-deterministic within {side}"
        print(f"  {side}: ttft_p50={stats['ttft_p50_ms']}ms "
              f"tok_s={stats['tok_s']}", file=sys.stderr)
    greedy_identical = outputs["cache_on"] == outputs["cache_off"]
    assert greedy_identical, "prefix cache changed greedy output"

    best = {side: min(runs, key=lambda s: s["ttft_p50_ms"])
            for side, runs in sides.items()}
    ab = {
        "cache_off": {**best["cache_off"],
                      "attempts": sides["cache_off"]},
        "cache_on": {**best["cache_on"], "attempts": sides["cache_on"]},
        "ttft_p50_speedup": round(
            best["cache_off"]["ttft_p50_ms"]
            / max(best["cache_on"]["ttft_p50_ms"], 1e-9), 2),
        "tok_s_ratio": round(
            best["cache_on"]["tok_s"]
            / max(best["cache_off"]["tok_s"], 1e-9), 3),
        "greedy_identical": greedy_identical,
    }

    # -- hit-rate vs concurrency (cold start: dilution included) ---------
    curve = []
    for conc in [int(c) for c in args.curve.split(",") if c]:
        stats, _outs = _run_engine_leg(
            cfg, params, prompts, args.max_tokens, conc,
            cache_on=True, prime=False)
        curve.append({"concurrency": conc, **stats})
        print(f"  curve conc={conc}: hit_rate={stats['hit_rate']} "
              f"ttft_p50={stats['ttft_p50_ms']}ms", file=sys.stderr)

    # -- proxy SSE -------------------------------------------------------
    proxy_sse = None
    if not args.skip_proxy:
        proxy_sse = _proxy_sse_leg(cfg, params, prompts,
                                   args.max_tokens, args.concurrency)
        print(f"  proxy_sse: ttft_p50={proxy_sse['ttft_p50_ms']}ms "
              f"hit_rate={proxy_sse['hit_rate']}", file=sys.stderr)

    doc = {
        "bench": "llm_serving",
        "revision": "r16",
        "host_calibration": cal,
        "workload": workload,
        "ab": ab,
        "hit_rate_vs_concurrency": curve,
        "proxy_sse": proxy_sse,
        "pass": {
            "greedy_identical": greedy_identical,
            "ttft_p50_improved": ab["ttft_p50_speedup"] > 1.0,
            "tok_s_no_worse": ab["tok_s_ratio"] >= 0.95,
        },
    }
    text = json.dumps(doc, indent=2, sort_keys=True)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0 if all(doc["pass"].values()) else 1


if __name__ == "__main__":
    sys.exit(main())
