"""One train-step timing for a (batch, remat) config — run one config per
process (HBM fragmentation across configs in one process causes spurious
OOMs). Driven by benchmarks/sweep_step.sh or manually:

    SWEEP_BATCH=8 SWEEP_REMAT=mlp python -m benchmarks.sweep_step
"""

from __future__ import annotations

import dataclasses
import os
import time


def main():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import (LlamaConfig, init_params_sharded,
                                init_train_state, loss_fn, make_optimizer,
                                make_train_step)
    from ray_tpu.parallel import MeshConfig, create_mesh

    batch = int(os.environ.get("SWEEP_BATCH", "4"))
    remat_s = os.environ.get("SWEEP_REMAT", "true")
    remat = {"true": True, "false": False}.get(remat_s, remat_s)
    seq = int(os.environ.get("SWEEP_SEQ", "2048"))

    cfg = dataclasses.replace(LlamaConfig.llama3_1b(), remat=remat)
    mesh = create_mesh(MeshConfig(data=-1, fsdp=1))
    params = init_params_sharded(cfg, mesh, jax.random.PRNGKey(0))
    tx = make_optimizer(3e-4, warmup_steps=0, moment_dtype=jnp.bfloat16)
    state = init_train_state(params, tx)
    del params
    step = make_train_step(
        lambda p, b: loss_fn(p, b, cfg, mesh=mesh), tx, mesh=mesh,
        batch_logical={"tokens": ("batch", "seq"),
                       "targets": ("batch", "seq")})
    key = jax.random.PRNGKey(1)
    tokens = jax.random.randint(key, (batch, seq), 0, cfg.vocab_size)
    bd = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

    state, m = step(state, bd)
    float(m["loss"])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(5):
            state, m = step(state, bd)
        float(m["loss"])
        best = min(best, (time.perf_counter() - t0) / 5)
    toks = batch * seq / best
    print(f"batch={batch} remat={remat_s}: {best * 1e3:.1f} ms/step, "
          f"{toks:.0f} tok/s")


if __name__ == "__main__":
    main()
