"""Ingest→train end-to-end benchmark (reference analog:
`release/air_tests/air_benchmarks/workloads/pytorch_training_e2e.py` —
the BASELINE.md "Dataset → trainer images/s" row).

Dataset (synthetic token blocks) → `iter_jax_batches` (background block
prefetch + async host→device staging one batch ahead) → sharded llama
train step. Reports tokens/s end to end, the data-wait fraction (how
much of wall time the step loop spent BLOCKED on ingest — ~0 means the
prefetch pipeline fully hides data behind compute), and writes a Chrome
trace (`--trace out.json`) where the overlap is visible as near-zero
`data_wait` slices between `train_step` slices.

Usage: python benchmarks/ingest_train_bench.py [--steps 30] [--trace f]
Writes one JSON line to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--batch", type=int, default=4)
    parser.add_argument("--seq", type=int, default=512)
    parser.add_argument("--blocks", type=int, default=24)
    parser.add_argument("--trace", default=None,
                        help="write a Chrome trace of the loop here")
    args = parser.parse_args()

    import numpy as np

    import jax
    import jax.numpy as jnp

    import ray_tpu
    from ray_tpu import data as rt_data
    from ray_tpu.models import (
        LlamaConfig,
        init_params_sharded,
        init_train_state,
        loss_fn,
        make_optimizer,
        make_train_step,
    )
    from ray_tpu.parallel import MeshConfig, create_mesh

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = LlamaConfig.llama3_1b() if on_tpu else LlamaConfig.debug()
    seq = min(args.seq, cfg.max_seq_len)
    mesh = create_mesh(MeshConfig(data=-1))
    params = init_params_sharded(cfg, mesh, jax.random.PRNGKey(0))
    tx = make_optimizer(1e-4, warmup_steps=0)
    state = init_train_state(params, tx)
    step = make_train_step(lambda p, b: loss_fn(p, b, cfg, mesh=mesh),
                           tx, mesh=mesh)

    rows_per_block = max(args.batch * 4, 16)

    def gen(batch):
        i = int(batch["id"][0])
        rng = np.random.RandomState(i)
        return {"tokens": rng.randint(
            0, cfg.vocab_size, (rows_per_block, seq)).astype(np.int32)}

    ds = rt_data.range(args.blocks, parallelism=args.blocks) \
        .map_batches(gen, batch_size=None)

    def epoch_batches():
        while True:  # loop the dataset so --steps sets the budget
            yield from ds.iter_jax_batches(
                batch_size=args.batch, prefetch_batches=2,
                drop_last=True)

    events = []  # chrome trace
    t_origin = time.perf_counter()

    def mark(name, t0, t1):
        events.append({
            "name": name, "ph": "X", "pid": 0, "tid": 0,
            "ts": (t0 - t_origin) * 1e6,
            "dur": (t1 - t0) * 1e6,
        })

    it = epoch_batches()
    # Warmup: first batch + first step (compile + platform stall).
    batch = next(it)
    tokens = jnp.asarray(np.asarray(batch["tokens"]))
    state, metrics = step(state, {
        "tokens": tokens, "targets": jnp.roll(tokens, -1, 1)})
    float(jax.device_get(metrics["loss"]))  # barrier

    data_wait = 0.0
    t_start = time.perf_counter()
    for i in range(args.steps):
        t0 = time.perf_counter()
        batch = next(it)  # blocks only if ingest lags compute
        t1 = time.perf_counter()
        data_wait += t1 - t0
        mark("data_wait", t0, t1)
        tokens = jnp.asarray(np.asarray(batch["tokens"]))
        state, metrics = step(state, {
            "tokens": tokens, "targets": jnp.roll(tokens, -1, 1)})
        t2 = time.perf_counter()
        mark("dispatch_step", t1, t2)
    loss = float(jax.device_get(metrics["loss"]))  # honest end barrier
    wall = time.perf_counter() - t_start

    tokens_total = args.steps * args.batch * seq
    ray_tpu.shutdown()

    if args.trace:
        with open(args.trace, "w") as f:
            json.dump({"traceEvents": events}, f)

    print(json.dumps({
        "metric": "ingest_train_tokens_per_s",
        "value": round(tokens_total / wall, 1),
        "unit": "tokens/s",
        "detail": {
            "config": "llama-1.24B" if on_tpu else "llama-debug-cpu",
            "steps": args.steps, "batch": args.batch, "seq": seq,
            "data_wait_fraction": round(data_wait / wall, 4),
            "data_wait_ms_per_step": round(
                data_wait / args.steps * 1e3, 2),
            "step_ms": round(wall / args.steps * 1e3, 1),
            "loss": round(loss, 3),
            "pipeline": "Dataset blocks -> prefetch thread -> "
                        "device_put one batch ahead -> train step",
        },
    }))


if __name__ == "__main__":
    main()
