from ray_tpu.experimental import state  # noqa: F401
