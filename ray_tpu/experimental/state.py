"""Typed state API: cluster introspection.

Reference: `python/ray/experimental/state/api.py` (`list_actors :738`,
`list_tasks :961`, `summarize_* :1278+`) backed by GcsTaskManager /
dashboard state aggregator. Here the sources are the worker's task-event
buffer, the backend actor table, and the GCS registries.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional

from ray_tpu._private import worker as worker_mod


def _worker():
    return worker_mod.global_worker()


def list_tasks(*, filters: Optional[List[tuple]] = None,
               limit: int = 10_000) -> List[Dict[str, Any]]:
    from ray_tpu._private.obs_plane import cluster_task_events

    # Cluster-wide on a head (node events arrive via the shipping
    # plane); plain process-local view everywhere else.
    events = cluster_task_events(_worker())[-limit:]
    rows = [
        {
            "task_id": ev.task_id,
            "name": ev.name,
            "type": ev.kind,
            "state": ev.state,
            "start_time_s": ev.start_s,
            "end_time_s": ev.end_s,
            "duration_s": ev.duration_s(),
            "node_id": ev.node_id,
            "worker": ev.worker,
            "error_message": ev.error,
            "actor_id": ev.actor_id,
            "job_id": ev.job_id,
        }
        for ev in events
    ]
    return _apply_filters(rows, filters)[:limit]


def list_actors(*, filters: Optional[List[tuple]] = None,
                limit: int = 10_000) -> List[Dict[str, Any]]:
    w = _worker()
    rows = []
    for actor_id, actor in list(w.backend._actors.items()):
        rows.append({
            "actor_id": actor_id.hex(),
            "state": actor.state,
            "class_name": getattr(actor.spec.func, "__name__",
                                  str(actor.spec.func)),
            "name": actor.spec.actor_name or "",
            "pending_tasks": actor.mailbox.qsize(),
            "death_cause": actor.death_cause,
        })
    return _apply_filters(rows, filters)[:limit]


def list_objects(*, limit: int = 10_000) -> List[Dict[str, Any]]:
    store = _worker().memory_store
    rows = []
    with store._lock:  # introspection only
        for oid, entry in list(store._entries.items())[:limit]:
            rows.append({
                "object_id": oid.hex(),
                "ready": entry.ready,
                "has_error": entry.error is not None,
                "local_refs": entry.local_refs,
            })
    return rows


def list_placement_groups(**kwargs) -> List[Dict[str, Any]]:
    from ray_tpu.util.placement_group import placement_group_table

    return [dict(pg_id=k, **v) for k, v in placement_group_table().items()]


def list_nodes(**kwargs) -> List[Dict[str, Any]]:
    return _worker().gcs.nodes()


def summarize_tasks() -> Dict[str, Any]:
    from ray_tpu._private.obs_plane import cluster_task_events

    counts: Dict[tuple, int] = collections.Counter()
    total_time: Dict[str, float] = collections.defaultdict(float)
    for ev in cluster_task_events(_worker()):
        counts[(ev.name, ev.state)] += 1
        if ev.duration_s():
            total_time[ev.name] += ev.duration_s()
    summary: Dict[str, Any] = {}
    for (name, state), n in counts.items():
        entry = summary.setdefault(
            name, {"states": {}, "total_time_s": 0.0})
        entry["states"][state] = n
        entry["total_time_s"] = round(total_time.get(name, 0.0), 6)
    return summary


def job_summary() -> Dict[str, Any]:
    """Per-job resource accounting (cluster-wide on a head): task counts
    by state, cumulative task CPU-seconds (summed execution time over
    retained events), objects + estimated bytes owned in this process's
    store, and serve requests by route. Untagged work rolls up under
    the ``""`` key so tenant totals always reconcile against the whole
    cluster."""
    from ray_tpu._private import perf_stats
    from ray_tpu._private.obs_plane import cluster_task_events

    w = _worker()
    jobs: Dict[str, Any] = {}

    def entry(job: str) -> Dict[str, Any]:
        e = jobs.get(job)
        if e is None:
            e = jobs[job] = {"tasks": {}, "cpu_seconds": 0.0,
                             "objects": 0, "object_store_bytes": 0,
                             "serve_requests": {}}
        return e

    for ev in cluster_task_events(w, sort=False):
        e = entry(ev.job_id or "")
        e["tasks"][ev.state] = e["tasks"].get(ev.state, 0) + 1
        dur = ev.duration_s()
        if dur:
            e["cpu_seconds"] += dur
    store = getattr(w, "memory_store", None)
    if store is not None and hasattr(store, "job_object_stats"):
        for job, (n, nbytes) in store.job_object_stats().items():
            e = entry(job)
            e["objects"] = n
            e["object_store_bytes"] = nbytes
    # Shared-arena bytes charged per producing job (tenancy budgets).
    plane = getattr(w, "shm_plane", None)
    if plane is not None and hasattr(plane, "job_arena_bytes"):
        for job, nbytes in plane.job_arena_bytes().items():
            entry(job)["arena_bytes"] = nbytes
    # Enforcement-side accounting: quota usage (running CPU milli +
    # high-water mark, queued, parked) and the per-job rejection/park/
    # rate-limit/arena-spill counters — the "what enforcement did to
    # me" half of a tenant's summary row.
    ledger = getattr(getattr(w, "backend", None), "quota_ledger", None)
    if ledger is not None:
        for job in ledger.jobs():
            entry(job)["quota"] = ledger.usage(job)
    for name, tags, stat in perf_stats.stats_items():
        if name not in ("job_quota_rejections", "job_quota_parks",
                        "job_quota_lease_denials", "job_rate_limited",
                        "job_arena_spill_bytes") or \
                not isinstance(stat, perf_stats.Counter) or \
                not stat.value:
            continue
        e = entry(dict(tags).get("job", ""))
        e.setdefault("enforcement", {})[name] = stat.value
    # Serve requests by (job, route) — recorded by the ingress in this
    # process (the proxy normally runs in the head/driver).
    for name, tags, stat in perf_stats.stats_items():
        if name != "serve_requests" or \
                not isinstance(stat, perf_stats.Counter):
            continue
        t = dict(tags)
        e = entry(t.get("job", ""))
        route = t.get("route", "(unmatched)")
        e["serve_requests"][route] = \
            e["serve_requests"].get(route, 0) + stat.value
    for e in jobs.values():
        e["cpu_seconds"] = round(e["cpu_seconds"], 6)
    return jobs


def summarize_actors() -> Dict[str, Any]:
    counts: Dict[tuple, int] = collections.Counter()
    for row in list_actors():
        counts[(row["class_name"], row["state"])] += 1
    summary: Dict[str, Any] = {}
    for (cls, state), n in counts.items():
        summary.setdefault(cls, {})[state] = n
    return summary


def summarize_objects() -> Dict[str, Any]:
    rows = list_objects()
    return {"total": len(rows),
            "with_error": sum(1 for r in rows if r["has_error"])}


def _apply_filters(rows, filters):
    if not filters:
        return rows
    out = []
    for row in rows:
        ok = True
        for key, op, value in filters:
            have = row.get(key)
            if op in ("=", "=="):
                ok = have == value
            elif op == "!=":
                ok = have != value
            else:
                raise ValueError(f"unsupported filter op {op!r}")
            if not ok:
                break
        if ok:
            out.append(row)
    return out
