"""Distributed tracing: task spans with cross-task parent linkage.

Role-equivalent to the reference's OpenTelemetry integration
(`ray.init(_tracing_startup_hook=...)` + `tracing_helper.py`, which
monkey-wraps remote calls to propagate span context through task
metadata): here the span context rides the TaskSpec itself
(`trace_parent`), every execution records a span in the task-event
buffer, and this module exports them in an OTLP-shaped JSON form any
OpenTelemetry backend can ingest after a trivial transform. No network
exporter is wired (the image has no collector); `export_spans()` returns
the list, `save_spans(path)` writes it.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ray_tpu._private import worker as worker_mod


def export_spans(worker=None) -> List[Dict[str, Any]]:
    """All recorded task spans, OTLP-shaped: traceId / spanId /
    parentSpanId / name / kind / start-end (ns) / status / attributes.

    On a cluster head this is the CLUSTER-wide view: worker-node events
    arrive through the shipping plane (`_private/obs_plane.py`), so one
    request's trace stitches across every node it touched, each span
    tagged with the node that executed it."""
    import time

    from ray_tpu._private.obs_plane import cluster_task_events

    w = worker or worker_mod.global_worker()
    spans = []
    # The full buffer (public snapshot API), not list_events' default
    # 10k tail — a truncated export would drop trace roots out from
    # under their children.
    for ev in cluster_task_events(w):
        running = ev.end_s is None
        end = time.time() if running else ev.end_s
        spans.append({
            "traceId": ev.trace_id or ev.task_id,
            "spanId": ev.task_id,
            "parentSpanId": ev.parent_span_id or None,
            "name": ev.name,
            "kind": "SPAN_KIND_INTERNAL",
            "startTimeUnixNano": int(ev.start_s * 1e9),
            "endTimeUnixNano": int(end * 1e9),
            # A still-running task must not export as a completed OK
            # span; UNSET + live end time mirrors chrome_trace.
            "status": {"code": "STATUS_CODE_ERROR" if ev.error
                       else ("STATUS_CODE_UNSET" if running
                             else "STATUS_CODE_OK"),
                       "message": ev.error},
            "attributes": {
                "ray_tpu.task_kind": ev.kind,
                "ray_tpu.node_id": ev.node_id,
                "ray_tpu.worker": ev.worker,
                "ray_tpu.actor_id": ev.actor_id or "",
                "ray_tpu.state": ev.state,
            },
        })
    spans.extend(_stage_spans({s["traceId"] for s in spans}))
    return spans


def _stage_spans(trace_ids) -> List[Dict[str, Any]]:
    """Synthetic stage spans from the critical-path engine, one per
    finished-request waterfall entry, sharing the request's traceId so
    an OTLP viewer shows the stage anatomy (proxy dispatch → replica
    execute → llm.prefill → ...) inside the same trace as the task
    spans. Durations are attributed (not wall-clock-positioned): each
    span is laid end-to-end from the request's finish timestamp minus
    its total, which preserves ordering and proportion."""
    from ray_tpu._private import critical_path

    out: List[Dict[str, Any]] = []
    for entry in critical_path.finished_waterfalls():
        trace_id = entry["trace_id"]
        t0 = entry["ts"] - (entry.get("total_s") or 0.0)
        cursor = t0
        parent = trace_id if trace_id in trace_ids else None
        for i, st in enumerate(entry.get("stages") or []):
            start, cursor = cursor, cursor + st["dur_s"]
            out.append({
                "traceId": trace_id,
                "spanId": f"stage:{st['stage']}:{i}:{trace_id[:8]}",
                "parentSpanId": parent,
                "name": f"stage.{st['stage']}",
                "kind": "SPAN_KIND_INTERNAL",
                "startTimeUnixNano": int(start * 1e9),
                "endTimeUnixNano": int(cursor * 1e9),
                "status": {"code": "STATUS_CODE_OK", "message": None},
                "attributes": {
                    "ray_tpu.stage": st["stage"],
                    "ray_tpu.route": entry.get("route") or "",
                    "ray_tpu.dominant_stage":
                        entry.get("dominant_stage") or "",
                },
            })
    return out


def get_trace(trace_id: str, worker=None) -> List[Dict[str, Any]]:
    """Spans belonging to one trace, in start-time order."""
    spans = [s for s in export_spans(worker) if s["traceId"] == trace_id]
    spans.sort(key=lambda s: s["startTimeUnixNano"])
    return spans


def save_spans(path: str, worker=None) -> int:
    spans = export_spans(worker)
    with open(path, "w") as f:
        json.dump(spans, f)
    return len(spans)


def current_trace_id(worker=None) -> Optional[str]:
    """The trace id of the currently executing task (None in the driver
    outside any task)."""
    w = worker or worker_mod.global_worker()
    from ray_tpu._private.task_spec import trace_id_of

    ctx = w.task_context.current()
    if ctx is None:
        return None
    return trace_id_of(ctx["task_spec"])
