"""Result: what a training/tuning run returns.

Reference: `python/ray/air/result.py` — final metrics, best checkpoint,
error (if any), and the full metrics history.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint


@dataclass
class Result:
    metrics: Optional[Dict[str, Any]] = None
    checkpoint: Optional[Checkpoint] = None
    error: Optional[Exception] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    path: Optional[str] = None
    best_checkpoints: List[tuple] = field(default_factory=list)

    @property
    def metrics_dataframe(self):
        import pandas as pd

        return pd.DataFrame(self.metrics_history)

    @property
    def config(self) -> Optional[dict]:
        return (self.metrics or {}).get("config")
