"""ray_tpu.air: shared ML plumbing (reference `python/ray/air/`).

Checkpoint (dict ↔ directory ↔ bytes, pytree-aware), ScalingConfig with
TPU mesh axes instead of `use_gpu`, RunConfig/FailureConfig/
CheckpointConfig, the worker-side `session` API, and Result.
"""

from ray_tpu.air.batch_predictor import (  # noqa: F401
    BatchPredictor,
    JaxPredictor,
    Predictor,
    TorchPredictor,
)
from ray_tpu.air.checkpoint import Checkpoint  # noqa: F401
from ray_tpu.air.config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.result import Result  # noqa: F401
from ray_tpu.air import session  # noqa: F401
