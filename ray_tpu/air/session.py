"""Worker-side training session API.

Reference: `python/ray/air/session.py` — `session.report(metrics,
checkpoint=)` is the single channel from the user's train loop back to the
framework (`:43`), plus rank/shard accessors. The active session is a
thread-local set up by the worker-group actor running the loop.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint

_local = threading.local()


class TrainSession:
    """Backing object; created by `train/_internal` per worker."""

    def __init__(self, *, world_rank: int = 0, world_size: int = 1,
                 local_rank: int = 0, local_world_size: int = 1,
                 node_rank: int = 0, dataset_shards: Optional[dict] = None,
                 checkpoint: Optional[Checkpoint] = None,
                 trial_name: str = "", trial_id: str = "",
                 experiment_name: str = ""):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.local_world_size = local_world_size
        self.node_rank = node_rank
        self.dataset_shards = dataset_shards or {}
        self.loaded_checkpoint = checkpoint
        self.trial_name = trial_name
        self.trial_id = trial_id
        self.experiment_name = experiment_name
        self._results: list = []
        self._lock = threading.Lock()
        self._iteration = 0

    # called by the user loop
    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        with self._lock:
            self._iteration += 1
            metrics = dict(metrics)
            metrics.setdefault("training_iteration", self._iteration)
            self._results.append((metrics, checkpoint))

    # called by the framework poller
    def drain_results(self) -> list:
        with self._lock:
            out = self._results
            self._results = []
            return out


def _session() -> TrainSession:
    s = getattr(_local, "session", None)
    if s is None:
        raise RuntimeError(
            "No training session active — session.* may only be called "
            "inside a train loop launched by a Trainer.")
    return s


def set_session(s: Optional[TrainSession]) -> None:
    """Install (or clear, with None) the ambient per-thread train session.
    Public: the train/tune worker loops are the callers."""
    _local.session = s


def get_session() -> Optional[TrainSession]:
    return getattr(_local, "session", None)


# -- public API (mirrors reference naming) ---------------------------------


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    _session().report(metrics, checkpoint=checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return _session().loaded_checkpoint


def get_dataset_shard(name: str = "train"):
    return _session().dataset_shards.get(name)


def get_world_rank() -> int:
    return _session().world_rank


def get_world_size() -> int:
    return _session().world_size


def get_local_rank() -> int:
    return _session().local_rank


def get_local_world_size() -> int:
    return _session().local_world_size


def get_node_rank() -> int:
    return _session().node_rank


def get_trial_name() -> str:
    return _session().trial_name


def get_trial_id() -> str:
    return _session().trial_id


def get_experiment_name() -> str:
    return _session().experiment_name
