"""Checkpoint: the universal training-artifact currency.

Reference: `python/ray/air/checkpoint.py:63` — a checkpoint freely
interconverts between dict, directory, bytes, and object-store forms.
Extended here with pytree awareness: JAX arrays (including sharded ones)
are fetched to host numpy on save and restored with `jax.device_put` on
load, so checkpoints round-trip across mesh topologies (the elastic
re-slice + restore recovery path, SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import io
import os
import pickle
import shutil
import tarfile
import tempfile
from typing import Any, Dict, Optional

import numpy as np

_PYTREE_FILE = "pytree.npz"
_META_FILE = "checkpoint_meta.pkl"


def _to_host(tree):
    """jax/device arrays → numpy, leaving other leaves untouched."""
    try:
        import jax

        return jax.tree.map(
            lambda x: np.asarray(jax.device_get(x))
            if isinstance(x, jax.Array) else x, tree)
    except ImportError:  # pragma: no cover
        return tree


class Checkpoint:
    def __init__(self, data: Optional[Dict[str, Any]] = None,
                 local_path: Optional[str] = None):
        if (data is None) == (local_path is None):
            raise ValueError("exactly one of data/local_path required")
        self._data = data
        self._local_path = local_path

    # -- constructors ----------------------------------------------------

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=_to_host(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(local_path=path)

    @classmethod
    def from_bytes(cls, blob: bytes) -> "Checkpoint":
        data = pickle.loads(blob)
        if isinstance(data, dict) and set(data) == {"__tar__"}:
            # Directory-backed checkpoint serialized by to_bytes(): unpack
            # the tarball so the round trip yields a dir checkpoint again
            # (reference: air/checkpoint.py _FS_CHECKPOINT_KEY handling).
            path = tempfile.mkdtemp(prefix="ckpt_")
            with tarfile.open(fileobj=io.BytesIO(data["__tar__"]),
                              mode="r") as tar:
                tar.extractall(path, filter="data")
            return cls(local_path=path)
        return cls(data=data)

    @classmethod
    def from_uri(cls, uri: str) -> "Checkpoint":
        if uri.startswith("file://"):
            return cls.from_directory(uri[len("file://"):])
        return cls.from_directory(uri)

    # -- conversions -----------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return self._data
        meta_path = os.path.join(self._local_path, _META_FILE)
        npz_path = os.path.join(self._local_path, _PYTREE_FILE)
        if os.path.exists(meta_path):
            with open(meta_path, "rb") as f:
                data = pickle.load(f)
            if os.path.exists(npz_path):
                arrays = np.load(npz_path, allow_pickle=False)
                flat = [arrays[k] for k in sorted(
                    arrays.files, key=lambda s: int(s.split("_")[1]))]
                import jax

                treedef = data.pop("__treedef__")
                data["__pytree__"] = jax.tree.unflatten(treedef, flat)
            return data
        # Arbitrary directory: pack file contents.
        out: Dict[str, Any] = {}
        for root, _, files in os.walk(self._local_path):
            for fname in files:
                p = os.path.join(root, fname)
                rel = os.path.relpath(p, self._local_path)
                with open(p, "rb") as f:
                    out[rel] = f.read()
        return out

    def to_directory(self, path: Optional[str] = None) -> str:
        path = path or tempfile.mkdtemp(prefix="ckpt_")
        os.makedirs(path, exist_ok=True)
        if self._local_path is not None:
            if os.path.abspath(self._local_path) != os.path.abspath(path):
                shutil.copytree(self._local_path, path, dirs_exist_ok=True)
            return path
        data = dict(self._data)
        pytree = data.pop("__pytree__", None)
        if pytree is not None:
            import jax

            flat, treedef = jax.tree.flatten(_to_host(pytree))
            np.savez(os.path.join(path, _PYTREE_FILE),
                     **{f"leaf_{i}": np.asarray(x)
                        for i, x in enumerate(flat)})
            data["__treedef__"] = treedef
        with open(os.path.join(path, _META_FILE), "wb") as f:
            pickle.dump(data, f)
        return path

    def to_bytes(self) -> bytes:
        if self._data is not None:
            return pickle.dumps(_to_host(self._data))
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tar:
            tar.add(self._local_path, arcname=".")
        return pickle.dumps({"__tar__": buf.getvalue()})

    def to_uri(self, uri: str) -> str:
        assert uri.startswith("file://"), "only file:// URIs supported"
        return "file://" + self.to_directory(uri[len("file://"):])

    # -- pytree sugar ----------------------------------------------------

    @classmethod
    def from_pytree(cls, tree, **extra) -> "Checkpoint":
        """Store a JAX pytree (e.g. a TrainState) plus metadata."""
        return cls(data={"__pytree__": _to_host(tree), **extra})

    def to_pytree(self, *, shardings=None):
        """Restore the pytree; with `shardings` (matching structure) the
        leaves are placed directly onto the mesh."""
        data = self.to_dict()
        tree = data.get("__pytree__")
        if tree is None:
            raise ValueError("checkpoint has no pytree payload")
        if shardings is not None:
            import jax

            tree = jax.tree.map(
                lambda x, s: jax.device_put(x, s), tree, shardings)
        return tree

    def metadata(self) -> Dict[str, Any]:
        d = self.to_dict()
        return {k: v for k, v in d.items() if k != "__pytree__"}

    def __repr__(self):
        kind = "dict" if self._data is not None else "dir"
        return f"Checkpoint({kind})"
