"""Run/scaling/failure/checkpoint configs.

Reference: `python/ray/air/config.py:80` (ScalingConfig), `:508`
(FailureConfig), `:567` (CheckpointConfig), `:695` (RunConfig). The TPU
shift: `use_gpu` becomes `use_tpu` + a `mesh` (MeshConfig or axis dict) —
parallelism is declared as named mesh axes (dp/fsdp/tp/sp/ep/pp) rather
than inferred from a flat worker count, and placement groups reserve
whole ICI slices for the group.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

from ray_tpu.parallel.mesh import MeshConfig


@dataclass
class ScalingConfig:
    """How a Train run scales.

    num_workers: actors in the worker group — one per *host/process*
    (on TPU pods the in-host parallelism is the mesh, not more workers).
    mesh: named-axis parallelism spec applied inside each SPMD program.
    """

    num_workers: int = 1
    use_tpu: bool = False
    mesh: Optional[Union[MeshConfig, Dict[str, int]]] = None
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    trainer_resources: Optional[Dict[str, float]] = None

    def mesh_config(self) -> MeshConfig:
        if self.mesh is None:
            return MeshConfig()
        if isinstance(self.mesh, MeshConfig):
            return self.mesh
        return MeshConfig(**self.mesh)

    @property
    def num_cpus_per_worker(self) -> float:
        return (self.resources_per_worker or {}).get("CPU", 1.0)

    @property
    def num_tpus_per_worker(self) -> float:
        default = 1.0 if self.use_tpu else 0.0
        return (self.resources_per_worker or {}).get("TPU", default)

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu:
            res.setdefault("TPU", 1.0)
        return res

    def as_placement_group_factory(self):
        from ray_tpu.util.placement_group import PlacementGroupFactory

        bundles = [dict(self.trainer_resources or {"CPU": 0.0})]
        bundles += [self.worker_resources()
                    for _ in range(self.num_workers)]
        return PlacementGroupFactory(bundles,
                                     strategy=self.placement_strategy)


@dataclass
class FailureConfig:
    """Reference: `air/config.py:508`."""

    max_failures: int = 0
    fail_fast: bool = False


@dataclass
class CheckpointConfig:
    """Reference: `air/config.py:567` — keep top-K by score."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0
    checkpoint_at_end: bool = False

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be max|min")


@dataclass
class RunConfig:
    """Reference: `air/config.py:695`."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(
        default_factory=CheckpointConfig)
    stop: Optional[Union[Dict[str, Any], Callable]] = None
    verbose: int = 1
    callbacks: List[Any] = field(default_factory=list)
    log_to_file: bool = False
    # ray_tpu.tune.syncer.SyncConfig — uploads the experiment dir to
    # durable storage after checkpoint events (reference tune/syncer.py).
    sync_config: Optional[Any] = None
