"""Predictor ABC + BatchPredictor: offline inference over Datasets.

Reference: `python/ray/train/predictor.py` (Predictor ABC:
`from_checkpoint`, `predict(batch)`) and
`python/ray/train/batch_predictor.py` (BatchPredictor: map a predictor
over a Dataset with actor-pool compute so the model loads once per
actor, not once per batch). TPU shape: a JaxPredictor's apply_fn is
jit-compiled once per actor and batches stream through it.
"""

from __future__ import annotations

from typing import Any, Callable, Type

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint


class Predictor:
    """Stateful inference wrapper built from a Checkpoint."""

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch) -> Any:
        """batch: dict of arrays (or a single array under "data")."""
        raise NotImplementedError


def _unwrap_batch(batch):
    """dict batch → its "data" column (or sole column); else as-is."""
    if isinstance(batch, dict):
        arr = batch.get("data")
        if arr is None:
            arr = next(iter(batch.values()))
        return arr
    return batch


class JaxPredictor(Predictor):
    """Runs a jitted apply_fn(params, batch_array) (reference
    TorchPredictor's role for the JAX stack)."""

    def __init__(self, params, apply_fn: Callable, jit: bool = True):
        import jax

        self.params = params
        self.apply_fn = jax.jit(apply_fn) if jit else apply_fn

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *,
                        apply_fn: Callable, params_key: str = "params",
                        **kwargs) -> "JaxPredictor":
        data = checkpoint.to_dict()
        return cls(data[params_key], apply_fn, **kwargs)

    def predict(self, batch):
        import jax.numpy as jnp

        arr = _unwrap_batch(batch)
        out = self.apply_fn(self.params, jnp.asarray(np.asarray(arr)))
        return {"predictions": np.asarray(out)}


class TorchPredictor(Predictor):
    """Runs a torch module restored from a TorchCheckpoint state dict."""

    def __init__(self, model):
        self.model = model
        self.model.eval()

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *,
                        model=None, **kwargs) -> "TorchPredictor":
        from ray_tpu.train.torch import TorchCheckpoint

        if model is None:
            raise ValueError("TorchPredictor.from_checkpoint needs "
                             "model= (an uninitialized torch module)")
        return cls(TorchCheckpoint.get_model(checkpoint, model))

    def predict(self, batch):
        import torch

        arr = _unwrap_batch(batch)
        with torch.no_grad():
            out = self.model(torch.as_tensor(np.asarray(arr)))
        return {"predictions": out.numpy()}


class BatchPredictor:
    """Map a Predictor over a Dataset with actor-pool compute: each pool
    actor builds the predictor ONCE (model load / jit compile amortized
    across its batches)."""

    def __init__(self, checkpoint: Checkpoint,
                 predictor_cls: Type[Predictor],
                 **predictor_kwargs: Any):
        self.checkpoint = checkpoint
        self.predictor_cls = predictor_cls
        self.predictor_kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        predictor_cls: Type[Predictor],
                        **predictor_kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **predictor_kwargs)

    def predict(self, dataset, *, batch_size: int = 256,
                min_actors: int = 1, max_actors: int = 2,
                num_cpus: float = 1.0):
        from ray_tpu.data.plan import ActorPoolStrategy

        ckpt_data = self.checkpoint.to_dict()
        predictor_cls = self.predictor_cls
        predictor_kwargs = self.predictor_kwargs

        class _PredictCallable:
            def __init__(self):
                self.predictor = predictor_cls.from_checkpoint(
                    Checkpoint.from_dict(ckpt_data), **predictor_kwargs)

            def __call__(self, batch):
                return self.predictor.predict(batch)

        return dataset.map_batches(
            _PredictCallable, batch_size=batch_size,
            batch_format="numpy",
            compute=ActorPoolStrategy(size=max_actors,
                                      min_size=min_actors),
            num_cpus=num_cpus)
