"""Checkpoint manager: keep top-K checkpoints by score.

Reference: `python/ray/air/_internal/checkpoint_manager.py` +
`CheckpointConfig` (`air/config.py:567`).
"""

from __future__ import annotations

import heapq
import itertools
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig


class CheckpointManager:
    def __init__(self, config: Optional[CheckpointConfig] = None):
        self.config = config or CheckpointConfig()
        self._heap: List[Tuple[float, int, Checkpoint, dict]] = []
        self._counter = itertools.count()
        self.latest: Optional[Checkpoint] = None
        self.latest_metrics: Optional[dict] = None

    def register(self, checkpoint: Checkpoint,
                 metrics: Optional[Dict[str, Any]] = None) -> None:
        metrics = metrics or {}
        self.latest = checkpoint
        self.latest_metrics = metrics
        attr = self.config.checkpoint_score_attribute
        if attr is not None and attr in metrics:
            score = float(metrics[attr])
        else:
            score = float(metrics.get("training_iteration", 0))
        # Min-heap of "badness": pop the worst when over capacity.
        sign = 1.0 if self.config.checkpoint_score_order == "max" else -1.0
        heapq.heappush(self._heap,
                       (sign * score, next(self._counter), checkpoint,
                        metrics))
        keep = self.config.num_to_keep
        if keep is not None and len(self._heap) > keep:
            heapq.heappop(self._heap)

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self._heap:
            return self.latest
        return max(self._heap)[2]

    @property
    def best_metrics(self) -> Optional[dict]:
        if not self._heap:
            return self.latest_metrics
        return max(self._heap)[3]

    def best_checkpoints(self) -> List[Tuple[Checkpoint, dict]]:
        return [(c, m) for _, _, c, m in sorted(self._heap, reverse=True)]
