"""ray_tpu.dag: lazy DAGs over tasks and actors.

Reference: `python/ray/dag/` — `DAGNode` graph built from
`fn.bind(...)` / `ActorClass.bind(...)` with `InputNode` placeholders;
`.execute(input)` walks the graph submitting tasks/actor calls. Used by
serve graphs and `ray_tpu.workflow`.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional

import ray_tpu


class DAGNode:
    def __init__(self, args: tuple = (), kwargs: Optional[dict] = None):
        self._bound_args = args
        self._bound_kwargs = kwargs or {}
        self._uuid = uuid.uuid4().hex

    # -- traversal -------------------------------------------------------

    def _children(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def _resolve_args(self, cache: Dict[str, Any], dag_input):
        args = [self._resolve_one(a, cache, dag_input)
                for a in self._bound_args]
        kwargs = {k: self._resolve_one(v, cache, dag_input)
                  for k, v in self._bound_kwargs.items()}
        return tuple(args), kwargs

    @staticmethod
    def _resolve_one(v, cache, dag_input):
        if isinstance(v, DAGNode):
            return v._execute_impl(cache, dag_input)
        return v

    # -- execution -------------------------------------------------------

    def execute(self, *input_args, _get: bool = True):
        """Run the DAG; leaf results fetched unless _get=False (then an
        ObjectRef or value is returned as produced)."""
        dag_input = input_args[0] if input_args else None
        cache: Dict[str, Any] = {}
        out = self._execute_impl(cache, dag_input)
        if _get and isinstance(out, ray_tpu.ObjectRef):
            return ray_tpu.get(out)
        return out

    def _execute_impl(self, cache: Dict[str, Any], dag_input):
        if self._uuid in cache:
            return cache[self._uuid]
        result = self._run(cache, dag_input)
        cache[self._uuid] = result
        return result

    def _run(self, cache, dag_input):
        raise NotImplementedError


class InputNode(DAGNode):
    """Placeholder for the value passed to `.execute(value)`."""

    def __init__(self):
        super().__init__()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _run(self, cache, dag_input):
        return dag_input


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._fn = remote_fn

    def _run(self, cache, dag_input):
        args, kwargs = self._resolve_args(cache, dag_input)
        return self._fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """An actor instantiation in the graph; methods create
    ClassMethodNodes."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._cls = actor_cls
        self._actor_handle = None

    def _run(self, cache, dag_input):
        if self._actor_handle is None:
            args, kwargs = self._resolve_args(cache, dag_input)
            self._actor_handle = self._cls.remote(*args, **kwargs)
        return self._actor_handle

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodBinder(self, name)


class _MethodBinder:
    def __init__(self, class_node: ClassNode, method: str):
        self._node = class_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._node, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method = method

    def _children(self):
        return super()._children() + [self._class_node]

    def _run(self, cache, dag_input):
        handle = self._class_node._execute_impl(cache, dag_input)
        args, kwargs = self._resolve_args(cache, dag_input)
        resolved = [ray_tpu.get(a) if isinstance(a, ray_tpu.ObjectRef)
                    else a for a in args]
        return getattr(handle, self._method).remote(*resolved, **kwargs)


def _install_bind():
    """Add `.bind()` to RemoteFunction and ActorClass (reference wires
    this in `ray/dag` import)."""
    from ray_tpu.actor import ActorClass
    from ray_tpu.remote_function import RemoteFunction

    def fn_bind(self, *args, **kwargs):
        return FunctionNode(self, args, kwargs)

    def cls_bind(cls_self, *args, **kwargs):
        return ClassNode(cls_self, args, kwargs)

    RemoteFunction.bind = fn_bind
    ActorClass.bind = cls_bind


_install_bind()
