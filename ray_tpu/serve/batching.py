"""@serve.batch: dynamic request batching.

Reference: `python/ray/serve/batching.py` — concurrent calls to the
decorated method are grouped (up to `max_batch_size`, waiting at most
`batch_wait_timeout_s`) and executed once over the list; each caller gets
its element back. Essential for ML serving: the replica turns N
single-sample requests into one batched device invocation.
"""

from __future__ import annotations

import functools
import queue
import threading
import weakref
from concurrent.futures import Future
from typing import Callable, List, Optional


_ALL_BATCHERS: "weakref.WeakSet[_Batcher]" = weakref.WeakSet()


def retire_all_batchers() -> None:
    """Ask every live batcher's drain thread to retire (queued work
    still runs first; the batcher itself stays usable — a later submit
    just respawns its thread). ``serve.shutdown()`` calls this so
    driver-side ``@serve.batch`` handlers that nobody explicitly shut
    down don't keep their 5s-idle threads past teardown."""
    for b in list(_ALL_BATCHERS):
        try:
            b.retire()
        except Exception:
            pass


class _Batcher:
    _STOP = object()  # drain sentinel: queued work ahead of it still runs

    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self.queue: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._closed = False
        _ALL_BATCHERS.add(self)

    def _ensure_thread(self):
        with self._lock:
            if self._closed:
                return
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True,
                                                name="serve-batcher")
                self._thread.start()

    def _loop(self):
        while True:
            try:
                first = self.queue.get(timeout=5.0)
            except queue.Empty:
                return  # idle thread exits; recreated on demand
            if first is self._STOP:
                self._handoff_if_stale_stop()
                return
            batch = [first]
            deadline = self.timeout
            while len(batch) < self.max_batch_size:
                try:
                    item = self.queue.get(timeout=deadline)
                except queue.Empty:
                    break
                if item is self._STOP:
                    # Re-queue so the outer get observes it AFTER this
                    # (already accepted) batch has run.
                    self.queue.put(self._STOP)
                    break
                batch.append(item)
            self._run(batch)

    def _handoff_if_stale_stop(self) -> None:
        """Called on consuming a STOP sentinel. retire() checks
        ``is_alive`` without holding the thread's idle-exit race, so a
        sentinel can land in an EMPTY queue after the thread already
        retired — and the next submit's respawned thread would then eat
        the stale sentinel and exit with that submit's item queued
        behind it, stranding the caller's future. submit() enqueues
        BEFORE _ensure_thread, so real work behind a stale sentinel is
        always visible here: spawn a successor for it."""
        with self._lock:
            if not self._closed and not self.queue.empty() \
                    and self._thread is threading.current_thread():
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True,
                                                name="serve-batcher")
                self._thread.start()

    def retire(self, timeout: float = 5.0) -> None:
        """Stop the drain thread WITHOUT closing the batcher: queued
        work still runs (the sentinel lands behind it), and a later
        submit simply respawns the thread. The teardown-sweep form —
        ``shutdown`` is the permanent one."""
        with self._lock:
            t = self._thread
        if t is not None and t.is_alive():
            self.queue.put(self._STOP)
            t.join(timeout)

    def shutdown(self, timeout: float = 5.0) -> None:
        """Stop the drain thread. Work queued before the call still
        runs — the sentinel lands behind it — and anything that raced
        past the closed check gets its Future failed, so no accepted
        request is left permanently pending."""
        with self._lock:
            self._closed = True
            t = self._thread
        if t is not None and t.is_alive():
            self.queue.put(self._STOP)
            t.join(timeout)
        # A submit() that passed the closed check before we set it may
        # have enqueued BEHIND the sentinel; fail those futures rather
        # than strand their callers.
        while True:
            try:
                item = self.queue.get_nowait()
            except queue.Empty:
                break
            if item is self._STOP:
                continue
            fut, _ = item
            if not fut.done():
                fut.set_exception(RuntimeError("batcher is shut down"))

    def _run(self, batch: List[tuple]):
        futures = [f for f, _ in batch]
        items = [x for _, x in batch]
        try:
            results = self.fn(items)
            if results is None or len(results) != len(items):
                raise ValueError(
                    f"@serve.batch function must return a list of "
                    f"length {len(items)}, got {results!r}")
            for f, r in zip(futures, results):
                f.set_result(r)
        except BaseException as e:  # noqa: BLE001
            for f in futures:
                if not f.done():
                    f.set_exception(e)

    def submit(self, item) -> Future:
        f: Future = Future()
        # Check-and-enqueue under the lock: shutdown() sets _closed
        # under the same lock before its final drain, so an accepted
        # put is always visible to that drain (or to a live thread) —
        # no caller can be stranded between the two.
        with self._lock:
            if self._closed:
                f.set_exception(RuntimeError("batcher is shut down"))
                return f
            self.queue.put((f, item))  # raylint: disable=R2 -- unbounded queue, put() cannot block; closed-check + enqueue must be one atomic step or shutdown's final drain can miss an accepted item
        self._ensure_thread()
        return f


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator. The wrapped function must accept a list and return a
    list of equal length; callers pass single items."""

    def decorate(fn: Callable):
        return _BatchWrapper(fn, max_batch_size, batch_wait_timeout_s)

    if _fn is not None:
        return decorate(_fn)
    return decorate


class _BatchWrapper:
    """The decorated callable: a descriptor, so that on a method both
    the sync call AND ``.aio`` see the bound instance (a plain function
    attribute would lose ``self`` for ``await self.method.aio(item)``
    — attribute lookup on a bound method reaches the raw function)."""

    _is_serve_batch = True

    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float, _instance=None):
        self._fn = fn
        self._max_batch_size = max_batch_size
        self._timeout_s = batch_wait_timeout_s
        self._instance = _instance
        self._batchers: dict = {}
        functools.update_wrapper(self, fn)

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        bound = _BatchWrapper.__new__(_BatchWrapper)
        bound.__dict__ = dict(self.__dict__)
        bound._instance = obj
        # Share the batcher table with the unbound wrapper: per-instance
        # keying below keeps instances separate while repeated __get__
        # calls reuse the same batcher (a fresh table per lookup would
        # defeat batching entirely).
        bound._batchers = self._batchers
        return bound

    def _submit(self, args) -> Future:
        if self._instance is not None:
            args = (self._instance,) + args
        # Methods: bind per-instance so `self` stays out of the batch.
        if len(args) == 2 and not isinstance(args[0], (list, tuple)):
            self_obj, item = args
            key = id(self_obj)
            if key not in self._batchers:
                self._batchers[key] = _Batcher(
                    lambda items, s=self_obj: self._fn(s, items),
                    self._max_batch_size, self._timeout_s)
            return self._batchers[key].submit(item)
        (item,) = args
        if "fn" not in self._batchers:
            self._batchers["fn"] = _Batcher(
                self._fn, self._max_batch_size, self._timeout_s)
        return self._batchers["fn"].submit(item)

    def __call__(self, *args):
        return self._submit(args).result()

    def shutdown(self, timeout: float = 5.0) -> None:
        """Drain and stop every per-instance batcher thread (replica
        teardown hook); queued work still runs before threads retire."""
        for b in list(self._batchers.values()):
            b.shutdown(timeout)

    async def aio(self, *args):
        # Async batch wakeup: the batcher thread's set_result lands on
        # the caller's event loop instead of blocking it — N concurrent
        # awaiters on one loop still coalesce into one batched call.
        import asyncio

        return await asyncio.wrap_future(self._submit(args))
