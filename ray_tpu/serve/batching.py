"""@serve.batch: dynamic request batching.

Reference: `python/ray/serve/batching.py` — concurrent calls to the
decorated method are grouped (up to `max_batch_size`, waiting at most
`batch_wait_timeout_s`) and executed once over the list; each caller gets
its element back. Essential for ML serving: the replica turns N
single-sample requests into one batched device invocation.
"""

from __future__ import annotations

import functools
import queue
import threading
from concurrent.futures import Future
from typing import Any, Callable, List, Optional


class _Batcher:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self.queue: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True,
                                                name="serve-batcher")
                self._thread.start()

    def _loop(self):
        while True:
            try:
                first = self.queue.get(timeout=5.0)
            except queue.Empty:
                return  # idle thread exits; recreated on demand
            batch = [first]
            deadline = self.timeout
            while len(batch) < self.max_batch_size:
                try:
                    batch.append(self.queue.get(timeout=deadline))
                except queue.Empty:
                    break
            self._run(batch)

    def _run(self, batch: List[tuple]):
        futures = [f for f, _ in batch]
        items = [x for _, x in batch]
        try:
            results = self.fn(items)
            if results is None or len(results) != len(items):
                raise ValueError(
                    f"@serve.batch function must return a list of "
                    f"length {len(items)}, got {results!r}")
            for f, r in zip(futures, results):
                f.set_result(r)
        except BaseException as e:  # noqa: BLE001
            for f in futures:
                if not f.done():
                    f.set_exception(e)

    def submit(self, item) -> Future:
        f: Future = Future()
        self.queue.put((f, item))
        self._ensure_thread()
        return f


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator. The wrapped function must accept a list and return a
    list of equal length; callers pass single items."""

    def decorate(fn: Callable):
        batchers: dict = {}

        @functools.wraps(fn)
        def wrapper(*args):
            # Methods: bind per-instance so `self` stays out of the batch.
            if len(args) == 2 and not isinstance(args[0], (list, tuple)):
                self_obj, item = args
                key = id(self_obj)
                if key not in batchers:
                    batchers[key] = _Batcher(
                        lambda items, s=self_obj: fn(s, items),
                        max_batch_size, batch_wait_timeout_s)
                return batchers[key].submit(item).result()
            (item,) = args
            if "fn" not in batchers:
                batchers["fn"] = _Batcher(fn, max_batch_size,
                                          batch_wait_timeout_s)
            return batchers["fn"].submit(item).result()

        wrapper._is_serve_batch = True
        return wrapper

    if _fn is not None:
        return decorate(_fn)
    return decorate
