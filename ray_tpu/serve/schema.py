"""Declarative Serve config schemas + apply.

Reference: `python/ray/serve/schema.py` (pydantic models behind the REST
API and `serve deploy`) — here as validated dataclasses: a config file
describes applications by import path with per-deployment option
overrides; `apply_config` makes the cluster match it; `status_schema`
is the inverse (live state → config-shaped dict). The dashboard mounts
these at `/api/serve/applications/` (GET/PUT, reference REST surface)
and `scripts/cli.py serve` drives them from the command line.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Dict, List, Optional

_DEPLOYMENT_FIELDS = ("name", "num_replicas", "max_concurrent_queries",
                      "user_config", "autoscaling_config",
                      "ray_actor_options", "version")


@dataclasses.dataclass
class DeploymentSchema:
    """Per-deployment override block (reference DeploymentSchema)."""

    name: str
    num_replicas: Optional[int] = None
    max_concurrent_queries: Optional[int] = None
    user_config: Any = None
    autoscaling_config: Optional[dict] = None
    ray_actor_options: Optional[dict] = None
    version: Optional[str] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "DeploymentSchema":
        unknown = set(d) - set(_DEPLOYMENT_FIELDS)
        if unknown:
            raise ValueError(
                f"unknown deployment config keys: {sorted(unknown)} "
                f"(valid: {list(_DEPLOYMENT_FIELDS)})")
        if "name" not in d:
            raise ValueError("deployment config requires 'name'")
        return DeploymentSchema(**d)

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}


@dataclasses.dataclass
class ServeApplicationSchema:
    """One application: an import path to a bound deployment (graph)
    plus overrides (reference ServeApplicationSchema)."""

    import_path: str
    name: str = "default"
    route_prefix: Optional[str] = None
    deployments: List[DeploymentSchema] = dataclasses.field(
        default_factory=list)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ServeApplicationSchema":
        d = dict(d)
        unknown = set(d) - {"import_path", "name", "route_prefix",
                            "deployments"}
        if unknown:
            raise ValueError(
                f"unknown application config keys: {sorted(unknown)}")
        if "import_path" not in d:
            raise ValueError("application config requires 'import_path'")
        deps = [DeploymentSchema.from_dict(x)
                for x in d.pop("deployments", [])]
        return ServeApplicationSchema(deployments=deps, **d)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"import_path": self.import_path,
                               "name": self.name}
        if self.route_prefix is not None:
            out["route_prefix"] = self.route_prefix
        if self.deployments:
            out["deployments"] = [x.to_dict() for x in self.deployments]
        return out


@dataclasses.dataclass
class ServeDeploySchema:
    """Top-level config: the list of applications (reference
    ServeDeploySchema)."""

    applications: List[ServeApplicationSchema]

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ServeDeploySchema":
        unknown = set(d) - {"applications", "proxy_location",
                            "http_options"}
        if unknown:
            raise ValueError(f"unknown serve config keys: "
                             f"{sorted(unknown)}")
        apps = d.get("applications")
        if not isinstance(apps, list) or not apps:
            raise ValueError("serve config requires a non-empty "
                             "'applications' list")
        return ServeDeploySchema(
            applications=[ServeApplicationSchema.from_dict(a)
                          for a in apps])

    def to_dict(self) -> Dict[str, Any]:
        return {"applications": [a.to_dict()
                                 for a in self.applications]}


def import_target(import_path: str):
    """Resolve "pkg.module:attr" to the bound application object."""
    if ":" not in import_path:
        raise ValueError(
            f"import path {import_path!r} must be 'module:attribute'")
    module_name, attr = import_path.split(":", 1)
    module = importlib.import_module(module_name)
    target = module
    for part in attr.split("."):
        target = getattr(target, part)
    return target


def _apply_overrides(target, overrides: Dict[str, DeploymentSchema]):
    """Rebuild an Application tree with per-deployment overrides."""
    from ray_tpu.serve import Application

    if not isinstance(target, Application):
        return target

    def rebuild(value):
        if isinstance(value, Application):
            dep = value.deployment
            sch = overrides.get(dep.name)
            if sch is not None:
                dep = dep.options(**sch.to_dict())
            args = tuple(rebuild(a) for a in value.args)
            kwargs = {k: rebuild(v) for k, v in value.kwargs.items()}
            return Application(dep, args, kwargs)
        if isinstance(value, (list, tuple)):
            return type(value)(rebuild(v) for v in value)
        if isinstance(value, dict):
            return {k: rebuild(v) for k, v in value.items()}
        return value

    return rebuild(target)


def apply_config(config: Dict[str, Any], *, blocking: bool = True):
    """Make the cluster match a declarative config (the PUT
    /api/serve/applications handler and `serve deploy`). Returns
    {app_name: ServeHandle}."""
    from ray_tpu import serve

    schema = ServeDeploySchema.from_dict(config)
    handles = {}
    for app in schema.applications:
        target = import_target(app.import_path)
        if isinstance(target, serve.Deployment):
            # bind here (not in serve.run) so overrides below can walk
            # the Application tree
            target = target.bind()
        overrides = {d.name: d for d in app.deployments}
        target = _apply_overrides(target, overrides)
        handles[app.name] = serve.run(
            target, name=app.name, route_prefix=app.route_prefix,
            _blocking=blocking)
    return handles


def status_schema() -> Dict[str, Any]:
    """Live deployment state, config-shaped (GET handler / `serve
    status`)."""
    from ray_tpu import serve

    out = {}
    for name, info in serve.status().items():
        out[name] = {
            "status": info.get("status"),
            "message": info.get("message", ""),
            "num_replicas": info.get("num_replicas"),
            "version": info.get("version"),
        }
    return out
