"""Streaming responses: incremental chunks from a deployment.

A deployment method that returns a *generator* streams automatically: the
replica pumps chunks through a bounded actor-backed queue
(`replica._start_stream`), the HTTP proxy renders them as
server-sent-events chunks, and Python callers unwrap with
``serve.iter_stream``. Reference role: ASGI StreamingResponse through the
uvicorn proxy (`serve/_private/http_proxy.py:425`); the transport here is
the object-plane queue, the contract — incremental chunks over one
request, first token before the last is computed — is the same.
"""

from __future__ import annotations

from typing import Any, Iterator

STREAM_KEY = "__ray_tpu_stream__"
STREAM_END_KEY = "__ray_tpu_stream_end__"


def is_stream(result: Any) -> bool:
    return isinstance(result, dict) and STREAM_KEY in result


def iter_stream(result: Any, timeout: float = 60.0) -> Iterator[Any]:
    """Iterate a streaming deployment response (pass-through for
    non-streaming results: yields the single value). The backing queue
    actor is torn down when the stream ends, errors, or the consumer
    abandons the iterator — the replica-side pump then unblocks on its
    put timeout and closes the generator."""
    if not is_stream(result):
        yield result
        return
    queue = result[STREAM_KEY]
    try:
        while True:
            item = queue.get(timeout=timeout)
            if isinstance(item, dict) and item.get(STREAM_END_KEY):
                error = item.get("error")
                if error:
                    raise RuntimeError(
                        f"stream failed in deployment: {error}")
                return
            yield item
    finally:
        try:
            queue.shutdown()
        except Exception:
            pass


async def aiter_stream(result: Any, timeout: float = 60.0):
    """Async counterpart of :func:`iter_stream` for event-loop consumers
    (the asyncio HTTP proxy): each chunk is awaited through the queue
    actor's ObjectRef, so a slow generator never blocks the loop other
    requests are running on. Same contract — pass-through for
    non-streaming results, queue torn down on exit."""
    if not is_stream(result):
        yield result
        return
    queue = result[STREAM_KEY]
    try:
        while True:
            ok, item = await queue.get_async(timeout)
            if not ok:
                raise TimeoutError(
                    f"no stream chunk within {timeout}s")
            if isinstance(item, dict) and item.get(STREAM_END_KEY):
                error = item.get("error")
                if error:
                    raise RuntimeError(
                        f"stream failed in deployment: {error}")
                return
            yield item
    finally:
        # Non-blocking teardown: the kill is a synchronous control
        # RPC, and this finally runs ON the proxy's event loop — the
        # blocking form would stall every other in-flight request
        # until the round-trip finished.
        try:
            queue.shutdown(block=False)
        except Exception:
            pass
