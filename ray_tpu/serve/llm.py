"""Continuous-batching LLM engine for TPU serving.

No reference equivalent (the reference serves arbitrary Python callables);
this is the TPU-specific serving layer SURVEY.md §7 step 8 calls for:
compiled-XLA replicas with continuous batching. Design constraints come
from XLA's compilation model — every device program must have static
shapes — so:

- The KV cache is slot-based: `max_batch_size` sequence slots, each with a
  `max_seq_len` KV region (`models.llama.init_kv_cache`). Admission =
  prefill into a free slot; retirement frees the slot. The decode step is
  ONE fixed-shape jit program over all slots regardless of occupancy.
- Prefill lengths are bucketed to powers of two, so at most log2(max_seq)
  prefill programs ever compile.
- Sampling (greedy / temperature / top-k) runs on device; one token per
  slot per step streams back to waiting callers.

The engine is thread-safe: callers enqueue requests and block on their
completion; a background loop interleaves admission and decode — the
continuous-batching scheduler (admission between decode steps, no
generation stall).
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import (
    LlamaConfig,
    forward_with_cache,
    init_kv_cache,
)


# lax.top_k needs a static k: per-slot top_k values are clamped to this.
_TOP_K_MAX = 64


@dataclasses.dataclass
class SamplingParams:
    max_tokens: int = 64
    temperature: float = 0.0  # 0 → greedy
    top_k: int = 0            # 0 = full softmax; clamped to _TOP_K_MAX
    stop_token_ids: tuple = ()


@dataclasses.dataclass
class _Request:
    request_id: int
    prompt: List[int]
    params: SamplingParams
    out_queue: "queue.Queue"
    tokens: List[int] = dataclasses.field(default_factory=list)
    slot: int = -1
    t_arrival: float = 0.0
    t_first_token: Optional[float] = None


class LLMEngine:
    def __init__(self, cfg: LlamaConfig, params, *,
                 max_batch_size: int = 8, max_seq_len: Optional[int] = None,
                 decode_steps: int = 1, seed: int = 0):
        self.cfg = cfg
        self.params = params
        self.n_slots = max_batch_size
        # Tokens generated per decode dispatch (in-program scan).
        # >1 trades admission granularity (a new request waits for the
        # current block) for K-fold fewer dispatches.
        self.decode_steps = max(1, int(decode_steps))
        self.max_seq = max_seq_len or cfg.max_seq_len
        self.cache = init_kv_cache(cfg, self.n_slots, self.max_seq)
        self._rng = jax.random.PRNGKey(seed)

        # Per-slot host state.
        self._free_slots = list(range(self.n_slots))
        self._slot_req: Dict[int, _Request] = {}
        self._lengths = np.zeros(self.n_slots, np.int32)  # tokens in cache
        self._last_token = np.zeros(self.n_slots, np.int32)
        self._active = np.zeros(self.n_slots, bool)

        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._req_counter = itertools.count()
        self._lock = threading.Lock()
        self._running = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Pipelined decode: the in-flight block's device token array (its
        # host fetch happens while the next block computes), plus
        # device-side last-token/length carries valid while no admission
        # has touched the host copies.
        self._pending_toks = None
        self._dev_last = None
        self._dev_lengths = None

        # Compiled programs. Prefill is per-slot (batch 1, bucketed T);
        # decode covers all slots at T=1. Params are explicit arguments —
        # closing over them would bake the full weight set into every
        # compiled program as constants (one 2.5GB copy per prefill
        # bucket), exploding compile time and HBM.
        from ray_tpu._private.compile_cache import enable_persistent_cache

        enable_persistent_cache()  # re-deploys load, not recompile
        # Pin the small-argument shardings at the jit boundary: the
        # serving loop alternates host-built arrays (admission refreshes
        # temps/last) with device carries (pipelined decode outputs),
        # whose differing shardings otherwise key DISTINCT compiled
        # variants — round 3's cold wave recompiled prefill/decode many
        # times over (19 prefill + 6 decode cache entries for what
        # should be 11 + 1 programs), serializing the first ~70 s of
        # traffic behind XLA.
        s1 = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        # Canonicalize params too: weights initialized onto a training
        # mesh carry a NamedSharding whose axes leak into every jit
        # OUTPUT's aval type; warmup (plain inputs) and the serving loop
        # (mesh-typed carries) then trace as DIFFERENT signatures and
        # each program compiles twice. One engine = one device = one
        # sharding vocabulary. (No-op copy when already single-device.)
        self.params = jax.device_put(self.params, s1)
        self.cache = jax.device_put(self.cache, s1)
        self._rng = jax.device_put(self._rng, s1)
        self._decode = jax.jit(
            self._decode_impl, donate_argnums=(1,),
            in_shardings=(None, s1, s1, s1, s1, s1, s1),
            out_shardings=(s1, s1, s1, s1, s1))
        self._prefill = jax.jit(
            self._prefill_impl, donate_argnums=(1,),
            static_argnums=(5,),  # t — positional: pjit rejects kwargs
            in_shardings=(None, s1, s1, s1, s1),  # with in_shardings
            out_shardings=(s1, s1))
        # First-token sampling for an admission wave — FIXED shape
        # [n_slots, vocab] (padded) so it is ONE program compiled at
        # warmup; the old eager stack/categorical/argmax chain compiled
        # a fresh variant per distinct admitted-count, which on a
        # high-compile-latency platform serialized the first real
        # admission wave for tens of seconds.
        self._sample_admitted = jax.jit(
            self._sample_admitted_impl,
            in_shardings=(s1, s1, s1), out_shardings=(s1, s1))
        # AOT-compiled executables, filled by warmup(): the bucket
        # ladder compiles CONCURRENTLY (XLA releases the GIL; compiles
        # parallelize across cores) and the serving path then calls the
        # compiled objects directly — no jit-cache recompile behind the
        # first request. Absent entries fall back to the jit functions.
        self._prefill_exec: Dict[int, Any] = {}
        self._decode_exec = None
        self._sample_exec = None

    def warmup(self, max_prompt_len: Optional[int] = None,
               concurrent: bool = True) -> float:
        """Compile every program the serving path needs BEFORE the first
        request (deploy-time AOT): prefill at each power-of-two bucket up
        to ``max_prompt_len`` (default max_seq) plus the decode body and
        the admission sampler. Must run before :meth:`start`.

        The bucket ladder compiles CONCURRENTLY: each program is
        lowered and compiled on a thread pool (XLA compilation drops the
        GIL and parallelizes across host cores), so a first-ever deploy
        pays roughly the LONGEST compile, not the sum of the ladder.
        The compiled executables then serve traffic directly (and each
        runs once here to validate + touch device memory). Returns the
        wall seconds spent — with the persistent compilation cache this
        is seconds on the first deploy of a config and near-zero
        afterwards. ``concurrent=False`` keeps the old sequential
        jit-call path (debugging escape hatch)."""
        assert self._thread is None or not self._thread.is_alive(), \
            "warmup() must run before the engine loop starts"
        t0 = time.perf_counter()
        limit = min(max_prompt_len or self.max_seq, self.max_seq)
        buckets, b = [], 1
        while b < limit:
            buckets.append(b)
            b *= 2
        buckets.append(min(b, self.max_seq))  # _admit's cap bucket
        buckets = sorted(set(buckets))
        if concurrent:
            try:
                self._compile_ladder_concurrent(buckets)
            except Exception:
                # AOT path unavailable (jax version / backend quirk):
                # the sequential jit pass below still compiles it all.
                self._prefill_exec.clear()
                self._decode_exec = self._sample_exec = None
        last = None
        for bucket in buckets:
            tokens = jnp.zeros((1, bucket), jnp.int32)
            self.cache, last = self._run_prefill(
                tokens, jnp.int32(0), jnp.int32(1), bucket)
        # Admission-wave sampling program (and its eager stack feeder).
        stacked = jnp.stack([last] * self.n_slots)
        _firsts, self._rng = self._run_sample(
            stacked, jnp.asarray(np.zeros(self.n_slots, np.float32)))
        (self.cache, toks, _last, _lens, self._rng) = self._run_decode(
            jnp.zeros(self.n_slots, jnp.int32),
            jnp.zeros(self.n_slots, jnp.int32),
            jnp.zeros(self.n_slots, jnp.float32),
            jnp.zeros(self.n_slots, jnp.int32))
        np.asarray(toks)  # host fetch = the only reliable barrier
        # Warmup wrote garbage KV into slot 0; lengths stay 0 so every
        # slot still reads as empty when serving starts.
        return time.perf_counter() - t0

    def _compile_ladder_concurrent(self, buckets) -> None:
        """AOT-compile every serving program on a thread pool."""
        import os
        from concurrent.futures import ThreadPoolExecutor

        import jax.numpy as _jnp

        def aval(shape, dtype=_jnp.int32):
            return jax.ShapeDtypeStruct(shape, dtype)

        params_avals = jax.tree_util.tree_map(
            lambda x: aval(x.shape, x.dtype), self.params)
        cache_avals = jax.tree_util.tree_map(
            lambda x: aval(x.shape, x.dtype), self.cache)
        rng_aval = aval(self._rng.shape, self._rng.dtype)
        n = self.n_slots

        def compile_prefill(bucket):
            lowered = self._prefill.lower(
                params_avals, cache_avals, aval((1, bucket)),
                aval(()), aval(()), bucket)
            return bucket, lowered.compile()

        def compile_decode():
            lowered = self._decode.lower(
                params_avals, cache_avals, aval((n,)), aval((n,)),
                aval((n,), _jnp.float32), aval((n,)), rng_aval)
            return "decode", lowered.compile()

        def compile_sample():
            lowered = self._sample_admitted.lower(
                aval((n, self.cfg.vocab_size), _jnp.float32),
                aval((n,), _jnp.float32), rng_aval)
            return "sample", lowered.compile()

        jobs = [lambda b=b: compile_prefill(b) for b in buckets]
        jobs += [compile_decode, compile_sample]
        workers = min(len(jobs), max(2, os.cpu_count() or 4))
        with ThreadPoolExecutor(max_workers=workers,
                                thread_name_prefix="aot-compile") as pool:
            for key, compiled in pool.map(lambda fn: fn(), jobs):
                if key == "decode":
                    self._decode_exec = compiled
                elif key == "sample":
                    self._sample_exec = compiled
                else:
                    self._prefill_exec[key] = compiled

    # -- compiled-or-jit call shims --------------------------------------
    #
    # Fallback contract: the AOT executables can only legitimately fail
    # at ARGUMENT VALIDATION (aval/sharding drift between warmup and the
    # serving loop) — which happens before dispatch, so no donated
    # buffer has been consumed and the jit retry with self.cache is
    # safe. A failure raised AFTER dispatch (device OOM etc.) may have
    # donated the cache, making a retry unsafe — so it is logged and
    # RE-RAISED, never silently converted into a mid-serving recompile.

    @staticmethod
    def _exec_fallback_ok(e: Exception) -> bool:
        return isinstance(e, (TypeError, ValueError))  # pre-dispatch checks

    def _run_prefill(self, tokens, slot, length, bucket):
        compiled = self._prefill_exec.get(bucket)
        if compiled is not None:
            try:
                return compiled(self.params, self.cache, tokens, slot,
                                length)
            except Exception as e:
                logging.getLogger(__name__).warning(
                    "AOT prefill[%d] failed (%s); %s", bucket, e,
                    "re-jitting" if self._exec_fallback_ok(e)
                    else "re-raising")
                self._prefill_exec.pop(bucket, None)
                if not self._exec_fallback_ok(e):
                    raise
        return self._prefill(self.params, self.cache, tokens, slot,
                             length, bucket)

    def _run_decode(self, last, lengths, temps, topks):
        if self._decode_exec is not None:
            try:
                return self._decode_exec(self.params, self.cache, last,
                                         lengths, temps, topks, self._rng)
            except Exception as e:
                logging.getLogger(__name__).warning(
                    "AOT decode failed (%s); %s", e,
                    "re-jitting" if self._exec_fallback_ok(e)
                    else "re-raising")
                self._decode_exec = None
                if not self._exec_fallback_ok(e):
                    raise
        return self._decode(self.params, self.cache, last, lengths,
                            temps, topks, self._rng)

    def _run_sample(self, logits, temps):
        if self._sample_exec is not None:
            try:
                return self._sample_exec(logits, temps, self._rng)
            except Exception as e:
                logging.getLogger(__name__).warning(
                    "AOT sampler failed (%s); %s", e,
                    "re-jitting" if self._exec_fallback_ok(e)
                    else "re-raising")
                self._sample_exec = None
                if not self._exec_fallback_ok(e):
                    raise
        return self._sample_admitted(logits, temps, self._rng)

    # -- compiled bodies -------------------------------------------------

    def _sample_admitted_impl(self, logits, temps, rng):
        """logits [n_slots, vocab], temps [n_slots] → first token per
        row (greedy at temp 0). Rows beyond the admitted count are
        padding and ignored host-side."""
        rng, sub = jax.random.split(rng)
        sampled = jax.random.categorical(
            sub, logits / jnp.maximum(temps, 1e-6)[:, None])
        firsts = jnp.where(temps > 0, sampled, logits.argmax(-1))
        return firsts.astype(jnp.int32), rng

    def _prefill_impl(self, params, cache, tokens, slot, length, t):
        """tokens: [1, t] padded prompt; writes KV for one slot, returns
        logits at the last real position [vocab]."""
        slot_cache = {"k": lax_slice_slot(cache["k"], slot),
                      "v": lax_slice_slot(cache["v"], slot)}
        logits, new_slot_cache = forward_with_cache(
            params, tokens, self.cfg, slot_cache,
            jnp.zeros((1,), jnp.int32))
        cache = {
            "k": lax_write_slot(cache["k"], new_slot_cache["k"], slot),
            "v": lax_write_slot(cache["v"], new_slot_cache["v"], slot),
        }
        last = logits[0, length - 1]
        return cache, last

    def _decode_impl(self, params, cache, last_tokens, lengths, temps,
                     topks, rng):
        """`decode_steps` tokens for every slot per dispatch, via an
        in-program `lax.scan` (vLLM-style multi-step decoding): one
        device execution amortizes the per-dispatch overhead over K
        tokens — the lever that matters both for high-latency runtimes
        and for launch overhead on real pods. Returns tokens
        [slots, K]."""

        def step(carry, _):
            cache, tokens, lengths, rng = carry
            # Clamp for retired slots that keep computing until their
            # slot is re-admitted (pipelined decode fetches lag a block):
            # their writes wrap at the last position instead of OOB.
            lengths = jnp.minimum(lengths, self.max_seq - 2)
            logits, cache = forward_with_cache(
                params, tokens[:, None], self.cfg, cache, lengths)
            logits = logits[:, 0, :].astype(jnp.float32)  # [slots, vocab]
            greedy = logits.argmax(-1)
            # Per-slot top-k truncation: threshold at each slot's k-th
            # largest logit (k clamped to _TOP_K_MAX — lax.top_k needs a
            # static k, so one sorted prefix serves every slot).
            kth_vals = jax.lax.top_k(logits, _TOP_K_MAX)[0]
            idx = jnp.clip(topks - 1, 0, _TOP_K_MAX - 1)
            thresh = jnp.take_along_axis(kth_vals, idx[:, None], axis=1)
            truncated = jnp.where(logits < thresh, -jnp.inf, logits)
            sample_logits = jnp.where((topks > 0)[:, None], truncated,
                                      logits)
            rng, sub = jax.random.split(rng)
            sampled = jax.random.categorical(
                sub, sample_logits / jnp.maximum(temps, 1e-6)[:, None])
            next_tokens = jnp.where(temps > 0, sampled,
                                    greedy).astype(jnp.int32)
            return (cache, next_tokens, lengths + 1, rng), next_tokens

        (cache, last, lengths, rng), toks = jax.lax.scan(
            step, (cache, last_tokens, lengths, rng), None,
            length=self.decode_steps)
        # Device-side carries (last/lengths) let the NEXT decode dispatch
        # before this block's tokens reach the host (pipelined decode).
        return cache, toks.T, last, lengths, rng  # toks: [slots, K]

    # -- public API ------------------------------------------------------

    def start(self):
        # Under the lock: concurrent generate() callers must never spawn
        # two engine loops — dueling loops double-assign slots and feed
        # the donated cache twice, silently losing requests.
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._running.set()
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="llm-engine")
                self._thread.start()

    def stop(self):
        self._running.clear()
        # Let the loop leave its current device fetch before interpreter
        # teardown (a daemon thread cancelled mid-fetch can abort the
        # process with pthread noise).
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=10)

    def generate(self, prompt_ids: List[int],
                 params: Optional[SamplingParams] = None,
                 stream: bool = False):
        """Blocking generate (or an iterator of tokens with stream=True)."""
        req = _Request(
            request_id=next(self._req_counter), prompt=list(prompt_ids),
            params=params or SamplingParams(), out_queue=queue.Queue(),
            t_arrival=time.perf_counter())
        self._queue.put(req)
        self.start()

        def token_iter():
            while True:
                item = req.out_queue.get()
                if item is None:
                    return
                yield item

        if stream:
            return token_iter()
        return list(token_iter())

    def metrics(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "active_slots": int(self._active.sum()),
                "free_slots": len(self._free_slots),
                "queued": self._queue.qsize(),
            }

    # -- engine loop -----------------------------------------------------

    def _loop(self):
        self._temps_arr = np.zeros(self.n_slots, np.float32)
        self._topks_arr = np.zeros(self.n_slots, np.int32)
        while self._running.is_set():
            admitted = self._admit()
            if not self._active.any():
                # Drop any in-flight block for fully-retired slots.
                self._flush_pending()
                if not admitted:
                    try:
                        req = self._queue.get(timeout=0.05)
                        self._queue.put(req)
                    except queue.Empty:
                        continue
                continue
            self._decode_once()

    def _admit(self) -> bool:
        if self._queue.empty() or not self._free_slots:
            return False
        # Admission invalidates the device carries and needs free slots:
        # drain the in-flight decode block first.
        self._flush_pending()
        staged = []  # (req, slot, t_real, last_logits_ref)
        while self._free_slots:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            prompt = req.prompt[-(self.max_seq - 1):]
            t_real = len(prompt)
            bucket = 1
            while bucket < t_real:
                bucket *= 2
            bucket = min(bucket, self.max_seq)
            slot = self._free_slots.pop()
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :t_real] = prompt
            self.cache, last_logits = self._run_prefill(
                jnp.asarray(tokens), jnp.int32(slot), jnp.int32(t_real),
                bucket)
            staged.append((req, slot, t_real, last_logits))
        if not staged:
            return False
        # ONE device-side sampling + ONE host sync for the whole wave:
        # per-admit argmax fetches would serialize a tunnel round-trip
        # per request (the dominant pre-first-token cost). Padded to
        # n_slots so the program (and the eager stack feeding it) has
        # one fixed shape, compiled once at warmup.
        pad = self.n_slots - len(staged)
        logits = jnp.stack([s[3] for s in staged]
                           + [staged[0][3]] * pad)  # [n_slots, vocab]
        temps_np = np.zeros(self.n_slots, np.float32)
        for i, s in enumerate(staged):
            temps_np[i] = s[0].params.temperature
        firsts_dev, self._rng = self._run_sample(
            logits, jnp.asarray(temps_np))
        firsts = np.asarray(firsts_dev)[:len(staged)]
        now = time.perf_counter()
        for (req, slot, t_real, _), first in zip(staged, firsts):
            first = int(first)
            req.t_first_token = now
            req.tokens.append(first)
            req.out_queue.put(first)
            with self._lock:
                req.slot = slot
                self._slot_req[slot] = req
                self._lengths[slot] = t_real
                self._last_token[slot] = first
                self._active[slot] = True
                self._temps_arr[slot] = req.params.temperature
                self._topks_arr[slot] = max(0, min(req.params.top_k,
                                                   _TOP_K_MAX))
            if self._finished(req, first):
                self._retire(slot)
        # Host state changed: rebuild device carries on the next decode.
        self._dev_last = self._dev_lengths = None
        return True

    def _decode_once(self):
        # The fed token occupies absolute position `lengths` (prompt is
        # 0..len-1, first generated token sits at len, etc.). Dispatch
        # block N+1 from the device-side carries, THEN fetch block N —
        # the host round-trip overlaps the next block's compute.
        last = self._dev_last if self._dev_last is not None \
            else jnp.asarray(self._last_token)
        lengths = self._dev_lengths if self._dev_lengths is not None \
            else jnp.asarray(self._lengths)
        (self.cache, next_tokens, self._dev_last, self._dev_lengths,
         self._rng) = self._run_decode(
            last, lengths,
            jnp.asarray(self._temps_arr),
            jnp.asarray(self._topks_arr))
        prev, self._pending_toks = self._pending_toks, next_tokens
        if prev is not None:
            self._consume_block(np.asarray(prev))

    def _flush_pending(self):
        prev, self._pending_toks = self._pending_toks, None
        if prev is not None:
            self._consume_block(np.asarray(prev))

    def _consume_block(self, next_host):
        with self._lock:
            for slot in np.nonzero(self._active)[0]:
                req = self._slot_req[slot]
                # Walk this slot's K-token block; once the request
                # finishes mid-block the remaining tokens are padding
                # compute and are discarded.
                for k in range(next_host.shape[1]):
                    tok = int(next_host[slot, k])
                    req.tokens.append(tok)
                    req.out_queue.put(tok)  # raylint: disable=R2 -- per-request stream queues are unbounded, so put() cannot block; token delivery and slot-state mutation must share one hold or a racing admit could reuse the slot mid-block
                    self._lengths[slot] += 1
                    self._last_token[slot] = tok
                    if self._finished(req, tok) or \
                            self._lengths[slot] >= self.max_seq - 1:
                        self._retire(slot)  # raylint: disable=R2 -- _retire only pushes the unbounded-queue end-of-stream sentinel and frees the slot; both must be atomic with the walk above
                        break

    def _finished(self, req: _Request, token: int) -> bool:
        if token in req.params.stop_token_ids:
            return True
        return len(req.tokens) >= req.params.max_tokens

    def _retire(self, slot: int):
        req = self._slot_req.pop(slot, None)
        if req is not None:
            req.out_queue.put(None)
        self._active[slot] = False
        self._lengths[slot] = 0
        self._free_slots.append(slot)


def lax_slice_slot(cache, slot):
    """cache: [L, slots, S, H, D] → [L, 1, S, H, D] at `slot`."""
    return jax.lax.dynamic_slice_in_dim(cache, slot, 1, axis=1)


def lax_write_slot(cache, slot_cache, slot):
    return jax.lax.dynamic_update_slice_in_dim(cache, slot_cache, slot,
                                               axis=1)


# -- Serve integration ------------------------------------------------------


class LLMDeployment:
    """Deployment-ready wrapper: `serve.deployment(LLMDeployment).bind(...)`.

    Each replica owns one engine (one model copy + cache in its chip's
    HBM); serve's router spreads requests over replicas.
    """

    def __init__(self, cfg: LlamaConfig, params_fn: Callable[[], Any],
                 max_batch_size: int = 8,
                 max_seq_len: Optional[int] = None,
                 decode_steps: int = 1,
                 warmup: bool = True,
                 warmup_max_prompt_len: Optional[int] = None):
        params = params_fn() if callable(params_fn) else params_fn
        self.engine = LLMEngine(cfg, params, max_batch_size=max_batch_size,
                                max_seq_len=max_seq_len,
                                decode_steps=decode_steps)
        # Deploy-time AOT: compile prefill buckets + decode BEFORE the
        # replica takes traffic, so the first request's TTFT is serving
        # latency, not XLA compile (round 3 measured 14 s cold TTFT).
        # With the persistent compilation cache, re-deploys of the same
        # config warm up in well under a second.
        self.warmup_s = self.engine.warmup(warmup_max_prompt_len) \
            if warmup else 0.0
        self.engine.start()

    def __call__(self, request: Dict[str, Any]):
        t0 = time.perf_counter()
        params = SamplingParams(
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            stop_token_ids=tuple(request.get("stop_token_ids", ())))
        if request.get("stream"):
            # Generator return → the replica streams it chunk-by-chunk
            # (tokens reach the client during decode, not after).
            def token_stream():
                for i, token in enumerate(self.engine.generate(
                        request["prompt_ids"], params, stream=True)):
                    yield {"token": int(token), "index": i}
            return token_stream()
        tokens = self.engine.generate(request["prompt_ids"], params)
        return {"tokens": tokens,
                "latency_s": time.perf_counter() - t0}

    def check_health(self):
        assert self.engine._thread is None or \
            self.engine._thread.is_alive() or \
            not self.engine._running.is_set()
