"""Continuous-batching LLM engine for TPU serving.

No reference equivalent (the reference serves arbitrary Python callables);
this is the TPU-specific serving layer SURVEY.md §7 step 8 calls for:
compiled-XLA replicas with continuous batching. Design constraints come
from XLA's compilation model — every device program must have static
shapes — so:

- The KV cache is slot-based: `max_batch_size` sequence slots, each with a
  `max_seq_len` KV region (`models.llama.init_kv_cache`). Admission =
  prefill into a free slot; retirement frees the slot. The decode step is
  ONE fixed-shape jit program over all slots regardless of occupancy.
- Prefill lengths are bucketed to powers of two, so at most log2(max_seq)
  prefill programs ever compile.
- Sampling (greedy / temperature / top-k) runs on device; one token per
  slot per step streams back to waiting callers.

The engine is thread-safe: callers enqueue requests and block on their
completion; a background loop interleaves admission and decode — the
continuous-batching scheduler (admission between decode steps, no
generation stall).

Prefix/KV cache (PR 16): full ``llm_kv_block_tokens``-sized chunks of
every admitted prompt are hash-chained into the
:class:`~ray_tpu._private.kv_cache.PrefixCache` decision core, with the
block KV payloads read back off-device into a host store. A later
request sharing the prompt head copies the matched blocks straight into
its slot's KV region and prefills ONLY the tail at the tail's bucket —
the shared-head prefill compute (the dominant pre-first-token cost on a
chatbot workload) is skipped entirely. Evicted-but-warm blocks persist
as shm-plane objects (spill-backed, tenant-charged), so a hit on
another replica restores KV bytes via the object plane instead of
recomputing. Chain keys are seeded with the model identity, so
multi-model replicas can never cross-hit.

Multi-model multiplexing: a replica holds N weight variants
(``LLMDeployment(models={...})``); the compiled programs take params as
ARGUMENTS, so a swap is one ``device_put`` — no recompile. Requests
carry a model tag and a priority class; interactive outranks batch at
the slot shed point.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import logging
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu._private import critical_path
from ray_tpu._private import perf_stats
from ray_tpu._private.config import ray_config
from ray_tpu._private.kv_cache import PrefixCache, chain_keys
from ray_tpu.models.llama import (
    LlamaConfig,
    forward_with_cache,
    init_kv_cache,
)


class PromptTooLongError(ValueError):
    """Prompt exceeds the engine's slot KV region (``max_seq_len - 1``
    tokens: one position must remain for generation). Raised at
    ``generate()`` — the old behavior silently truncated the head,
    which corrupts answers instead of failing loudly."""

    def __init__(self, n_tokens: int, cap: int):
        super().__init__(
            f"prompt of {n_tokens} tokens exceeds the engine's "
            f"{cap}-token cap (max_seq_len {cap + 1}); truncate or "
            f"shard client-side")
        self.n_tokens = n_tokens
        self.cap = cap


class UnknownModelError(ValueError):
    """X-Model names a variant this deployment does not hold."""

    def __init__(self, model: str, known):
        super().__init__(
            f"unknown model {model!r}; this replica serves {known}")
        self.model = model
        self.known = list(known)


class ModelSwapDeadlineError(RuntimeError):
    """A cold-start weight swap blew the ``llm_model_swap_deadline_s``
    SLA. The loaded weights STAY cached (and published to the shm
    plane), so an immediate retry is warm — the deadline is a latency
    contract, not a capability failure."""

    def __init__(self, model: str, took_s: float, deadline_s: float):
        super().__init__(
            f"swap to model {model!r} took {took_s:.2f}s, over the "
            f"{deadline_s:.2f}s cold-start deadline (retry is warm)")
        self.model = model
        self.took_s = took_s
        self.deadline_s = deadline_s


# lax.top_k needs a static k: per-slot top_k values are clamped to this.
_TOP_K_MAX = 64


@dataclasses.dataclass
class SamplingParams:
    max_tokens: int = 64
    temperature: float = 0.0  # 0 → greedy
    top_k: int = 0            # 0 = full softmax; clamped to _TOP_K_MAX
    stop_token_ids: tuple = ()


@dataclasses.dataclass
class _Request:
    request_id: int
    prompt: List[int]
    params: SamplingParams
    out_queue: "queue.Queue"
    tokens: List[int] = dataclasses.field(default_factory=list)
    slot: int = -1
    t_arrival: float = 0.0
    t_first_token: Optional[float] = None
    model: Optional[str] = None
    priority: int = 1     # 0 interactive > 1 normal > 2 batch
    job: str = "default"
    # Critical-path attribution: the HTTP request's trace id (stamped
    # at generate() from the calling task's ambient trace, "" outside
    # any trace) plus the per-request stage marks the engine loop sets
    # while the request crosses admit → kv-lookup → prefill → sample.
    trace_id: str = ""
    t_kv_done: float = 0.0
    t_prefill_done: float = 0.0


class LLMEngine:
    def __init__(self, cfg: LlamaConfig, params, *,
                 max_batch_size: int = 8, max_seq_len: Optional[int] = None,
                 decode_steps: int = 1, seed: int = 0,
                 model: str = "default"):
        self.cfg = cfg
        self.params = params
        self.model = model
        self.n_slots = max_batch_size
        # Tokens generated per decode dispatch (in-program scan).
        # >1 trades admission granularity (a new request waits for the
        # current block) for K-fold fewer dispatches.
        self.decode_steps = max(1, int(decode_steps))
        self.max_seq = max_seq_len or cfg.max_seq_len
        self.cache = init_kv_cache(cfg, self.n_slots, self.max_seq)
        self._rng = jax.random.PRNGKey(seed)

        # Per-slot host state.
        self._free_slots = list(range(self.n_slots))
        self._slot_req: Dict[int, _Request] = {}
        self._lengths = np.zeros(self.n_slots, np.int32)  # tokens in cache
        self._last_token = np.zeros(self.n_slots, np.int32)
        self._active = np.zeros(self.n_slots, bool)

        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._req_counter = itertools.count()
        self._lock = threading.Lock()
        self._running = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Pipelined decode: the in-flight block's device token array (its
        # host fetch happens while the next block computes), plus
        # device-side last-token/length carries valid while no admission
        # has touched the host copies.
        self._pending_toks = None
        self._dev_last = None
        self._dev_lengths = None

        # Compiled programs. Prefill is per-slot (batch 1, bucketed T);
        # decode covers all slots at T=1. Params are explicit arguments —
        # closing over them would bake the full weight set into every
        # compiled program as constants (one 2.5GB copy per prefill
        # bucket), exploding compile time and HBM.
        from ray_tpu._private.compile_cache import enable_persistent_cache

        enable_persistent_cache()  # re-deploys load, not recompile
        # Pin the small-argument shardings at the jit boundary: the
        # serving loop alternates host-built arrays (admission refreshes
        # temps/last) with device carries (pipelined decode outputs),
        # whose differing shardings otherwise key DISTINCT compiled
        # variants — round 3's cold wave recompiled prefill/decode many
        # times over (19 prefill + 6 decode cache entries for what
        # should be 11 + 1 programs), serializing the first ~70 s of
        # traffic behind XLA.
        s1 = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        # Canonicalize params too: weights initialized onto a training
        # mesh carry a NamedSharding whose axes leak into every jit
        # OUTPUT's aval type; warmup (plain inputs) and the serving loop
        # (mesh-typed carries) then trace as DIFFERENT signatures and
        # each program compiles twice. One engine = one device = one
        # sharding vocabulary. (No-op copy when already single-device.)
        self.params = jax.device_put(self.params, s1)
        self.cache = jax.device_put(self.cache, s1)
        self._rng = jax.device_put(self._rng, s1)
        self._decode = jax.jit(
            self._decode_impl, donate_argnums=(1,),
            in_shardings=(None, s1, s1, s1, s1, s1, s1),
            out_shardings=(s1, s1, s1, s1, s1))
        self._prefill = jax.jit(
            self._prefill_impl, donate_argnums=(1,),
            static_argnums=(6,),  # t — positional: pjit rejects kwargs
            in_shardings=(None, s1, s1, s1, s1, s1),  # with in_shardings
            out_shardings=(s1, s1))
        # First-token sampling for an admission wave — FIXED shape
        # [n_slots, vocab] (padded) so it is ONE program compiled at
        # warmup; the old eager stack/categorical/argmax chain compiled
        # a fresh variant per distinct admitted-count, which on a
        # high-compile-latency platform serialized the first real
        # admission wave for tens of seconds.
        self._sample_admitted = jax.jit(
            self._sample_admitted_impl,
            in_shardings=(s1, s1, s1), out_shardings=(s1, s1))
        # AOT-compiled executables, filled by warmup(): the bucket
        # ladder compiles CONCURRENTLY (XLA releases the GIL; compiles
        # parallelize across cores) and the serving path then calls the
        # compiled objects directly — no jit-cache recompile behind the
        # first request. Absent entries fall back to the jit functions.
        self._prefill_exec: Dict[int, Any] = {}
        self._decode_exec = None
        self._sample_exec = None
        self._s1 = s1

        # Prefix/KV cache: the PrefixCache decision core decides which
        # blocks exist / are pinned / get evicted; _kv_store holds the
        # actual host-side KV payloads keyed by block generation id
        # (evicted payloads fall to the shm-plane warm tier).
        self.block_tokens = max(1, int(ray_config.llm_kv_block_tokens))
        self.prefix_cache: Optional[PrefixCache] = None
        if ray_config.llm_prefix_cache and self.block_tokens < self.max_seq:
            self.prefix_cache = PrefixCache(
                ray_config.llm_prefix_cache_bytes, self.block_tokens)
        self._kv_store: Dict[int, tuple] = {}
        k = self.cache["k"]
        per_token = 2 * k.size * k.dtype.itemsize // (k.shape[1] * k.shape[2])
        self._block_nbytes = per_token * self.block_tokens
        self._chain_seed = self._seed_for(model)
        self._c_shm_offloads = perf_stats.counter("llm_kv_shm_offloads")
        self._c_shm_restores = perf_stats.counter("llm_kv_shm_restores")
        # Per-block KV copy-in/read-back programs (fixed [L, B, Hkv, D]
        # block shape, traced slot/offset → exactly one compiled
        # program each, touched at warmup).
        self._read_block_j = jax.jit(
            self._read_block_impl,
            in_shardings=(s1, s1, s1), out_shardings=(s1, s1))
        self._write_block_j = jax.jit(
            self._write_block_impl, donate_argnums=(0,),
            in_shardings=(s1, s1, s1, s1, s1), out_shardings=s1)

    def _seed_for(self, model: str) -> str:
        """Chain-key seed: model identity + the KV-shape fingerprint.
        Two chains share keys only when the cached bytes are
        interchangeable — same model, same layout — which is what makes
        the shm tier safe to share across replicas."""
        c = self.cfg
        return (f"{model}|{c.n_layers}x{c.dim}x{c.n_kv_heads}x"
                f"{c.max_seq_len}|{self.block_tokens}")

    def warmup(self, max_prompt_len: Optional[int] = None,
               concurrent: bool = True) -> float:
        """Compile every program the serving path needs BEFORE the first
        request (deploy-time AOT): prefill at each power-of-two bucket up
        to ``max_prompt_len`` (default max_seq) plus the decode body and
        the admission sampler. Must run before :meth:`start`.

        The bucket ladder compiles CONCURRENTLY: each program is
        lowered and compiled on a thread pool (XLA compilation drops the
        GIL and parallelizes across host cores), so a first-ever deploy
        pays roughly the LONGEST compile, not the sum of the ladder.
        The compiled executables then serve traffic directly (and each
        runs once here to validate + touch device memory). Returns the
        wall seconds spent — with the persistent compilation cache this
        is seconds on the first deploy of a config and near-zero
        afterwards. ``concurrent=False`` keeps the old sequential
        jit-call path (debugging escape hatch)."""
        assert self._thread is None or not self._thread.is_alive(), \
            "warmup() must run before the engine loop starts"
        t0 = time.perf_counter()
        limit = min(max_prompt_len or self.max_seq, self.max_seq)
        buckets, b = [], 1
        while b < limit:
            buckets.append(b)
            b *= 2
        buckets.append(min(b, self.max_seq))  # _admit's cap bucket
        buckets = sorted(set(buckets))
        if concurrent:
            try:
                self._compile_ladder_concurrent(buckets)
            except Exception:
                # AOT path unavailable (jax version / backend quirk):
                # the sequential jit pass below still compiles it all.
                self._prefill_exec.clear()
                self._decode_exec = self._sample_exec = None
        last = None
        for bucket in buckets:
            tokens = jnp.zeros((1, bucket), jnp.int32)
            self.cache, last = self._run_prefill(
                tokens, jnp.int32(0), jnp.int32(1), jnp.int32(0), bucket)
        if self.prefix_cache is not None \
                and self.block_tokens <= self.max_seq:
            # Touch the per-block KV copy programs so the first cache
            # hit/readback doesn't pay a mid-serving compile.
            kb, vb = self._read_block_j(
                self.cache, jnp.int32(0), jnp.int32(0))
            self.cache = self._write_block_j(
                self.cache, kb, vb, jnp.int32(0), jnp.int32(0))
        # Admission-wave sampling program (and its eager stack feeder).
        stacked = jnp.stack([last] * self.n_slots)
        _firsts, self._rng = self._run_sample(
            stacked, jnp.asarray(np.zeros(self.n_slots, np.float32)))
        (self.cache, toks, _last, _lens, self._rng) = self._run_decode(
            jnp.zeros(self.n_slots, jnp.int32),
            jnp.zeros(self.n_slots, jnp.int32),
            jnp.zeros(self.n_slots, jnp.float32),
            jnp.zeros(self.n_slots, jnp.int32))
        np.asarray(toks)  # host fetch = the only reliable barrier
        # Warmup wrote garbage KV into slot 0; lengths stay 0 so every
        # slot still reads as empty when serving starts.
        return time.perf_counter() - t0

    def _compile_ladder_concurrent(self, buckets) -> None:
        """AOT-compile every serving program on a thread pool."""
        import os
        from concurrent.futures import ThreadPoolExecutor

        import jax.numpy as _jnp

        def aval(shape, dtype=_jnp.int32):
            return jax.ShapeDtypeStruct(shape, dtype)

        params_avals = jax.tree_util.tree_map(
            lambda x: aval(x.shape, x.dtype), self.params)
        cache_avals = jax.tree_util.tree_map(
            lambda x: aval(x.shape, x.dtype), self.cache)
        rng_aval = aval(self._rng.shape, self._rng.dtype)
        n = self.n_slots

        def compile_prefill(bucket):
            lowered = self._prefill.lower(
                params_avals, cache_avals, aval((1, bucket)),
                aval(()), aval(()), aval(()), bucket)
            return bucket, lowered.compile()

        def compile_decode():
            lowered = self._decode.lower(
                params_avals, cache_avals, aval((n,)), aval((n,)),
                aval((n,), _jnp.float32), aval((n,)), rng_aval)
            return "decode", lowered.compile()

        def compile_sample():
            lowered = self._sample_admitted.lower(
                aval((n, self.cfg.vocab_size), _jnp.float32),
                aval((n,), _jnp.float32), rng_aval)
            return "sample", lowered.compile()

        jobs = [lambda b=b: compile_prefill(b) for b in buckets]
        jobs += [compile_decode, compile_sample]
        workers = min(len(jobs), max(2, os.cpu_count() or 4))
        with ThreadPoolExecutor(max_workers=workers,
                                thread_name_prefix="aot-compile") as pool:
            for key, compiled in pool.map(lambda fn: fn(), jobs):
                if key == "decode":
                    self._decode_exec = compiled
                elif key == "sample":
                    self._sample_exec = compiled
                else:
                    self._prefill_exec[key] = compiled

    # -- compiled-or-jit call shims --------------------------------------
    #
    # Fallback contract: the AOT executables can only legitimately fail
    # at ARGUMENT VALIDATION (aval/sharding drift between warmup and the
    # serving loop) — which happens before dispatch, so no donated
    # buffer has been consumed and the jit retry with self.cache is
    # safe. A failure raised AFTER dispatch (device OOM etc.) may have
    # donated the cache, making a retry unsafe — so it is logged and
    # RE-RAISED, never silently converted into a mid-serving recompile.

    @staticmethod
    def _exec_fallback_ok(e: Exception) -> bool:
        return isinstance(e, (TypeError, ValueError))  # pre-dispatch checks

    def _run_prefill(self, tokens, slot, length, start, bucket):
        compiled = self._prefill_exec.get(bucket)
        if compiled is not None:
            try:
                return compiled(self.params, self.cache, tokens, slot,
                                length, start)
            except Exception as e:
                logging.getLogger(__name__).warning(
                    "AOT prefill[%d] failed (%s); %s", bucket, e,
                    "re-jitting" if self._exec_fallback_ok(e)
                    else "re-raising")
                self._prefill_exec.pop(bucket, None)
                if not self._exec_fallback_ok(e):
                    raise
        return self._prefill(self.params, self.cache, tokens, slot,
                             length, start, bucket)

    def _run_decode(self, last, lengths, temps, topks):
        if self._decode_exec is not None:
            try:
                return self._decode_exec(self.params, self.cache, last,
                                         lengths, temps, topks, self._rng)
            except Exception as e:
                logging.getLogger(__name__).warning(
                    "AOT decode failed (%s); %s", e,
                    "re-jitting" if self._exec_fallback_ok(e)
                    else "re-raising")
                self._decode_exec = None
                if not self._exec_fallback_ok(e):
                    raise
        return self._decode(self.params, self.cache, last, lengths,
                            temps, topks, self._rng)

    def _run_sample(self, logits, temps):
        if self._sample_exec is not None:
            try:
                return self._sample_exec(logits, temps, self._rng)
            except Exception as e:
                logging.getLogger(__name__).warning(
                    "AOT sampler failed (%s); %s", e,
                    "re-jitting" if self._exec_fallback_ok(e)
                    else "re-raising")
                self._sample_exec = None
                if not self._exec_fallback_ok(e):
                    raise
        return self._sample_admitted(logits, temps, self._rng)

    # -- compiled bodies -------------------------------------------------

    def _sample_admitted_impl(self, logits, temps, rng):
        """logits [n_slots, vocab], temps [n_slots] → first token per
        row (greedy at temp 0). Rows beyond the admitted count are
        padding and ignored host-side."""
        rng, sub = jax.random.split(rng)
        sampled = jax.random.categorical(
            sub, logits / jnp.maximum(temps, 1e-6)[:, None])
        firsts = jnp.where(temps > 0, sampled, logits.argmax(-1))
        return firsts.astype(jnp.int32), rng

    def _prefill_impl(self, params, cache, tokens, slot, length, start, t):
        """tokens: [1, t] padded prompt tail; writes KV for one slot
        beginning at absolute position `start` (0 for a full prefill;
        the matched-prefix length when cached KV blocks were copied in
        ahead of this call), returns logits at the last real position
        [vocab]."""
        slot_cache = {"k": lax_slice_slot(cache["k"], slot),
                      "v": lax_slice_slot(cache["v"], slot)}
        logits, new_slot_cache = forward_with_cache(
            params, tokens, self.cfg, slot_cache,
            jnp.full((1,), start, jnp.int32))
        cache = {
            "k": lax_write_slot(cache["k"], new_slot_cache["k"], slot),
            "v": lax_write_slot(cache["v"], new_slot_cache["v"], slot),
        }
        last = logits[0, length - 1]
        return cache, last

    def _read_block_impl(self, cache, slot, start):
        """Read one `block_tokens`-sized KV block out of a slot's region
        at token offset `start` → (k, v) each [L, B, Hkv, D]."""
        bt = self.block_tokens
        out = []
        for name in ("k", "v"):
            x = cache[name]  # [L, slots, S, Hkv, D]
            blk = jax.lax.dynamic_slice(
                x, (0, slot, start, 0, 0),
                (x.shape[0], 1, bt, x.shape[3], x.shape[4]))
            out.append(blk[:, 0])
        return tuple(out)

    def _write_block_impl(self, cache, kb, vb, slot, start):
        """Write one KV block (shapes from `_read_block_impl`) into a
        slot's region at token offset `start`."""
        new = {}
        for name, blk in (("k", kb), ("v", vb)):
            x = cache[name]
            new[name] = jax.lax.dynamic_update_slice(
                x, blk[:, None], (0, slot, start, 0, 0))
        return new

    def _decode_impl(self, params, cache, last_tokens, lengths, temps,
                     topks, rng):
        """`decode_steps` tokens for every slot per dispatch, via an
        in-program `lax.scan` (vLLM-style multi-step decoding): one
        device execution amortizes the per-dispatch overhead over K
        tokens — the lever that matters both for high-latency runtimes
        and for launch overhead on real pods. Returns tokens
        [slots, K]."""

        def step(carry, _):
            cache, tokens, lengths, rng = carry
            # Clamp for retired slots that keep computing until their
            # slot is re-admitted (pipelined decode fetches lag a block):
            # their writes wrap at the last position instead of OOB.
            lengths = jnp.minimum(lengths, self.max_seq - 2)
            logits, cache = forward_with_cache(
                params, tokens[:, None], self.cfg, cache, lengths)
            logits = logits[:, 0, :].astype(jnp.float32)  # [slots, vocab]
            greedy = logits.argmax(-1)
            # Per-slot top-k truncation: threshold at each slot's k-th
            # largest logit (k clamped to _TOP_K_MAX — lax.top_k needs a
            # static k, so one sorted prefix serves every slot).
            kth_vals = jax.lax.top_k(logits, _TOP_K_MAX)[0]
            idx = jnp.clip(topks - 1, 0, _TOP_K_MAX - 1)
            thresh = jnp.take_along_axis(kth_vals, idx[:, None], axis=1)
            truncated = jnp.where(logits < thresh, -jnp.inf, logits)
            sample_logits = jnp.where((topks > 0)[:, None], truncated,
                                      logits)
            rng, sub = jax.random.split(rng)
            sampled = jax.random.categorical(
                sub, sample_logits / jnp.maximum(temps, 1e-6)[:, None])
            next_tokens = jnp.where(temps > 0, sampled,
                                    greedy).astype(jnp.int32)
            return (cache, next_tokens, lengths + 1, rng), next_tokens

        (cache, last, lengths, rng), toks = jax.lax.scan(
            step, (cache, last_tokens, lengths, rng), None,
            length=self.decode_steps)
        # Device-side carries (last/lengths) let the NEXT decode dispatch
        # before this block's tokens reach the host (pipelined decode).
        return cache, toks.T, last, lengths, rng  # toks: [slots, K]

    # -- public API ------------------------------------------------------

    def start(self):
        # Under the lock: concurrent generate() callers must never spawn
        # two engine loops — dueling loops double-assign slots and feed
        # the donated cache twice, silently losing requests.
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._running.set()
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="llm-engine")
                self._thread.start()

    def stop(self):
        self._running.clear()
        # Let the loop leave its current device fetch before interpreter
        # teardown (a daemon thread cancelled mid-fetch can abort the
        # process with pthread noise).
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=10)

    def generate(self, prompt_ids: List[int],
                 params: Optional[SamplingParams] = None,
                 stream: bool = False, *,
                 model: Optional[str] = None,
                 priority: int = 1,
                 job: str = "default"):
        """Blocking generate (or an iterator of tokens with stream=True)."""
        prompt = list(prompt_ids)
        cap = self.max_seq - 1
        if len(prompt) > cap:
            raise PromptTooLongError(len(prompt), cap)
        req = _Request(
            request_id=next(self._req_counter), prompt=prompt,
            params=params or SamplingParams(), out_queue=queue.Queue(),
            t_arrival=time.perf_counter(),
            model=model, priority=max(0, min(2, int(priority))), job=job,
            # Stamped on the CALLING thread (the replica's task context
            # is thread-local; the engine loop below has none).
            trace_id=(critical_path.ambient_trace_id() or "")
            if critical_path.enabled() else "")
        self._queue.put(req)
        self.start()

        def token_iter():
            while True:
                item = req.out_queue.get()
                if item is None:
                    return
                yield item

        if stream:
            return token_iter()
        return list(token_iter())

    def metrics(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "active_slots": int(self._active.sum()),
                "free_slots": len(self._free_slots),
                "queued": self._queue.qsize(),
                "model": self.model,
            }
        if self.prefix_cache is not None:
            out["kv_cache"] = self.prefix_cache.stats()
        return out

    # -- engine loop -----------------------------------------------------

    def _loop(self):
        self._temps_arr = np.zeros(self.n_slots, np.float32)
        self._topks_arr = np.zeros(self.n_slots, np.int32)
        while self._running.is_set():
            admitted = self._admit()
            if not self._active.any():
                # Drop any in-flight block for fully-retired slots.
                self._flush_pending()
                if not admitted:
                    try:
                        req = self._queue.get(timeout=0.05)
                        self._queue.put(req)
                    except queue.Empty:
                        continue
                continue
            self._decode_once()

    def _serve_bucket(self, t_real: int) -> int:
        """Smallest compiled bucket that fits `t_real` tokens. The old
        code keyed `_run_prefill` on the exact power-of-two, so a
        request just over `warmup_max_prompt_len` missed the AOT ladder
        and paid a mid-serving compile even though a LARGER compiled
        bucket could serve it; now any bucket ≤ the compiled max
        serves from the ladder."""
        b = 1
        while b < t_real:
            b *= 2
        b = min(b, self.max_seq)
        if b in self._prefill_exec or not self._prefill_exec:
            return b
        bigger = [x for x in self._prefill_exec if x >= b]
        return min(bigger) if bigger else b

    def _admit(self) -> bool:
        if self._queue.empty() or not self._free_slots:
            return False
        # Admission invalidates the device carries and needs free slots:
        # drain the in-flight decode block first.
        self._flush_pending()
        drained: List[_Request] = []
        while True:
            try:
                drained.append(self._queue.get_nowait())
            except queue.Empty:
                break
        # Priority classes decide who gets the scarce slots at the shed
        # point: interactive (0) outranks normal (1) outranks batch (2);
        # FIFO within a class via the monotonic request id.
        drained.sort(key=lambda r: (r.priority, r.request_id))
        staged = []  # (req, slot, t_real, last_logits_ref, chain)
        leftover: List[_Request] = []
        for req in drained:
            if not self._free_slots:
                leftover.append(req)
                continue
            prompt = req.prompt
            t_real = len(prompt)
            slot = self._free_slots.pop()
            # Stage: admit = time spent queued for a slot.
            t_admit = time.perf_counter()
            critical_path.record_stage(req.trace_id, "llm.admit",
                                       t_admit - req.t_arrival)
            # Prefix-cache fast path: copy matched KV blocks straight
            # into the slot, then prefill ONLY the tail at the tail's
            # bucket, starting at the matched offset.
            m_tok, chain = self._prefix_copy_in(req, slot, prompt)
            req.t_kv_done = time.perf_counter()
            critical_path.record_stage(req.trace_id, "llm.kv_lookup",
                                       req.t_kv_done - t_admit)
            tail = prompt[m_tok:]
            t_tail = len(tail)
            bucket = self._serve_bucket(t_tail)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :t_tail] = tail
            self.cache, last_logits = self._run_prefill(
                jnp.asarray(tokens), jnp.int32(slot), jnp.int32(t_tail),
                jnp.int32(m_tok), bucket)
            req.t_prefill_done = time.perf_counter()
            staged.append((req, slot, t_real, last_logits, chain))
        for req in leftover:
            self._queue.put(req)
        if not staged:
            return False
        # ONE device-side sampling + ONE host sync for the whole wave:
        # per-admit argmax fetches would serialize a tunnel round-trip
        # per request (the dominant pre-first-token cost). Padded to
        # n_slots so the program (and the eager stack feeding it) has
        # one fixed shape, compiled once at warmup.
        pad = self.n_slots - len(staged)
        logits = jnp.stack([s[3] for s in staged]
                           + [staged[0][3]] * pad)  # [n_slots, vocab]
        temps_np = np.zeros(self.n_slots, np.float32)
        for i, s in enumerate(staged):
            temps_np[i] = s[0].params.temperature
        t_sample = time.perf_counter()
        firsts_dev, self._rng = self._run_sample(
            logits, jnp.asarray(temps_np))
        # The host sync below is where the wave's ASYNC-dispatched
        # prefill compute actually completes; the fused sample kernel
        # is trivial next to a transformer prefill, so the sync wait is
        # attributed to each staged request's prefill stage (split
        # evenly across the wave). The residual — dispatch overhead of
        # the batched sample path — is the first-token stage. The two
        # splits tile the wave's wall time, so the per-request vector
        # still sums to what the request actually spent here.
        firsts = np.asarray(firsts_dev)[:len(staged)]
        now = time.perf_counter()
        sync_share = (now - t_sample) / len(staged)
        for (req, slot, t_real, _, _chain), first in zip(staged, firsts):
            critical_path.record_stage(
                req.trace_id, "llm.prefill",
                (req.t_prefill_done - req.t_kv_done) + sync_share)
            critical_path.record_stage(
                req.trace_id, "llm.first_token",
                max(0.0, t_sample - req.t_prefill_done))
            first = int(first)
            req.t_first_token = now
            req.tokens.append(first)
            req.out_queue.put(first)
            with self._lock:
                req.slot = slot
                self._slot_req[slot] = req
                self._lengths[slot] = t_real
                self._last_token[slot] = first
                self._active[slot] = True
                self._temps_arr[slot] = req.params.temperature
                self._topks_arr[slot] = max(0, min(req.params.top_k,
                                                   _TOP_K_MAX))
            if self._finished(req, first):
                self._retire(slot)
        # Prefix-cache read-back AFTER the first-token wave (TTFT is not
        # taxed by the host copies). Safe ordering: a slot retired above
        # cannot be re-admitted until a LATER _admit call, so the KV
        # bytes being read are still this request's prefill output.
        for req, slot, t_real, _logits, chain in staged:
            self._prefix_admit(req, slot, chain)
        # Host state changed: rebuild device carries on the next decode.
        self._dev_last = self._dev_lengths = None
        return True

    def _decode_once(self):
        # The fed token occupies absolute position `lengths` (prompt is
        # 0..len-1, first generated token sits at len, etc.). Dispatch
        # block N+1 from the device-side carries, THEN fetch block N —
        # the host round-trip overlaps the next block's compute.
        last = self._dev_last if self._dev_last is not None \
            else jnp.asarray(self._last_token)
        lengths = self._dev_lengths if self._dev_lengths is not None \
            else jnp.asarray(self._lengths)
        (self.cache, next_tokens, self._dev_last, self._dev_lengths,
         self._rng) = self._run_decode(
            last, lengths,
            jnp.asarray(self._temps_arr),
            jnp.asarray(self._topks_arr))
        prev, self._pending_toks = self._pending_toks, next_tokens
        if prev is not None:
            self._consume_block(np.asarray(prev))

    def _flush_pending(self):
        prev, self._pending_toks = self._pending_toks, None
        if prev is not None:
            self._consume_block(np.asarray(prev))

    def _consume_block(self, next_host):
        with self._lock:
            for slot in np.nonzero(self._active)[0]:
                req = self._slot_req[slot]
                # Walk this slot's K-token block; once the request
                # finishes mid-block the remaining tokens are padding
                # compute and are discarded.
                for k in range(next_host.shape[1]):
                    tok = int(next_host[slot, k])
                    req.tokens.append(tok)
                    req.out_queue.put(tok)  # raylint: disable=R2 -- per-request stream queues are unbounded, so put() cannot block; token delivery and slot-state mutation must share one hold or a racing admit could reuse the slot mid-block
                    self._lengths[slot] += 1
                    self._last_token[slot] = tok
                    if self._finished(req, tok) or \
                            self._lengths[slot] >= self.max_seq - 1:
                        self._retire(slot)  # raylint: disable=R2 -- _retire only pushes the unbounded-queue end-of-stream sentinel and frees the slot; both must be atomic with the walk above
                        break

    def _finished(self, req: _Request, token: int) -> bool:
        if token in req.params.stop_token_ids:
            return True
        return len(req.tokens) >= req.params.max_tokens

    def _retire(self, slot: int):
        req = self._slot_req.pop(slot, None)
        if req is not None:
            if req.t_first_token is not None:
                # Per-slot decode stage: first token → end of stream.
                critical_path.record_stage(
                    req.trace_id, "llm.decode",
                    time.perf_counter() - req.t_first_token)
            req.out_queue.put(None)
        self._active[slot] = False
        self._lengths[slot] = 0
        self._free_slots.append(slot)

    # -- prefix/KV cache ------------------------------------------------
    #
    # The PrefixCache core (pure, spec-checked) decides which blocks
    # exist; the engine owns the PAYLOADS: `_kv_store` maps block
    # generation id → (k, v) host arrays, and evicted payloads fall to
    # the shm plane under a deterministic ObjectID derived from the
    # chain key. A chain key commits to the model seed + every token of
    # the prefix, so a key hit on ANY tier is byte-identical KV by
    # construction (same weights + same tokens + causal attention).

    def _prefix_copy_in(self, req: _Request, slot: int, prompt):
        """Copy the longest cached prefix of `prompt` into `slot`'s KV
        region. Returns (matched_tokens, chain_keys)."""
        pc = self.prefix_cache
        if pc is None:
            return 0, []
        chain = chain_keys(prompt, self.block_tokens, self._chain_seed)
        if not chain:
            return 0, []
        hit = pc.lookup(chain, req.job)
        # Cap the match: (a) ≥1 real token must go through prefill (the
        # last-position logits feed the first sampled token), and (b)
        # matched_offset + tail_bucket must FIT the slot's KV region —
        # an overhanging padded bucket would clamp its KV write and
        # corrupt the copied prefix.
        m = min(len(hit), (len(prompt) - 1) // self.block_tokens)
        while m > 0:
            t_tail = len(prompt) - m * self.block_tokens
            if m * self.block_tokens + self._serve_bucket(t_tail) \
                    <= self.max_seq:
                break
            m -= 1
        while len(hit) > m:
            pc.release([hit.pop()])
        # Resolve payloads hot→warm; the first miss truncates the match
        # (a child block without its parent is useless).
        payloads = []
        for i, h in enumerate(hit):
            p = self._kv_store.get(h.block_id)
            if p is None:
                p = self._shm_restore(h)
            if p is None:
                pc.release(hit[i:])
                hit = hit[:i]
                break
            payloads.append(p)
        for h, (k_np, v_np) in zip(hit, payloads):
            self.cache = self._write_block_j(
                self.cache, jnp.asarray(k_np), jnp.asarray(v_np),
                jnp.int32(slot), jnp.int32(h.index * self.block_tokens))
        pc.release(hit)
        return len(hit) * self.block_tokens, chain

    def _prefix_admit(self, req: _Request, slot: int, chain):
        """After prefill, admit the prompt's full-block chain and read
        the KV bytes for newly-created blocks back to the host store.
        Runs post-first-token so TTFT never pays for the readback."""
        pc = self.prefix_cache
        if pc is None or not chain:
            return
        created, evicted = pc.admit(chain, req.job, self._block_nbytes)
        for h in created:
            kb, vb = self._read_block_j(
                self.cache, jnp.int32(slot),
                jnp.int32(h.index * self.block_tokens))
            self._kv_store[h.block_id] = (np.asarray(kb), np.asarray(vb))
        pc.release(created)
        self._offload_evicted(evicted)

    @staticmethod
    def _shm_object_id(key: str):
        from ray_tpu._private.ids import ObjectID
        return ObjectID(hashlib.blake2b(
            ("llmkv|" + key).encode(), digest_size=ObjectID.SIZE).digest())

    def _shm_plane(self):
        if not ray_config.llm_prefix_shm_tier:
            return None
        try:
            from ray_tpu._private.worker import global_worker_or_none
            w = global_worker_or_none()
            return getattr(w, "shm_plane", None)
        except Exception:
            return None

    def _shm_restore(self, handle):
        """Warm-tier fetch: a block evicted here (or admitted by ANOTHER
        replica — keys are content-addressed) comes back through the
        object plane instead of being recomputed."""
        plane = self._shm_plane()
        if plane is None:
            return None
        try:
            ok, payload = plane.get(self._shm_object_id(handle.key))
        except Exception:
            return None
        if not ok or payload is None:
            return None
        self._kv_store[handle.block_id] = payload
        self._c_shm_restores.inc()
        return payload

    def _offload_evicted(self, evicted):
        """Evicted blocks leave the host store but persist as shm-plane
        objects (spill-backed, charged to the admitting tenant's plane
        quota) — a later hit restores bytes instead of recomputing."""
        plane = self._shm_plane() if evicted else None
        for e in evicted:
            payload = self._kv_store.pop(e.block_id, None)
            if plane is None or payload is None:
                continue
            try:
                if plane.maybe_put(self._shm_object_id(e.key), payload,
                                   timeout=0.1):
                    self._c_shm_offloads.inc()
            except Exception:
                pass  # warm tier is best-effort; the cold path recomputes

    # -- multi-model ----------------------------------------------------

    def swap_params(self, params, model: str):
        """Swap the served weight set (multi-model multiplexing). The
        compiled programs take params as ARGUMENTS with unchanged avals,
        so no recompile happens — the swap is one device_put. Caller
        must have drained the engine (no active slots / queued work):
        in-flight KV belongs to the OLD model."""
        with self._lock:
            if self._active.any() or not self._queue.empty():
                raise RuntimeError(
                    "swap_params on a non-idle engine: drain first")
            self.params = jax.device_put(params, self._s1)
            self.model = model
            self._chain_seed = self._seed_for(model)

    def prefix_digests(self) -> Optional[Dict[str, Any]]:
        """Hot prefix-head digests for cache-affinity routing (exported
        through the serve membership channel). None ⇒ no hints (router
        falls back to least-loaded/round-robin)."""
        if self.prefix_cache is None or not ray_config.llm_affinity_routing:
            return None
        return {
            "model": self.model,
            "block_tokens": self.block_tokens,
            "seed": self._chain_seed,
            "block_bytes": self._block_nbytes,
            "keys": self.prefix_cache.hot_digests(
                int(ray_config.llm_digest_blocks)),
        }


def lax_slice_slot(cache, slot):
    """cache: [L, slots, S, H, D] → [L, 1, S, H, D] at `slot`."""
    return jax.lax.dynamic_slice_in_dim(cache, slot, 1, axis=1)


def lax_write_slot(cache, slot_cache, slot):
    return jax.lax.dynamic_update_slice_in_dim(cache, slot_cache, slot,
                                               axis=1)


# -- Serve integration ------------------------------------------------------


# Priority classes understood on the wire (ints 0-2 also accepted).
_PRIORITY_CLASSES = {
    "high": 0, "interactive": 0, "normal": 1, "low": 2, "batch": 2,
}


def _parse_priority(raw) -> int:
    if isinstance(raw, str):
        return _PRIORITY_CLASSES.get(raw.lower().strip(), 1)
    try:
        return max(0, min(2, int(raw)))
    except (TypeError, ValueError):
        return 1


class LLMDeployment:
    """Deployment-ready wrapper: `serve.deployment(LLMDeployment).bind(...)`.

    Each replica owns one engine (one KV cache in its chip's HBM) and
    may multiplex N weight variants (``models={name: params_fn}``): the
    compiled programs take params as arguments, so switching models is
    a drain + ``device_put``, never a recompile. A swap is charged to
    the requesting tenant and bounded by the
    ``llm_model_swap_deadline_s`` cold-start SLA (post-hoc: the weights
    stay cached, so a deadline miss leaves the NEXT attempt warm).
    Serve's router spreads requests over replicas, preferring replicas
    whose prefix cache already holds the request's prompt head.
    """

    def __init__(self, cfg: LlamaConfig, params_fn: Callable[[], Any] = None,
                 max_batch_size: int = 8,
                 max_seq_len: Optional[int] = None,
                 decode_steps: int = 1,
                 warmup: bool = True,
                 warmup_max_prompt_len: Optional[int] = None,
                 models: Optional[Dict[str, Any]] = None,
                 default_model: Optional[str] = None):
        self.models: Dict[str, Any] = dict(models or {})
        if params_fn is not None and not self.models:
            self.models[default_model or "default"] = params_fn
        if not self.models:
            raise ValueError("LLMDeployment needs params_fn or models={...}")
        self.default_model = default_model or next(iter(self.models))
        if self.default_model not in self.models:
            raise UnknownModelError(self.default_model, self.models)
        self._loaded: Dict[str, Any] = {}
        self._swap_lock = threading.RLock()
        self._c_swaps = perf_stats.counter("llm_model_swaps")
        params = self._load_model(self.default_model, job="deploy")
        self.engine = LLMEngine(cfg, params, max_batch_size=max_batch_size,
                                max_seq_len=max_seq_len,
                                decode_steps=decode_steps,
                                model=self.default_model)
        # Deploy-time AOT: compile prefill buckets + decode BEFORE the
        # replica takes traffic, so the first request's TTFT is serving
        # latency, not XLA compile (round 3 measured 14 s cold TTFT).
        # With the persistent compilation cache, re-deploys of the same
        # config warm up in well under a second.
        self.warmup_s = self.engine.warmup(warmup_max_prompt_len) \
            if warmup else 0.0
        self.engine.start()

    # -- model loading / swapping ---------------------------------------

    def _load_model(self, model: str, job: str):
        """Resolve a model's weights: host cache → shm-plane warm tier →
        loader callable. The load is charged to the requesting tenant
        via the swap-bytes counter (and the plane publish is
        quota-charged by the plane itself)."""
        cached = self._loaded.get(model)
        if cached is not None:
            return cached
        src = self.models[model]
        params = src() if callable(src) else src
        self._loaded[model] = params
        try:
            nbytes = sum(
                int(x.size) * int(x.dtype.itemsize)
                for x in jax.tree_util.tree_leaves(params)
                if hasattr(x, "size") and hasattr(x, "dtype"))
            perf_stats.counter(
                "llm_model_swap_bytes", {"job": job}).inc(nbytes)
        except Exception:
            pass
        return params

    def _ensure_model(self, model: str, job: str):
        """Make `model` the engine's live weight set. Caller holds
        `_swap_lock`, which also covers the subsequent enqueue — no
        other request can slip a different model in between. Returns
        the loaded params (unused by the engine path, handy for
        tests)."""
        if model not in self.models:
            raise UnknownModelError(model, self.models)
        if self.engine.model == model:
            return self._loaded.get(model)
        t0 = time.perf_counter()
        # Drain: every request enqueues under _swap_lock (held by us),
        # so active/queued can only fall.
        while True:
            m = self.engine.metrics()
            if m["active_slots"] == 0 and m["queued"] == 0:
                break
            time.sleep(0.002)
        params = self._load_model(model, job)
        self.engine.swap_params(params, model)
        self._c_swaps.inc()
        took = time.perf_counter() - t0
        deadline = float(ray_config.llm_model_swap_deadline_s or 0)
        if deadline and took > deadline:
            # Post-hoc SLA: the swap COMPLETED and the weights stay
            # cached, so the caller's retry is warm.
            raise ModelSwapDeadlineError(model, took, deadline)
        return params

    def prefix_digests(self):
        return self.engine.prefix_digests()

    def __call__(self, request: Dict[str, Any]):
        t0 = time.perf_counter()
        params = SamplingParams(
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            stop_token_ids=tuple(request.get("stop_token_ids", ())))
        model = str(request.get("model") or self.default_model)
        priority = _parse_priority(request.get("priority", 1))
        job = str(request.get("job") or request.get("job_id") or "default")
        # Hold the swap lock across ensure + enqueue: a concurrent
        # request for a DIFFERENT model must not swap weights between
        # our check and our admission. Token consumption happens
        # outside the lock — a queued request pins its model because
        # any later swap drains the queue first.
        with self._swap_lock:
            self._ensure_model(model, job)  # raylint: disable=R2 -- the blocking drain IS the design: the swap lock must span drain+swap+enqueue or a concurrent request could swap weights between our model check and our admission; the engine drains independently of this lock, so the wait always terminates
            it = self.engine.generate(
                request["prompt_ids"], params, stream=True,
                model=model, priority=priority, job=job)
        if request.get("stream"):
            # Generator return → the replica streams it chunk-by-chunk
            # (tokens reach the client during decode, not after).
            def token_stream():
                for i, token in enumerate(it):
                    yield {"token": int(token), "index": i}
            return token_stream()
        tokens = []
        ttft_s = None
        for token in it:
            if ttft_s is None:
                ttft_s = time.perf_counter() - t0
            tokens.append(int(token))
        return {"tokens": tokens,
                "model": model,
                "ttft_s": ttft_s,
                "latency_s": time.perf_counter() - t0}

    def check_health(self):
        assert self.engine._thread is None or \
            self.engine._thread.is_alive() or \
            not self.engine._running.is_set()
