"""ray_tpu.serve: online model serving on actors.

Reference `python/ray/serve/` (SURVEY.md §2.4 + §3.4 request path):
`@serve.deployment` → `serve.run` → detached controller reconciles
replica actors; handles route via client-side routers fed by long-poll;
`@serve.batch` batches concurrent calls; an HTTP proxy fronts handles.
TPU-specific serving (compiled-XLA replicas, continuous batching with a
paged KV cache) lives in `ray_tpu.serve.llm`.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve._private.controller import (
    CONTROLLER_NAME,
    get_or_create_controller,
)
from ray_tpu.serve._private.http_proxy import HTTPProxy
from ray_tpu.serve._private.proxy_actor import (  # noqa: F401
    HTTPProxyActor,
    ProxyFleet,
    start_proxy_fleet,
)
from ray_tpu.serve._private.router import ServeHandle
from ray_tpu.serve.streaming import (  # noqa: F401
    aiter_stream,
    is_stream,
    iter_stream,
)

_proxy: Optional[HTTPProxy] = None


@dataclass
class Deployment:
    """Result of @serve.deployment; `.bind()`/`.options()` mirror the
    reference's deployment DSL (`serve/deployment.py`)."""

    func_or_class: Any
    name: str
    num_replicas: int = 1
    init_args: tuple = ()
    init_kwargs: dict = field(default_factory=dict)
    user_config: Any = None
    max_concurrent_queries: int = 100
    ray_actor_options: Optional[dict] = None
    autoscaling_config: Optional[dict] = None
    route_prefix: Optional[str] = None
    version: Optional[str] = None

    def options(self, **kwargs) -> "Deployment":
        import dataclasses as dc

        known = {f.name for f in dc.fields(Deployment)}
        clean = {k: v for k, v in kwargs.items() if k in known}
        return dc.replace(self, **clean)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def deploy(self, *init_args, **init_kwargs):
        return run(self.bind(*init_args, **init_kwargs),
                   route_prefix=self.route_prefix)


@dataclass
class Application:
    deployment: Deployment
    args: tuple
    kwargs: dict


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, init_args: tuple = (),
               init_kwargs: Optional[dict] = None, user_config: Any = None,
               max_concurrent_queries: int = 100,
               ray_actor_options: Optional[dict] = None,
               autoscaling_config: Optional[dict] = None,
               route_prefix: Optional[str] = None,
               version: Optional[str] = None, **_ignored):
    """`@serve.deployment` (reference `serve/api.py`)."""

    def wrap(obj):
        return Deployment(
            func_or_class=obj, name=name or obj.__name__,
            num_replicas=num_replicas, init_args=init_args,
            init_kwargs=init_kwargs or {}, user_config=user_config,
            max_concurrent_queries=max_concurrent_queries,
            ray_actor_options=ray_actor_options,
            autoscaling_config=autoscaling_config,
            route_prefix=route_prefix, version=version)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


def run(target, *, name: str = "default", route_prefix: Optional[str] = None,
        _blocking: bool = True) -> ServeHandle:
    """Deploy an Application — or a *deployment graph*: bound arguments
    that are themselves Applications deploy first and arrive in the
    parent's constructor as ServeHandles, composing multi-model
    pipelines (reference: `serve/_private/deployment_graph_build.py` +
    `serve/drivers.py` DAGDriver)."""
    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise TypeError(f"serve.run expects a bound deployment, got "
                        f"{type(target)}")
    handle = _deploy_application(target, {}, _blocking)
    dep = target.deployment
    prefix = route_prefix if route_prefix is not None else dep.route_prefix
    if prefix is not None:
        start_http_proxy().routes.set(prefix, handle)
        # Route table lives on the controller too: proxy-actor fleets
        # (HTTPProxyActor) learn it via the "routes" long-poll channel.
        controller = get_or_create_controller()
        ray_tpu.get(controller.set_route.remote(prefix, dep.name))
    return handle


def _resolve_bound(value, seen: dict, blocking: bool):
    if isinstance(value, Application):
        return _deploy_application(value, seen, blocking)
    if isinstance(value, (list, tuple)):
        return type(value)(_resolve_bound(v, seen, blocking)
                           for v in value)
    if isinstance(value, dict):
        return {k: _resolve_bound(v, seen, blocking)
                for k, v in value.items()}
    return value


def _deploy_application(app: Application, seen: dict,
                        blocking: bool = True) -> ServeHandle:
    """Deploy one node of a graph (children first, depth-first). The
    same bound node appearing twice (diamond graphs) deploys once."""
    if id(app) in seen:
        return seen[id(app)]
    dep = app.deployment
    init_args = tuple(_resolve_bound(a, seen, blocking) for a in app.args)
    init_kwargs = {k: _resolve_bound(v, seen, blocking)
                   for k, v in app.kwargs.items()}
    controller = get_or_create_controller()
    info = {
        "cls": dep.func_or_class,
        "init_args": init_args,
        "init_kwargs": init_kwargs,
        "num_replicas": dep.num_replicas,
        "user_config": dep.user_config,
        "max_concurrent_queries": dep.max_concurrent_queries,
        "ray_actor_options": dep.ray_actor_options,
        "autoscaling_config": dep.autoscaling_config,
        "version": dep.version,
    }
    ray_tpu.get(controller.deploy.remote(dep.name, info))
    if blocking:
        _wait_healthy(controller, dep.name)
    handle = ServeHandle(controller, dep.name,
                         dep.max_concurrent_queries)
    seen[id(app)] = handle
    return handle


def _wait_healthy(controller, name: str, timeout: float = 30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        info = ray_tpu.get(controller.get_deployment_info.remote(name))
        if info and info["status"] == "HEALTHY":
            return
        time.sleep(0.02)
    raise TimeoutError(f"deployment {name} not healthy after {timeout}s")


@deployment
class DAGDriver:
    """HTTP entry point for a deployment graph (reference:
    `serve/drivers.py` DAGDriver): routes each request into the bound
    graph's root handle and returns its result.

    Usage::

        graph = Combiner.bind(ModelA.bind(), ModelB.bind())
        serve.run(serve.DAGDriver.bind(graph), route_prefix="/pipeline")
    """

    def __init__(self, root_handle, http_adapter=None):
        self.root = root_handle
        self.http_adapter = http_adapter

    def __call__(self, request=None):
        if self.http_adapter is not None:
            request = self.http_adapter(request)
        ref = self.root.remote(request) if request is not None \
            else self.root.remote()
        return ray_tpu.get(ref, timeout=60)


def get_deployment_handle(name: str, *_args, **_kwargs) -> ServeHandle:
    controller = get_or_create_controller()
    info = ray_tpu.get(controller.get_deployment_info.remote(name))
    if info is None:
        raise ValueError(f"deployment {name!r} not found")
    return ServeHandle(controller, name)


def get_app_handle(name: str) -> ServeHandle:
    return get_deployment_handle(name)


def status() -> Dict[str, Any]:
    controller = get_or_create_controller()
    names = ray_tpu.get(controller.list_deployments.remote())
    return {
        n: ray_tpu.get(controller.get_deployment_info.remote(n))
        for n in names
    }


def delete(name: str):
    controller = get_or_create_controller()
    ray_tpu.get(controller.delete_deployment.remote(name))
    # Retract the deployment's routes everywhere: the controller table
    # (proxy-actor fleets long-poll it) and the driver-local proxy.
    ray_tpu.get(controller.remove_routes_of.remote(name))
    if _proxy is not None:
        for prefix, handle in list(_proxy.routes._routes.items()):
            if getattr(handle, "_deployment", None) == name:
                _proxy.routes.remove(prefix)


def start_http_proxy(host: str = "127.0.0.1", port: int = 0,
                     **proxy_options) -> HTTPProxy:
    """Driver-local ingress. ``proxy_options`` forward to
    :class:`HTTPProxy` (``max_in_flight``, ``queue_timeout_s``,
    ``idle_timeout_s``); on an already-running proxy (serve.run starts
    one for any routed deployment) they reconfigure it in place —
    they're read per-request, so the change applies immediately."""
    global _proxy
    if _proxy is None:
        _proxy = HTTPProxy(host, port, **proxy_options)
    else:
        allowed = ("max_in_flight", "queue_timeout_s", "idle_timeout_s",
                   "result_timeout_s")
        unknown = [k for k in proxy_options if k not in allowed]
        if unknown:  # validate ALL keys before mutating any (atomic)
            raise TypeError(f"unknown proxy option(s) {unknown!r}")
        for key, value in proxy_options.items():
            setattr(_proxy, key, value)
    return _proxy


def shutdown():
    global _proxy
    from ray_tpu.serve._private.membership import (
        shutdown_all_dispatchers,
        shutdown_all_watches,
    )
    from ray_tpu.serve._private.router import shutdown_all_routers
    from ray_tpu.serve.batching import retire_all_batchers

    # Routers first: their stop flags must be set before the
    # controller dies so the long-poll threads exit on the resulting
    # error instead of re-resolving a replacement controller. Direct
    # dispatchers and any orphaned membership watches go down with
    # them (watches stop on last unsubscribe; the sweep below catches
    # subscribers that never unsubscribed).
    shutdown_all_routers()
    shutdown_all_dispatchers()
    shutdown_all_watches()
    retire_all_batchers()
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.graceful_shutdown.remote())
        ray_tpu.kill(controller)
    except ValueError:
        pass
    if _proxy is not None:
        _proxy.shutdown()
        _proxy = None
