"""ServeController: the reconciliation brain.

Reference: `serve/controller.py:70` + `_private/deployment_state.py:998` —
a detached singleton actor holding target state per deployment (replica
count, version, config) and a reconcile loop that starts/stops replica
actors to match, performs rolling updates on version change, health-checks
replicas, and drives autoscaling from router-reported queue metrics.
Membership changes broadcast to routers via the long-poll host.

Fault tolerance (reference `serve/_private/storage/kv_store.py:1` +
controller recovery in `serve/controller.py:70` ff.): every target-state
mutation checkpoints {deployments, routes, replica names} to the GCS
internal KV (durable when the head runs with gcs_storage_path). Replicas
are NAMED detached actors, so a restarted controller re-attaches the
live ones instead of cold-starting the fleet; dead ones are replaced by
the normal reconcile loop. While the controller is down, routers keep
answering from their last long-poll snapshot.
"""

from __future__ import annotations

import hashlib
import threading
import time
import traceback
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve._private.long_poll import LongPollHost
from ray_tpu.serve._private.replica import ServeReplica

CONTROLLER_NAME = "SERVE_CONTROLLER"
_CKPT_NS = b"__serve__"
_CKPT_KEY = b"controller_state"


def _version_hash(payload) -> str:
    import pickle

    try:
        blob = pickle.dumps(payload)
    except Exception:
        blob = repr(payload).encode()
    return hashlib.sha1(blob).hexdigest()[:12]


class _DeploymentState:
    def __init__(self, name: str, info: Dict[str, Any]):
        self.name = name
        self.info = info  # cls, init_args, init_kwargs, num_replicas, ...
        self.version = info["version"]
        self.replicas: List[Any] = []
        self.replica_versions: Dict[Any, str] = {}
        self.replica_names: Dict[Any, str] = {}  # handle -> actor name
        self.status = "UPDATING"
        self.message = ""


@ray_tpu.remote
class ServeController:
    def __init__(self):
        self._lock = threading.RLock()
        # Serializes checkpoint snapshot+write so concurrent mutators
        # cannot commit out of order (a stale snapshot overwriting a
        # newer one would lose deployments across a crash).
        self._ckpt_lock = threading.Lock()
        self._deployments: Dict[str, _DeploymentState] = {}
        self._long_poll = LongPollHost()
        self._metrics: Dict[str, Dict[str, float]] = {}
        # Route table: prefix -> deployment name. Proxy actors learn it
        # via the "routes" long-poll channel (reference: the
        # control->data-plane LongPollHost route updates).
        self._routes: Dict[str, str] = {}
        self._shutdown = threading.Event()
        self._recover()
        self._reconciler = threading.Thread(target=self._reconcile_loop,
                                            daemon=True)
        self._reconciler.start()

    # -- checkpoint / recovery (reference serve kv_store.py) -------------

    def _kv(self):
        from ray_tpu._private.worker import global_worker

        return global_worker().gcs

    def _checkpoint(self):
        import cloudpickle

        with self._ckpt_lock:
            if self._shutdown.is_set():
                return  # never re-create the key after a wipe
            with self._lock:
                state = {
                    "routes": dict(self._routes),
                    "deployments": {
                        name: {
                            "info": st.info,
                            "replicas": [
                                (st.replica_names.get(r),
                                 st.replica_versions.get(r))
                                for r in st.replicas
                                if st.replica_names.get(r)
                            ],
                        }
                        for name, st in self._deployments.items()
                    },
                }
            try:
                self._kv().kv_put(_CKPT_KEY, cloudpickle.dumps(state),
                                  namespace=_CKPT_NS)
            except Exception:
                traceback.print_exc()

    def _recover(self):
        import cloudpickle

        try:
            blob = self._kv().kv_get(_CKPT_KEY, namespace=_CKPT_NS)
        except Exception:
            blob = None
        if not blob:
            return
        try:
            state = cloudpickle.loads(blob)
        except Exception:
            traceback.print_exc()
            return
        self._routes = dict(state.get("routes") or {})
        recovered_replicas = 0
        for name, d in (state.get("deployments") or {}).items():
            st = _DeploymentState(name, d["info"])
            # Re-attach live named replicas; dead/missing ones are
            # replaced by the first reconcile pass. An unreachable one
            # is best-effort KILLED, never silently skipped — skipping
            # would strand a detached actor (and its resources) forever.
            for rname, version in d.get("replicas") or []:
                h = None
                try:
                    h = ray_tpu.get_actor(rname)
                    ray_tpu.get(h.check_health.remote(), timeout=10.0)
                except Exception:
                    if h is not None:
                        try:
                            ray_tpu.kill(h)
                        except Exception:
                            pass
                    continue
                st.replicas.append(h)
                st.replica_versions[h] = version
                st.replica_names[h] = rname
                recovered_replicas += 1
            st.status = "UPDATING"
            self._deployments[name] = st
        for st in self._deployments.values():
            self._broadcast(st.name, st.replicas)
        self._long_poll.notify_changed("routes", dict(self._routes))
        if self._deployments:
            from ray_tpu._private.events import record_event

            record_event(
                "serve", "controller recovered "
                f"{len(self._deployments)} deployment(s), "
                f"{recovered_replicas} live replica(s) from checkpoint")

    # -- routes (consumed by HTTPProxyActor fleet) -----------------------

    def set_route(self, prefix: str, deployment_name: str) -> bool:
        with self._lock:
            self._routes[prefix.rstrip("/") or "/"] = deployment_name
            snapshot = dict(self._routes)
        self._long_poll.notify_changed("routes", snapshot)
        self._checkpoint()
        return True

    def remove_route(self, prefix: str) -> bool:
        with self._lock:
            self._routes.pop(prefix.rstrip("/") or "/", None)
            snapshot = dict(self._routes)
        self._long_poll.notify_changed("routes", snapshot)
        self._checkpoint()
        return True

    def remove_routes_of(self, deployment_name: str) -> bool:
        """Drop every prefix routing to a deployment (serve.delete)."""
        with self._lock:
            for prefix in [p for p, d in self._routes.items()
                           if d == deployment_name]:
                del self._routes[prefix]
            snapshot = dict(self._routes)
        self._long_poll.notify_changed("routes", snapshot)
        self._checkpoint()
        return True

    def get_routes(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._routes)

    # -- API -------------------------------------------------------------

    def deploy(self, name: str, info: Dict[str, Any]) -> bool:
        info = dict(info)
        info["version"] = info.get("version") or _version_hash(
            (info.get("init_args"), info.get("init_kwargs"),
             info.get("user_config"), info.get("num_replicas")))
        with self._lock:
            existing = self._deployments.get(name)
            if existing is None:
                self._deployments[name] = _DeploymentState(name, info)
            else:
                existing.info = info
                existing.version = info["version"]
                existing.status = "UPDATING"
        from ray_tpu._private.events import record_event

        record_event("serve", f"deployment {name} deployed "
                     f"(version {info['version'][:8]})",
                     deployment=name)
        self._checkpoint()
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            state = self._deployments.pop(name, None)
        if state:
            for r in state.replicas:
                self._stop_replica(r)
            self._broadcast(name, [])
            from ray_tpu._private.events import record_event

            record_event("serve", f"deployment {name} deleted",
                         deployment=name)
        self._checkpoint()
        return True

    def get_deployment_info(self, name: str) -> Optional[dict]:
        with self._lock:
            st = self._deployments.get(name)
            if st is None:
                return None
            return {"name": name, "status": st.status,
                    "num_replicas": len(st.replicas),
                    "target_replicas": st.info.get("num_replicas", 1),
                    "version": st.version, "message": st.message}

    def list_deployments(self) -> List[str]:
        with self._lock:
            return list(self._deployments)

    def listen(self, key: str, known_version: int = -1):
        return self._long_poll.listen(key, known_version)

    def record_handle_metrics(self, deployment: str,
                              queued: float) -> bool:
        with self._lock:
            self._metrics.setdefault(deployment, {})["queued"] = queued
            self._metrics[deployment]["ts"] = time.monotonic()
        return True

    def graceful_shutdown(self) -> bool:
        self._shutdown.set()
        # Release long-poll waiters FIRST: an in-flight listen would
        # otherwise hold an executor thread (and its client's get) in a
        # 30s condvar wait long after this actor is gone.
        self._long_poll.shutdown()
        # Let the in-flight reconcile pass finish before tearing down:
        # it could otherwise start a replica after we've iterated
        # st.replicas (a detached-actor leak) or re-write the
        # checkpoint after the wipe below.
        self._reconciler.join(timeout=10.0)
        with self._lock:
            states = list(self._deployments.values())
            self._deployments.clear()
            self._routes.clear()
        for st in states:
            for r in st.replicas:
                self._stop_replica(r)
        with self._ckpt_lock:  # flush any in-flight checkpoint write
            try:
                self._kv().kv_del(_CKPT_KEY, namespace=_CKPT_NS)
            except Exception:
                pass
        return True

    def _on_actor_stop(self):
        """Runtime abrupt-stop hook (`_Actor.stop`): fires on ANY stop
        — kill, crash-simulation, restart-in-place — where
        graceful_shutdown never ran. Retires the reconciler thread and
        releases parked long-poll listeners; without it a killed
        controller leaks both (threads outlive their thread-simulated
        'process')."""
        self._shutdown.set()
        self._long_poll.shutdown()

    # -- reconcile -------------------------------------------------------

    def _reconcile_loop(self):
        while not self._shutdown.is_set():
            try:
                self._reconcile_once()
            except Exception:
                traceback.print_exc()
            self._shutdown.wait(0.1)

    def _reconcile_once(self):
        with self._lock:
            states = list(self._deployments.values())
        for st in states:
            self._autoscale(st)
            target = int(st.info.get("num_replicas", 1))
            version = st.version
            changed = False
            # Rolling update: stop outdated replicas one at a time.
            outdated = [r for r in st.replicas
                        if st.replica_versions.get(r) != version]
            if outdated and len(st.replicas) >= target:
                victim = outdated[0]
                st.replicas.remove(victim)
                st.replica_versions.pop(victim, None)
                st.replica_names.pop(victim, None)
                self._stop_replica(victim)
                changed = True
            while len(st.replicas) < target:
                r = self._start_replica(st)
                if r is None:
                    break
                st.replicas.append(r)
                st.replica_versions[r] = version
                changed = True
            while len(st.replicas) > target:
                victim = st.replicas.pop()
                st.replica_versions.pop(victim, None)
                st.replica_names.pop(victim, None)
                self._stop_replica(victim)
                changed = True
            if changed or st.status == "UPDATING":
                up_to_date = all(st.replica_versions.get(r) == version
                                 for r in st.replicas)
                if len(st.replicas) == target and up_to_date:
                    st.status = "HEALTHY"
                self._broadcast(st.name, st.replicas)
            if changed:
                self._checkpoint()

    def _autoscale(self, st: _DeploymentState):
        cfg = st.info.get("autoscaling_config")
        if not cfg:
            return
        m = self._metrics.get(st.name)
        if not m:
            return
        # Routers report continuously while anything is queued or in
        # flight (Router._report_loop) and send a final 0 on drain, so
        # scale-down normally rides FRESH zero reports. The stale branch
        # is only the backstop for a vanished driver/router — generous
        # threshold so a mid-request deployment whose router hiccups is
        # never torn down under its callers.
        stale = time.monotonic() - m.get("ts", 0) > 30
        queued = 0.0 if stale else m["queued"]
        target_in_flight = cfg.get("target_num_ongoing_requests_per_replica",
                                   1.0)
        current = max(1, len(st.replicas))
        desired = queued / max(target_in_flight, 1e-6)
        desired = int(min(max(desired, cfg.get("min_replicas", 1)),
                          cfg.get("max_replicas", current)))
        if desired != st.info.get("num_replicas"):
            from ray_tpu._private.events import record_event

            record_event(
                "serve", f"autoscaling {st.name}: "
                f"{st.info.get('num_replicas')} -> {desired} replicas "
                f"(queued={queued:.0f})", deployment=st.name)
            st.info["num_replicas"] = desired
            st.status = "UPDATING"

    def _start_replica(self, st: _DeploymentState):
        info = st.info
        try:
            # Replicas serve queries concurrently up to the queries cap
            # (the reference replica is an asyncio actor).
            opts: Dict[str, Any] = {
                # The router already enforces max_concurrent_queries as
                # the in-flight cap; the replica needs only enough
                # executor threads for real parallelism — one OS thread
                # per queued query (100 threads x N replicas) starves
                # small hosts.
                "max_concurrency": min(
                    int(info.get("max_concurrent_queries") or 100), 16),
            }
            res = dict(info.get("ray_actor_options") or {})
            if "num_cpus" in res:
                opts["num_cpus"] = res["num_cpus"]
            if "num_tpus" in res:
                opts["num_tpus"] = res["num_tpus"]
            # Named + detached so a recovered controller can re-attach
            # live replicas instead of cold-starting the fleet.
            rname = f"SERVE_REPLICA::{st.name}::{uuid.uuid4().hex[:8]}"
            opts["name"] = rname
            opts["lifetime"] = "detached"
            r = ServeReplica.options(**opts).remote(
                st.name, info["cls"], info.get("init_args"),
                info.get("init_kwargs"), info.get("user_config"),
                st.version)
            st.replica_names[r] = rname
            return r
        except Exception:
            st.message = traceback.format_exc()
            return None

    def _stop_replica(self, replica):
        try:
            # Await the drain (bounded slightly above the replica's own
            # 10s in-flight wait): a fire-and-forget send would race the
            # kill below, skipping both the graceful drain and the
            # replica's teardown (request-loop stop + lag-sampler
            # component retirement).
            try:
                ray_tpu.get(replica.prepare_for_shutdown.remote(),
                            timeout=12.0)
            except Exception:
                pass
            ray_tpu.kill(replica)
        except Exception:
            pass

    def _broadcast(self, deployment: str, replicas: List[Any]):
        self._long_poll.notify_changed(f"replicas::{deployment}",
                                       list(replicas))


def get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        try:
            # max_restarts=-1: a crashed controller restarts in place,
            # re-runs __init__, and recovers from the KV checkpoint —
            # the reference's controller FT loop (serve/controller.py:70).
            return ServeController.options(
                name=CONTROLLER_NAME, lifetime="detached",
                max_concurrency=64, num_cpus=0,
                max_restarts=-1).remote()
        except ValueError:
            return ray_tpu.get_actor(CONTROLLER_NAME)


def resolve_live_controller(ping_timeout: float = 2.0):
    """The ONE controller-replacement probe the data plane shares
    (routers, proxies, long-poll clients): resolve the well-known name
    and prove liveness with a cheap ping. Returns a handle or None."""
    try:
        handle = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(handle.get_routes.remote(), timeout=ping_timeout)
        return handle
    except Exception:
        return None
