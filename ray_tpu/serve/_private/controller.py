"""ServeController: the reconciliation brain.

Reference: `serve/controller.py:70` + `_private/deployment_state.py:998` —
a detached singleton actor holding target state per deployment (replica
count, version, config) and a reconcile loop that starts/stops replica
actors to match, performs rolling updates on version change, health-checks
replicas, and drives autoscaling from router-reported queue metrics.
Membership changes broadcast to routers via the long-poll host.
"""

from __future__ import annotations

import hashlib
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve._private.long_poll import LongPollHost
from ray_tpu.serve._private.replica import ServeReplica

CONTROLLER_NAME = "SERVE_CONTROLLER"


def _version_hash(payload) -> str:
    import pickle

    try:
        blob = pickle.dumps(payload)
    except Exception:
        blob = repr(payload).encode()
    return hashlib.sha1(blob).hexdigest()[:12]


class _DeploymentState:
    def __init__(self, name: str, info: Dict[str, Any]):
        self.name = name
        self.info = info  # cls, init_args, init_kwargs, num_replicas, ...
        self.version = info["version"]
        self.replicas: List[Any] = []
        self.replica_versions: Dict[Any, str] = {}
        self.status = "UPDATING"
        self.message = ""


@ray_tpu.remote
class ServeController:
    def __init__(self):
        self._lock = threading.RLock()
        self._deployments: Dict[str, _DeploymentState] = {}
        self._long_poll = LongPollHost()
        self._metrics: Dict[str, Dict[str, float]] = {}
        # Route table: prefix -> deployment name. Proxy actors learn it
        # via the "routes" long-poll channel (reference: the
        # control->data-plane LongPollHost route updates).
        self._routes: Dict[str, str] = {}
        self._shutdown = threading.Event()
        self._reconciler = threading.Thread(target=self._reconcile_loop,
                                            daemon=True)
        self._reconciler.start()

    # -- routes (consumed by HTTPProxyActor fleet) -----------------------

    def set_route(self, prefix: str, deployment_name: str) -> bool:
        with self._lock:
            self._routes[prefix.rstrip("/") or "/"] = deployment_name
            snapshot = dict(self._routes)
        self._long_poll.notify_changed("routes", snapshot)
        return True

    def remove_route(self, prefix: str) -> bool:
        with self._lock:
            self._routes.pop(prefix.rstrip("/") or "/", None)
            snapshot = dict(self._routes)
        self._long_poll.notify_changed("routes", snapshot)
        return True

    def remove_routes_of(self, deployment_name: str) -> bool:
        """Drop every prefix routing to a deployment (serve.delete)."""
        with self._lock:
            for prefix in [p for p, d in self._routes.items()
                           if d == deployment_name]:
                del self._routes[prefix]
            snapshot = dict(self._routes)
        self._long_poll.notify_changed("routes", snapshot)
        return True

    def get_routes(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._routes)

    # -- API -------------------------------------------------------------

    def deploy(self, name: str, info: Dict[str, Any]) -> bool:
        info = dict(info)
        info["version"] = info.get("version") or _version_hash(
            (info.get("init_args"), info.get("init_kwargs"),
             info.get("user_config"), info.get("num_replicas")))
        with self._lock:
            existing = self._deployments.get(name)
            if existing is None:
                self._deployments[name] = _DeploymentState(name, info)
            else:
                existing.info = info
                existing.version = info["version"]
                existing.status = "UPDATING"
        from ray_tpu._private.events import record_event

        record_event("serve", f"deployment {name} deployed "
                     f"(version {info['version'][:8]})",
                     deployment=name)
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            state = self._deployments.pop(name, None)
        if state:
            for r in state.replicas:
                self._stop_replica(r)
            self._broadcast(name, [])
            from ray_tpu._private.events import record_event

            record_event("serve", f"deployment {name} deleted",
                         deployment=name)
        return True

    def get_deployment_info(self, name: str) -> Optional[dict]:
        with self._lock:
            st = self._deployments.get(name)
            if st is None:
                return None
            return {"name": name, "status": st.status,
                    "num_replicas": len(st.replicas),
                    "target_replicas": st.info.get("num_replicas", 1),
                    "version": st.version, "message": st.message}

    def list_deployments(self) -> List[str]:
        with self._lock:
            return list(self._deployments)

    def listen(self, key: str, known_version: int = -1):
        return self._long_poll.listen(key, known_version)

    def record_handle_metrics(self, deployment: str,
                              queued: float) -> bool:
        with self._lock:
            self._metrics.setdefault(deployment, {})["queued"] = queued
            self._metrics[deployment]["ts"] = time.monotonic()
        return True

    def graceful_shutdown(self) -> bool:
        self._shutdown.set()
        with self._lock:
            states = list(self._deployments.values())
            self._deployments.clear()
        for st in states:
            for r in st.replicas:
                self._stop_replica(r)
        return True

    # -- reconcile -------------------------------------------------------

    def _reconcile_loop(self):
        while not self._shutdown.is_set():
            try:
                self._reconcile_once()
            except Exception:
                traceback.print_exc()
            self._shutdown.wait(0.1)

    def _reconcile_once(self):
        with self._lock:
            states = list(self._deployments.values())
        for st in states:
            self._autoscale(st)
            target = int(st.info.get("num_replicas", 1))
            version = st.version
            changed = False
            # Rolling update: stop outdated replicas one at a time.
            outdated = [r for r in st.replicas
                        if st.replica_versions.get(r) != version]
            if outdated and len(st.replicas) >= target:
                victim = outdated[0]
                st.replicas.remove(victim)
                st.replica_versions.pop(victim, None)
                self._stop_replica(victim)
                changed = True
            while len(st.replicas) < target:
                r = self._start_replica(st)
                if r is None:
                    break
                st.replicas.append(r)
                st.replica_versions[r] = version
                changed = True
            while len(st.replicas) > target:
                victim = st.replicas.pop()
                st.replica_versions.pop(victim, None)
                self._stop_replica(victim)
                changed = True
            if changed or st.status == "UPDATING":
                up_to_date = all(st.replica_versions.get(r) == version
                                 for r in st.replicas)
                if len(st.replicas) == target and up_to_date:
                    st.status = "HEALTHY"
                self._broadcast(st.name, st.replicas)

    def _autoscale(self, st: _DeploymentState):
        cfg = st.info.get("autoscaling_config")
        if not cfg:
            return
        m = self._metrics.get(st.name)
        if not m:
            return
        # Routers report continuously while anything is queued or in
        # flight (Router._report_loop) and send a final 0 on drain, so
        # scale-down normally rides FRESH zero reports. The stale branch
        # is only the backstop for a vanished driver/router — generous
        # threshold so a mid-request deployment whose router hiccups is
        # never torn down under its callers.
        stale = time.monotonic() - m.get("ts", 0) > 30
        queued = 0.0 if stale else m["queued"]
        target_in_flight = cfg.get("target_num_ongoing_requests_per_replica",
                                   1.0)
        current = max(1, len(st.replicas))
        desired = queued / max(target_in_flight, 1e-6)
        desired = int(min(max(desired, cfg.get("min_replicas", 1)),
                          cfg.get("max_replicas", current)))
        if desired != st.info.get("num_replicas"):
            from ray_tpu._private.events import record_event

            record_event(
                "serve", f"autoscaling {st.name}: "
                f"{st.info.get('num_replicas')} -> {desired} replicas "
                f"(queued={queued:.0f})", deployment=st.name)
            st.info["num_replicas"] = desired
            st.status = "UPDATING"

    def _start_replica(self, st: _DeploymentState):
        info = st.info
        try:
            # Replicas serve queries concurrently up to the queries cap
            # (the reference replica is an asyncio actor).
            opts: Dict[str, Any] = {
                # The router already enforces max_concurrent_queries as
                # the in-flight cap; the replica needs only enough
                # executor threads for real parallelism — one OS thread
                # per queued query (100 threads x N replicas) starves
                # small hosts.
                "max_concurrency": min(
                    int(info.get("max_concurrent_queries") or 100), 16),
            }
            res = dict(info.get("ray_actor_options") or {})
            if "num_cpus" in res:
                opts["num_cpus"] = res["num_cpus"]
            if "num_tpus" in res:
                opts["num_tpus"] = res["num_tpus"]
            return ServeReplica.options(**opts).remote(
                st.name, info["cls"], info.get("init_args"),
                info.get("init_kwargs"), info.get("user_config"),
                st.version)
        except Exception:
            st.message = traceback.format_exc()
            return None

    def _stop_replica(self, replica):
        try:
            replica.prepare_for_shutdown.remote()
            ray_tpu.kill(replica)
        except Exception:
            pass

    def _broadcast(self, deployment: str, replicas: List[Any]):
        self._long_poll.notify_changed(f"replicas::{deployment}",
                                       list(replicas))


def get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        try:
            return ServeController.options(
                name=CONTROLLER_NAME, lifetime="detached",
                max_concurrency=64, num_cpus=0).remote()
        except ValueError:
            return ray_tpu.get_actor(CONTROLLER_NAME)
