"""ServeController: the reconciliation brain.

Reference: `serve/controller.py:70` + `_private/deployment_state.py:998` —
a detached singleton actor holding target state per deployment (replica
count, version, config) and a reconcile loop that starts/stops replica
actors to match, performs rolling updates on version change, health-checks
replicas, and drives autoscaling from router-reported queue metrics.
Membership changes broadcast to routers via the long-poll host.

Fault tolerance (reference `serve/_private/storage/kv_store.py:1` +
controller recovery in `serve/controller.py:70` ff.): every target-state
mutation checkpoints {deployments, routes, replica names} to the GCS
internal KV (durable when the head runs with gcs_storage_path). Replicas
are NAMED detached actors, so a restarted controller re-attaches the
live ones instead of cold-starting the fleet; dead ones are replaced by
the normal reconcile loop. While the controller is down, routers keep
answering from their last long-poll snapshot.
"""

from __future__ import annotations

import hashlib
import threading
import time
import traceback
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu._private import health as _health
from ray_tpu._private.config import ray_config
from ray_tpu.exceptions import ActorDiedError
from ray_tpu.serve._private.long_poll import LongPollHost
from ray_tpu.serve._private.replica import ServeReplica

CONTROLLER_NAME = "SERVE_CONTROLLER"
_CKPT_NS = b"__serve__"
_CKPT_KEY = b"controller_state"


def _version_hash(payload) -> str:
    import pickle

    try:
        blob = pickle.dumps(payload)
    except Exception:
        blob = repr(payload).encode()
    return hashlib.sha1(blob).hexdigest()[:12]


class _DeploymentState:
    def __init__(self, name: str, info: Dict[str, Any]):
        self.name = name
        self.info = info  # cls, init_args, init_kwargs, num_replicas, ...
        self.version = info["version"]
        self.replicas: List[Any] = []
        self.replica_versions: Dict[Any, str] = {}
        self.replica_names: Dict[Any, str] = {}  # handle -> actor name
        self.status = "UPDATING"
        self.message = ""
        # Replica supervision state: per-replica consecutive health-
        # check strikes, the in-flight (ping ref, sent_at) checked on
        # later passes, and the set of replicas whose LAST ping
        # answered ok (a degraded reason only clears once the
        # replacement fleet confirms).
        self.health_strikes: Dict[Any, int] = {}
        self.health_pings: Dict[Any, Any] = {}
        self.health_ok: set = set()
        self.last_health = 0.0
        # Burn-driven autoscaling hysteresis.
        self.last_burn_scale = 0.0
        # Cache-affinity digest channel state: the in-flight
        # prefix_digests() ref per replica (collected on later passes,
        # like health pings), the last committed doc per replica NAME
        # (what digests:: broadcasts), and the poll rate limiter.
        self.digest_pings: Dict[Any, Any] = {}
        self.digests: Dict[str, Any] = {}
        self.last_digest = 0.0

    def forget_replica(self, r) -> None:
        """Drop ALL supervision state for a replica leaving membership
        (rolling update, scale-down, health-detected death) — stale
        entries would otherwise accumulate one row (and a pending ping
        ref) per stopped replica for the controller's lifetime. The
        progress-heartbeat row keyed by the actor name goes with it."""
        rname = self.replica_names.pop(r, None)
        if rname:
            from ray_tpu.serve._private.replica import clear_progress

            clear_progress(rname)
        self.replica_versions.pop(r, None)
        self.health_strikes.pop(r, None)
        self.health_pings.pop(r, None)
        self.health_ok.discard(r)
        self.digest_pings.pop(r, None)
        if rname:
            self.digests.pop(rname, None)


@ray_tpu.remote
class ServeController:
    def __init__(self):
        self._lock = threading.RLock()
        # Serializes checkpoint snapshot+write so concurrent mutators
        # cannot commit out of order (a stale snapshot overwriting a
        # newer one would lose deployments across a crash).
        self._ckpt_lock = threading.Lock()
        self._deployments: Dict[str, _DeploymentState] = {}
        self._long_poll = LongPollHost()
        self._metrics: Dict[str, Dict[str, float]] = {}
        # Route table: prefix -> deployment name. Proxy actors learn it
        # via the "routes" long-poll channel (reference: the
        # control->data-plane LongPollHost route updates).
        self._routes: Dict[str, str] = {}
        self._shutdown = threading.Event()
        # Dead/degraded serve components, keyed by component id: the
        # /api/healthz provider reads the values, so a chaos kill is
        # NAMED while the fleet is degraded and the reason drops the
        # moment the deployment reconciles back to target.
        self._degraded: Dict[str, str] = {}
        # Burn-rate sampling for autoscaling is rate-limited (the
        # reconcile loop runs at 10Hz; sampling the SLO tracker that
        # often would grow its window history 10x for no signal).
        self._last_burn_sample = 0.0
        self._burn_cache: Dict[str, float] = {}
        _health.register_degraded_provider("serve", self._health_reasons)
        self._recover()
        self._reconciler = threading.Thread(target=self._reconcile_loop,
                                            daemon=True)
        self._reconciler.start()

    # -- checkpoint / recovery (reference serve kv_store.py) -------------

    def _kv(self):
        from ray_tpu._private.worker import global_worker

        return global_worker().gcs

    def _checkpoint(self):
        import cloudpickle

        with self._ckpt_lock:
            if self._shutdown.is_set():
                return  # never re-create the key after a wipe
            with self._lock:
                state = {
                    "routes": dict(self._routes),
                    "deployments": {
                        name: {
                            "info": st.info,
                            "replicas": [
                                (st.replica_names.get(r),
                                 st.replica_versions.get(r))
                                for r in st.replicas
                                if st.replica_names.get(r)
                            ],
                        }
                        for name, st in self._deployments.items()
                    },
                }
            try:
                self._kv().kv_put(_CKPT_KEY, cloudpickle.dumps(state),
                                  namespace=_CKPT_NS)
            except Exception:
                traceback.print_exc()

    def _recover(self):
        import cloudpickle

        try:
            blob = self._kv().kv_get(_CKPT_KEY, namespace=_CKPT_NS)
        except Exception:
            blob = None
        if not blob:
            return
        try:
            state = cloudpickle.loads(blob)
        except Exception:
            traceback.print_exc()
            return
        self._routes = dict(state.get("routes") or {})
        recovered_replicas = 0
        for name, d in (state.get("deployments") or {}).items():
            st = _DeploymentState(name, d["info"])
            # Re-attach live named replicas; dead/missing ones are
            # replaced by the first reconcile pass. An unreachable one
            # is best-effort KILLED, never silently skipped — skipping
            # would strand a detached actor (and its resources) forever.
            for rname, version in d.get("replicas") or []:
                h = None
                try:
                    h = ray_tpu.get_actor(rname)
                    ray_tpu.get(h.check_health.remote(), timeout=10.0)
                except Exception:
                    if h is not None:
                        try:
                            ray_tpu.kill(h)
                        except Exception:
                            pass
                    continue
                st.replicas.append(h)
                st.replica_versions[h] = version
                st.replica_names[h] = rname
                recovered_replicas += 1
            st.status = "UPDATING"
            self._deployments[name] = st
        for st in self._deployments.values():
            self._broadcast(st.name, st.replicas)
        self._long_poll.notify_changed("routes", dict(self._routes))
        if self._deployments:
            from ray_tpu._private.events import record_event

            record_event(
                "serve", "controller recovered "
                f"{len(self._deployments)} deployment(s), "
                f"{recovered_replicas} live replica(s) from checkpoint")

    # -- routes (consumed by HTTPProxyActor fleet) -----------------------

    def set_route(self, prefix: str, deployment_name: str) -> bool:
        with self._lock:
            self._routes[prefix.rstrip("/") or "/"] = deployment_name
            snapshot = dict(self._routes)
        self._long_poll.notify_changed("routes", snapshot)
        self._checkpoint()
        return True

    def remove_route(self, prefix: str) -> bool:
        with self._lock:
            self._routes.pop(prefix.rstrip("/") or "/", None)
            snapshot = dict(self._routes)
        self._long_poll.notify_changed("routes", snapshot)
        self._checkpoint()
        return True

    def remove_routes_of(self, deployment_name: str) -> bool:
        """Drop every prefix routing to a deployment (serve.delete)."""
        with self._lock:
            for prefix in [p for p, d in self._routes.items()
                           if d == deployment_name]:
                del self._routes[prefix]
            snapshot = dict(self._routes)
        self._long_poll.notify_changed("routes", snapshot)
        self._checkpoint()
        return True

    def get_routes(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._routes)

    # -- API -------------------------------------------------------------

    def deploy(self, name: str, info: Dict[str, Any]) -> bool:
        info = dict(info)
        info["version"] = info.get("version") or _version_hash(
            (info.get("init_args"), info.get("init_kwargs"),
             info.get("user_config"), info.get("num_replicas")))
        with self._lock:
            existing = self._deployments.get(name)
            if existing is None:
                self._deployments[name] = _DeploymentState(name, info)
            else:
                existing.info = info
                existing.version = info["version"]
                existing.status = "UPDATING"
        from ray_tpu._private.events import record_event

        record_event("serve", f"deployment {name} deployed "
                     f"(version {info['version'][:8]})",
                     deployment=name)
        self._checkpoint()
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            state = self._deployments.pop(name, None)
        if state:
            # Membership commits empty BEFORE the replicas die, so
            # routers and direct tables stop dispatching first.
            self._broadcast(name, [])
            for r in state.replicas:
                self._stop_replica(r)
            from ray_tpu._private.events import record_event

            record_event("serve", f"deployment {name} deleted",
                         deployment=name)
        self._checkpoint()
        return True

    def get_deployment_info(self, name: str) -> Optional[dict]:
        with self._lock:
            st = self._deployments.get(name)
            if st is None:
                return None
            return {"name": name, "status": st.status,
                    "num_replicas": len(st.replicas),
                    "target_replicas": st.info.get("num_replicas", 1),
                    "version": st.version, "message": st.message}

    def list_deployments(self) -> List[str]:
        with self._lock:
            return list(self._deployments)

    def listen(self, key: str, known_version: int = -1):
        return self._long_poll.listen(key, known_version)

    def record_handle_metrics(self, deployment: str,
                              queued: float) -> bool:
        with self._lock:
            self._metrics.setdefault(deployment, {})["queued"] = queued
            self._metrics[deployment]["ts"] = time.monotonic()
        return True

    def _health_reasons(self) -> List[str]:
        """The /api/healthz degraded-provider payload: every dead
        serve component this controller currently knows about."""
        with self._lock:
            return list(self._degraded.values())

    def graceful_shutdown(self) -> bool:
        self._shutdown.set()
        _health.unregister_degraded_provider("serve")
        # Release long-poll waiters FIRST: an in-flight listen would
        # otherwise hold an executor thread (and its client's get) in a
        # 30s condvar wait long after this actor is gone.
        self._long_poll.shutdown()
        # Let the in-flight reconcile pass finish before tearing down:
        # it could otherwise start a replica after we've iterated
        # st.replicas (a detached-actor leak) or re-write the
        # checkpoint after the wipe below.
        self._reconciler.join(timeout=10.0)
        with self._lock:
            states = list(self._deployments.values())
            self._deployments.clear()
            self._routes.clear()
        for st in states:
            for r in st.replicas:
                self._stop_replica(r)
        with self._ckpt_lock:  # flush any in-flight checkpoint write
            try:
                self._kv().kv_del(_CKPT_KEY, namespace=_CKPT_NS)
            except Exception:
                pass
        return True

    def _on_actor_stop(self):
        """Runtime abrupt-stop hook (`_Actor.stop`): fires on ANY stop
        — kill, crash-simulation, restart-in-place — where
        graceful_shutdown never ran. Retires the reconciler thread and
        releases parked long-poll listeners; without it a killed
        controller leaks both (threads outlive their thread-simulated
        'process')."""
        self._shutdown.set()
        _health.unregister_degraded_provider("serve")
        self._long_poll.shutdown()

    # -- reconcile -------------------------------------------------------

    def _reconcile_loop(self):
        while not self._shutdown.is_set():
            try:
                self._reconcile_once()
            except Exception:
                traceback.print_exc()
            self._shutdown.wait(0.1)

    def _reconcile_once(self):
        with self._lock:
            states = list(self._deployments.values())
        for st in states:
            self._check_replica_health(st)
            self._poll_digests(st)
            self._autoscale(st)
            target = int(st.info.get("num_replicas", 1))
            version = st.version
            changed = False
            # Victims are collected and stopped only AFTER their
            # removal broadcasts: the replica-direct tables (and
            # routers) must see the membership commit before the
            # replica dies, so steady-state dispatch never races a
            # planned stop (the raymc replica_direct property's
            # product-side discipline).
            stops: List[Any] = []
            # Rolling update: stop outdated replicas one at a time.
            outdated = [r for r in st.replicas
                        if st.replica_versions.get(r) != version]
            if outdated and len(st.replicas) >= target:
                victim = outdated[0]
                st.replicas.remove(victim)
                st.forget_replica(victim)
                stops.append(victim)
                changed = True
            while len(st.replicas) < target:
                r = self._start_replica(st)
                if r is None:
                    break
                st.replicas.append(r)
                st.replica_versions[r] = version
                changed = True
            while len(st.replicas) > target:
                victim = st.replicas.pop()
                st.forget_replica(victim)
                stops.append(victim)
                changed = True
            if changed or st.status == "UPDATING":
                up_to_date = all(st.replica_versions.get(r) == version
                                 for r in st.replicas)
                if len(st.replicas) == target and up_to_date:
                    st.status = "HEALTHY"
                self._broadcast(st.name, st.replicas)
            for victim in stops:
                self._stop_replica(victim)
            if changed:
                self._checkpoint()

    def _poll_digests(self, st: _DeploymentState):
        """Cache-affinity digest channel: collect each replica's hot
        prefix-head digests (``prefix_digests()``, answered by LLM
        deployments; None for everything else) and broadcast the
        per-replica-name snapshot on ``digests::<deployment>`` for the
        proxy fleet's replica-direct tables. Fire-and-collect like the
        health pings — the reconcile loop never blocks on a replica.
        Purely advisory: any failure leaves the last snapshot standing
        (the router degrades to least-loaded/round-robin)."""
        if not ray_config.llm_affinity_routing:
            return
        now = time.monotonic()
        if now - st.last_digest < ray_config.llm_digest_refresh_s:
            return
        st.last_digest = now
        changed = False
        for r in list(st.replicas):
            rname = st.replica_names.get(r)
            if not rname:
                continue
            prev = st.digest_pings.pop(r, None)
            if prev is not None:
                try:
                    ready, _ = ray_tpu.wait([prev], timeout=0)
                except Exception:
                    ready = []
                if not ready:
                    st.digest_pings[r] = prev  # still in flight
                    continue
                doc = None
                try:
                    doc = ray_tpu.get(prev, timeout=0.1)
                except Exception:
                    doc = None
                if doc != st.digests.get(rname):
                    if doc is None:
                        st.digests.pop(rname, None)
                    else:
                        st.digests[rname] = doc
                    changed = True
            try:
                st.digest_pings[r] = r.prefix_digests.remote()
            except Exception:
                pass
        live = {st.replica_names.get(r) for r in st.replicas}
        for rname in [n for n in st.digests if n not in live]:
            st.digests.pop(rname, None)
            changed = True
        if changed:
            self._long_poll.notify_changed(f"digests::{st.name}",
                                           dict(st.digests))

    def _check_replica_health(self, st: _DeploymentState):
        """Replica supervision: detect dead replicas and remove them
        from membership (broadcast FIRST), so the reconcile pass below
        replaces them — before this, a replica dying under a live
        controller stayed dead forever (only controller *recovery*
        re-checked liveness).

        Liveness is two-tier: (a) the named-actor registry — a DEAD
        replica's name is gone, definitive, instant; (b) a
        ``check_health`` ping collected on later passes — an
        ActorDiedError answer is death, a user-raised error is a
        strike, and a ping still pending past
        ``serve_replica_health_timeout_s`` is a strike too (the hung/
        deadlocked-replica detector — a merely BUSY replica serves the
        FIFO'd ping within one item's time, while a wedged one never
        does). ``serve_replica_health_failures`` consecutive strikes =
        dead; any successful ping resets the count.
        """
        now = time.monotonic()
        if now - st.last_health < ray_config.serve_replica_health_period_s:
            return
        st.last_health = now
        # Degraded-reason retirement: only once the fleet is back at
        # target AND every replica's last ping answered ok — clearing
        # on "replacement started" would close healthz's degraded
        # window before the replacement can actually serve.
        with self._lock:
            has_degraded = any(k.startswith(f"replica:{st.name}:")
                               for k in self._degraded)
        if has_degraded and st.status == "HEALTHY" and \
                len(st.replicas) >= int(st.info.get("num_replicas", 1)) \
                and all(r in st.health_ok for r in st.replicas):
            with self._lock:
                for key in [k for k in self._degraded
                            if k.startswith(f"replica:{st.name}:")]:
                    del self._degraded[key]
            from ray_tpu._private.events import record_event

            record_event("serve", f"deployment {st.name} recovered: "
                         f"all replicas confirm healthy",
                         deployment=st.name)
        dead: List[Any] = []
        for r in list(st.replicas):
            rname = st.replica_names.get(r)
            cause = ""
            if rname:
                try:
                    ray_tpu.get_actor(rname)
                except ValueError:
                    cause = "actor gone from the registry"
                except Exception:
                    pass
            if not cause:
                # Collect an earlier ping (never blocks: timeout 0).
                prev = st.health_pings.pop(r, None)
                resend = True
                if prev is not None:
                    ref, sent_at = prev
                    try:
                        ready, _ = ray_tpu.wait([ref], timeout=0)
                    except Exception:
                        ready = []
                    if ready:
                        try:
                            ray_tpu.get(ref, timeout=0.1)
                            st.health_strikes.pop(r, None)
                            st.health_ok.add(r)
                        except ActorDiedError as e:
                            cause = f"health ping failed: {e}"
                        except Exception as e:  # noqa: BLE001
                            strikes = st.health_strikes.get(r, 0) + 1
                            st.health_strikes[r] = strikes
                            if strikes >= \
                                    ray_config.serve_replica_health_failures:
                                cause = (f"{strikes} consecutive failed "
                                         f"health checks ({e})")
                    elif now - sent_at > \
                            ray_config.serve_replica_health_timeout_s:
                        # Unanswered past the timeout: hung-replica
                        # strike — but ONLY when the replica made no
                        # progress since the ping was sent. A
                        # SATURATED replica's ping queues behind a
                        # deep mailbox (admission caps exceed its
                        # execution slots by design) while requests
                        # keep completing; striking it would kill a
                        # healthy replica under exactly the load that
                        # needs it, and the replacement would saturate
                        # and be killed again — a kill loop. Progress
                        # stamps are process-local (replica.py); a
                        # remote replica with no visible stamp still
                        # strikes (conservative, same as pre-fix).
                        from ray_tpu.serve._private.replica import (
                            last_progress,
                        )

                        progressed = rname and \
                            (last_progress(rname) or 0.0) >= sent_at
                        st.health_pings[r] = prev
                        resend = False
                        if progressed:
                            st.health_strikes.pop(r, None)
                        else:
                            strikes = st.health_strikes.get(r, 0) + 1
                            st.health_strikes[r] = strikes
                            if strikes >= \
                                    ray_config.serve_replica_health_failures:
                                cause = (f"unresponsive: health ping "
                                         f"unanswered for "
                                         f"{now - sent_at:.1f}s with "
                                         f"no completed request since "
                                         f"({strikes} strikes)")
                    else:
                        # In flight, within the timeout: keep waiting.
                        st.health_pings[r] = prev
                        resend = False
                if resend and not cause:
                    try:
                        st.health_pings[r] = (r.check_health.remote(),
                                              now)
                    except Exception as e:  # noqa: BLE001
                        cause = f"health ping could not be sent: {e}"
            if cause:
                dead.append((r, rname, cause))
        if not dead:
            return
        for r, rname, cause in dead:
            if r in st.replicas:
                st.replicas.remove(r)
            st.forget_replica(r)
            # A strike-dead (wedged, not crashed) replica is still
            # alive: kill it so it cannot linger half-serving after
            # its removal broadcast (no-op for already-dead actors).
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
            with self._lock:
                self._degraded[f"replica:{st.name}:{rname}"] = (
                    f"serve_replica_dead: deployment {st.name} replica "
                    f"{rname or '(unnamed)'} removed ({cause}); "
                    f"{len(st.replicas)}/"
                    f"{int(st.info.get('num_replicas', 1))} live, "
                    f"replacing")
            from ray_tpu._private.events import record_event

            record_event("serve",
                         f"replica {rname} of {st.name} found dead "
                         f"({cause}); replacing", deployment=st.name)
        st.status = "UPDATING"
        # Removal commits to long-poll BEFORE any replacement work (or
        # the next dispatch): routers and replica-direct tables drop
        # the dead replica now.
        self._broadcast(st.name, st.replicas)
        self._checkpoint()

    def _route_burn(self, deployment: str) -> float:
        """Max short-window SLO burn over the deployment's routes —
        status-aware (PR 6), so proxy load-shed 503s push it up. The
        tracker sample is rate-limited to ~1/s across ALL deployments
        (the reconcile loop ticks at 10Hz)."""
        now = time.monotonic()
        if now - self._last_burn_sample >= 1.0:
            self._last_burn_sample = now
            try:
                _health.tracker.sample()
                rates = _health.tracker.burn_rates()
            except Exception:
                rates = {}
            with self._lock:
                routes = dict(self._routes)
            burns: Dict[str, float] = {}
            for route, windows in rates.items():
                dep = routes.get(route)
                if dep is None:
                    continue
                burn = float(windows.get("short", 0.0))
                if burn > burns.get(dep, 0.0):
                    burns[dep] = burn
            self._burn_cache = burns
        return self._burn_cache.get(deployment, 0.0)

    def _autoscale(self, st: _DeploymentState):
        cfg = st.info.get("autoscaling_config")
        if not cfg:
            return
        m = self._metrics.get(st.name)
        if not m:
            return
        # Routers report continuously while anything is queued or in
        # flight (Router._report_loop) and send a final 0 on drain, so
        # scale-down normally rides FRESH zero reports. The stale branch
        # is only the backstop for a vanished driver/router — generous
        # threshold so a mid-request deployment whose router hiccups is
        # never torn down under its callers.
        stale = time.monotonic() - m.get("ts", 0) > 30
        queued = 0.0 if stale else m["queued"]
        target_in_flight = cfg.get("target_num_ongoing_requests_per_replica",
                                   1.0)
        current = max(1, len(st.replicas))
        max_replicas = cfg.get("max_replicas", current)
        desired = queued / max(target_in_flight, 1e-6)
        desired = int(min(max(desired, cfg.get("min_replicas", 1)),
                          max_replicas))
        # SLO-burn input (closes the ROADMAP loop): a route burning its
        # error budget — status-aware, so the proxy's own load-shed
        # 503s count — scales UP one replica per cooldown even when
        # the queue signal reads low (e.g. requests being shed never
        # reach the router's queue metric), and a burning deployment
        # never scales DOWN under its callers.
        burn = 0.0
        burn_thr = float(ray_config.serve_autoscale_burn_threshold)
        if burn_thr > 0:
            burn = self._route_burn(st.name)
            if burn > burn_thr:
                desired = max(desired, len(st.replicas))
                now = time.monotonic()
                if desired < max_replicas and now - st.last_burn_scale \
                        >= ray_config.serve_autoscale_cooldown_s:
                    st.last_burn_scale = now
                    desired += 1
        if desired != st.info.get("num_replicas"):
            from ray_tpu._private.events import record_event

            record_event(
                "serve", f"autoscaling {st.name}: "
                f"{st.info.get('num_replicas')} -> {desired} replicas "
                f"(queued={queued:.0f}, burn={burn:.1f}x)",
                deployment=st.name)
            st.info["num_replicas"] = desired
            st.status = "UPDATING"

    def _start_replica(self, st: _DeploymentState):
        info = st.info
        try:
            # Replicas serve queries concurrently up to the queries cap
            # (the reference replica is an asyncio actor).
            opts: Dict[str, Any] = {
                # The router already enforces max_concurrent_queries as
                # the in-flight cap; the replica needs only enough
                # executor threads for real parallelism — one OS thread
                # per queued query (100 threads x N replicas) starves
                # small hosts.
                "max_concurrency": min(
                    int(info.get("max_concurrent_queries") or 100), 16),
            }
            res = dict(info.get("ray_actor_options") or {})
            if "num_cpus" in res:
                opts["num_cpus"] = res["num_cpus"]
            if "num_tpus" in res:
                opts["num_tpus"] = res["num_tpus"]
            # Named + detached so a recovered controller can re-attach
            # live replicas instead of cold-starting the fleet.
            rname = f"SERVE_REPLICA::{st.name}::{uuid.uuid4().hex[:8]}"
            opts["name"] = rname
            opts["lifetime"] = "detached"
            r = ServeReplica.options(**opts).remote(
                st.name, info["cls"], info.get("init_args"),
                info.get("init_kwargs"), info.get("user_config"),
                st.version, actor_name=rname)
            st.replica_names[r] = rname
            return r
        except Exception:
            st.message = traceback.format_exc()
            return None

    def _stop_replica(self, replica):
        try:
            # Await the drain (bounded slightly above the replica's own
            # 10s in-flight wait): a fire-and-forget send would race the
            # kill below, skipping both the graceful drain and the
            # replica's teardown (request-loop stop + lag-sampler
            # component retirement).
            try:
                ray_tpu.get(replica.prepare_for_shutdown.remote(),
                            timeout=12.0)
            except Exception:
                pass
            ray_tpu.kill(replica)
        except Exception:
            pass

    def _broadcast(self, deployment: str, replicas: List[Any]):
        self._long_poll.notify_changed(f"replicas::{deployment}",
                                       list(replicas))


def get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        try:
            # max_restarts=-1: a crashed controller restarts in place,
            # re-runs __init__, and recovers from the KV checkpoint —
            # the reference's controller FT loop (serve/controller.py:70).
            return ServeController.options(
                name=CONTROLLER_NAME, lifetime="detached",
                max_concurrency=64, num_cpus=0,
                max_restarts=-1).remote()
        except ValueError:
            return ray_tpu.get_actor(CONTROLLER_NAME)


def resolve_live_controller(ping_timeout: float = 2.0):
    """The ONE controller-replacement probe the data plane shares
    (routers, proxies, long-poll clients): resolve the well-known name
    and prove liveness with a cheap ping. Returns a handle or None."""
    try:
        handle = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(handle.get_routes.remote(), timeout=ping_timeout)
        return handle
    except Exception:
        return None
