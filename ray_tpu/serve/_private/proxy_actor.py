"""HTTPProxyActor: a proxy running as an actor, one (or more) per node.

Reference: `serve/_private/http_proxy.py:425` HTTPProxyActor +
`http_state.py` (the controller-managed proxy fleet) — each proxy serves
HTTP on its own process/port, learns the route table from the
controller's "routes" long-poll channel, and builds deployment handles
locally, so request traffic never passes through the driver. Place with
node-affinity / SPREAD options to front every node of a cluster.
"""

from __future__ import annotations

import threading
from typing import Dict

import ray_tpu
from ray_tpu.serve._private.http_proxy import HTTPProxy
from ray_tpu.serve._private.router import ServeHandle


@ray_tpu.remote
class HTTPProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_in_flight: int = 256,
                 queue_timeout_s: float = 15.0):
        from ray_tpu.serve._private.controller import (
            get_or_create_controller,
        )

        self._controller = get_or_create_controller()
        self._proxy = HTTPProxy(host, port, max_in_flight=max_in_flight,
                                queue_timeout_s=queue_timeout_s)
        self._handles: Dict[str, ServeHandle] = {}
        self._stop = threading.Event()
        self._sync(ray_tpu.get(self._controller.get_routes.remote()))
        self._thread = threading.Thread(target=self._route_loop,
                                        daemon=True, name="proxy-routes")
        self._thread.start()

    def _sync(self, routes: Dict[str, str]):
        for prefix, deployment in routes.items():
            handle = self._handles.get(deployment)
            if handle is None:
                handle = ServeHandle(self._controller, deployment)
                self._handles[deployment] = handle
            self._proxy.routes.set(prefix, handle)
        known = set(routes)
        for prefix in list(self._proxy.routes._routes):
            if prefix not in known:
                self._proxy.routes.remove(prefix)

    def _route_loop(self):
        version = -1
        while not self._stop.is_set():
            try:
                version, snapshot = ray_tpu.get(
                    self._controller.listen.remote("routes", version))
                if snapshot is not None:
                    self._sync(snapshot)
            except Exception:
                if self._stop.is_set():
                    return
                # Controller may have crashed: watch for a live
                # (replacement or restarted) controller and re-sync
                # from scratch; the last-known routes keep serving
                # meanwhile.
                from ray_tpu.serve._private.controller import (
                    resolve_live_controller,
                )

                new = resolve_live_controller()
                if new is not None:
                    self._controller = new
                    version = -1
                self._stop.wait(0.5)

    def address(self):
        return (self._proxy.host, self._proxy.port)

    def stats(self):
        """Ingress counters (in_flight, served, shed_503, open
        connections) — the fleet-level load/shedding signal."""
        return self._proxy.stats()

    def shutdown(self):
        self._stop.set()
        self._proxy.shutdown()
        return True


def start_proxy_fleet(num_proxies: int = 1, *, host: str = "127.0.0.1",
                      base_port: int = 0, spread: bool = True,
                      max_in_flight: int = 256,
                      queue_timeout_s: float = 15.0):
    """Start N proxy actors (SPREAD-scheduled across nodes when
    possible); returns [(actor_handle, (host, port)), ...]."""
    from ray_tpu.util.scheduling_strategies import (
        SpreadSchedulingStrategy,
    )

    actors = []
    for i in range(num_proxies):
        # Proxies restart indefinitely (the reference's http_state keeps
        # the fleet alive across node failures).
        opts = {"max_restarts": -1}
        if spread:
            opts["scheduling_strategy"] = SpreadSchedulingStrategy()
        port = base_port + i if base_port else 0
        a = HTTPProxyActor.options(**opts).remote(
            host, port, max_in_flight, queue_timeout_s)
        actors.append((a, ray_tpu.get(a.address.remote())))
    return actors
