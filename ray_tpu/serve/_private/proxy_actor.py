"""HTTPProxyActor: a proxy running as an actor, one (or more) per node.

Reference: `serve/_private/http_proxy.py:425` HTTPProxyActor +
`http_state.py` (the controller-managed proxy fleet) — each proxy serves
HTTP on its own process/port, learns the route table from the
controller's "routes" long-poll channel, and builds deployment handles
locally, so request traffic never passes through the driver. Place with
node-affinity / SPREAD options to front every node of a cluster.

:class:`ProxyFleet` is the supervised form (reference ``http_state``'s
proxy-state manager): proxies get STABLE explicit ports (a restarted
proxy rebinds the same address, so clients/LBs reconnect where they
were), a supervisor thread detects dead proxies, reports them into
``/api/healthz`` (named, while degraded), and restarts them in place.
"""

from __future__ import annotations

import socket
import threading
from typing import Dict, List, Tuple

import ray_tpu
from ray_tpu._private import health as _health
from ray_tpu._private.config import ray_config
from ray_tpu.serve._private.http_proxy import HTTPProxy
from ray_tpu.serve._private.router import ServeHandle


@ray_tpu.remote
class HTTPProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_in_flight: int = 256,
                 queue_timeout_s: float = 15.0):
        from ray_tpu.serve._private.controller import (
            get_or_create_controller,
        )

        self._controller = get_or_create_controller()
        self._proxy = HTTPProxy(host, port, max_in_flight=max_in_flight,
                                queue_timeout_s=queue_timeout_s)
        self._handles: Dict[str, ServeHandle] = {}
        self._stop = threading.Event()
        self._sync(ray_tpu.get(self._controller.get_routes.remote()))
        self._thread = threading.Thread(target=self._route_loop,
                                        daemon=True, name="proxy-routes")
        self._thread.start()

    def _sync(self, routes: Dict[str, str]):
        for prefix, deployment in routes.items():
            handle = self._handles.get(deployment)
            if handle is None:
                handle = ServeHandle(self._controller, deployment)
                self._handles[deployment] = handle
            self._proxy.routes.set(prefix, handle)
        known = set(routes)
        for prefix in list(self._proxy.routes._routes):
            if prefix not in known:
                self._proxy.routes.remove(prefix)

    def _route_loop(self):
        version = -1
        while not self._stop.is_set():
            try:
                version, snapshot = ray_tpu.get(
                    self._controller.listen.remote("routes", version))
                if snapshot is not None:
                    self._sync(snapshot)
            except Exception:
                if self._stop.is_set():
                    return
                # Controller may have crashed: watch for a live
                # (replacement or restarted) controller and re-sync
                # from scratch; the last-known routes keep serving
                # meanwhile.
                from ray_tpu.serve._private.controller import (
                    resolve_live_controller,
                )

                new = resolve_live_controller()
                if new is not None:
                    self._controller = new
                    version = -1
                self._stop.wait(0.5)

    def address(self):
        return (self._proxy.host, self._proxy.port)

    def stats(self):
        """Ingress counters (in_flight, served, shed_503, direct_served,
        open connections) — the fleet-level load/shedding signal."""
        return self._proxy.stats()

    def _teardown(self):
        self._stop.set()
        self._proxy.shutdown()
        # The deployment handles own routers + direct dispatchers with
        # membership subscriptions: release them so a restarted proxy
        # doesn't leave orphaned long-poll threads behind.
        for handle in self._handles.values():
            holder = getattr(handle, "_router_holder", {})
            router = holder.get("r")
            if router is not None:
                try:
                    router.shutdown()
                except Exception:
                    pass
            direct = holder.get("d")
            if direct is not None:
                try:
                    direct.shutdown()
                except Exception:
                    pass
        self._handles.clear()

    def _on_actor_stop(self):
        """Runtime abrupt-stop hook: a KILLED proxy (chaos, restart-in-
        place via max_restarts) must release its server socket and loop
        thread — otherwise the replacement's bind of the SAME port
        fails and the 'restart' dies in __init__."""
        self._teardown()

    def shutdown(self):
        self._teardown()
        return True


def _free_port(host: str) -> int:
    """Pick a currently-free TCP port. The tiny bind→close→rebind race
    is acceptable for fleet startup (a collision fails the proxy
    constructor loudly and the supervisor retries)."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


class ProxyFleet:
    """A supervised proxy fleet: N :class:`HTTPProxyActor`s on STABLE
    ports, restarted in place when they die, with deaths reported into
    ``/api/healthz`` while degraded.

    Each proxy builds its deployment handles locally and shares replica
    membership through the per-process long-poll watch
    (``membership.watch_replicas``) — membership changes fan out once
    per proxy process, and steady-state requests dispatch
    proxy→replica directly (``serve_replica_direct``).
    """

    def __init__(self, num_proxies: int = 2, *,
                 host: str = "127.0.0.1",
                 base_port: int = 0, spread: bool = True,
                 max_in_flight: int = 256,
                 queue_timeout_s: float = 15.0):
        self._host = host
        self._spread = spread
        self._max_in_flight = max_in_flight
        self._queue_timeout_s = queue_timeout_s
        self._lock = threading.Lock()
        self._degraded: Dict[int, str] = {}  # port -> reason
        self._restarts = 0
        # Stable explicit ports: a supervisor-restarted (or runtime-
        # restarted) proxy rebinds the address clients already hold.
        self._ports: List[int] = [
            base_port + i if base_port else _free_port(host)
            for i in range(num_proxies)]
        self._actors: Dict[int, object] = {}
        for port in self._ports:
            self._actors[port] = self._start_proxy(port)
        # Wait for every proxy to be serving before returning.
        for port, actor in self._actors.items():
            ray_tpu.get(actor.address.remote(), timeout=30)
        _health.register_degraded_provider(
            "serve_proxy_fleet", self._health_reasons)
        self._stop = threading.Event()
        self._supervisor = threading.Thread(
            target=self._supervise_loop, daemon=True,
            name="proxy-fleet-supervisor")
        self._supervisor.start()

    def _start_proxy(self, port: int):
        from ray_tpu.util.scheduling_strategies import (
            SpreadSchedulingStrategy,
        )

        opts: Dict[str, object] = {"max_restarts": -1}
        if self._spread:
            opts["scheduling_strategy"] = SpreadSchedulingStrategy()
        return HTTPProxyActor.options(**opts).remote(
            self._host, port, self._max_in_flight,
            self._queue_timeout_s)

    # -- supervision -----------------------------------------------------

    def _supervise_loop(self):
        period = ray_config.serve_proxy_supervise_period_s
        while not self._stop.wait(period):
            for port in list(self._ports):
                if self._stop.is_set():
                    return
                actor = self._actors.get(port)
                alive = False
                if actor is not None:
                    try:
                        ray_tpu.get(actor.address.remote(), timeout=2.0)
                        alive = True
                    except Exception:
                        alive = False
                if alive:
                    with self._lock:
                        self._degraded.pop(port, None)
                    continue
                # Name the dead proxy BEFORE attempting the restart:
                # healthz must tell the true story while degraded.
                with self._lock:
                    self._degraded[port] = (
                        f"serve_proxy_dead: proxy {self._host}:{port} "
                        f"unresponsive; restarting")
                try:
                    replacement = self._start_proxy(port)
                    ray_tpu.get(replacement.address.remote(),
                                timeout=10.0)
                except Exception:
                    continue  # port may still be draining: retry next tick
                with self._lock:
                    self._actors[port] = replacement
                    self._restarts += 1
                    # The degraded reason is NOT cleared here: the
                    # next supervision tick's successful ping of the
                    # replacement clears it — healthz stays degraded
                    # until the restarted proxy CONFIRMS serving on
                    # its port, never just "a restart was attempted".
                from ray_tpu._private.events import record_event

                record_event("serve", f"proxy fleet restarted proxy on "
                             f"{self._host}:{port}")

    def _health_reasons(self) -> List[str]:
        with self._lock:
            return list(self._degraded.values())

    # -- surface ---------------------------------------------------------

    def addresses(self) -> List[Tuple[str, int]]:
        return [(self._host, port) for port in self._ports]

    def actors(self) -> List[object]:
        with self._lock:
            return [self._actors[p] for p in self._ports
                    if p in self._actors]

    def stats(self) -> Dict[str, int]:
        """Summed ingress counters across the live fleet (dead proxies
        contribute nothing), plus fleet supervision counters."""
        out: Dict[str, int] = {"proxies": len(self._ports),
                               "restarts": self._restarts}
        for actor in self.actors():
            try:
                for k, v in ray_tpu.get(actor.stats.remote(),
                                        timeout=5.0).items():
                    out[k] = out.get(k, 0) + v
            except Exception:
                continue
        return out

    def shutdown(self):
        self._stop.set()
        _health.unregister_degraded_provider("serve_proxy_fleet")
        self._supervisor.join(timeout=5.0)
        for actor in self.actors():
            try:
                ray_tpu.get(actor.shutdown.remote(), timeout=10.0)
            except Exception:
                pass
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
        with self._lock:
            self._actors.clear()


def start_proxy_fleet(num_proxies: int = 1, *, host: str = "127.0.0.1",
                      base_port: int = 0, spread: bool = True,
                      max_in_flight: int = 256,
                      queue_timeout_s: float = 15.0):
    """Start N proxy actors (SPREAD-scheduled across nodes when
    possible); returns [(actor_handle, (host, port)), ...]. The
    list-of-pairs contract predates :class:`ProxyFleet` — new callers
    that want supervision/restart should hold a ``ProxyFleet``."""
    actors = []
    for i in range(num_proxies):
        from ray_tpu.util.scheduling_strategies import (
            SpreadSchedulingStrategy,
        )

        opts: Dict[str, object] = {"max_restarts": -1}
        if spread:
            opts["scheduling_strategy"] = SpreadSchedulingStrategy()
        port = base_port + i if base_port else 0
        a = HTTPProxyActor.options(**opts).remote(
            host, port, max_in_flight, queue_timeout_s)
        actors.append((a, ray_tpu.get(a.address.remote())))
    return actors
