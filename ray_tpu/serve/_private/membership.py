"""Shared replica membership + the replica-direct dispatch plane.

Reference: `serve/_private/long_poll.py` feeding `http_state.py` /
`router.py` — ONE long-poll subscription per (controller, deployment)
per process, fanned out to every consumer. Before this module each
``Router`` owned its own ``LongPollClient`` (N handles = N identical
long-poll streams); now membership changes arrive once per process and
fan out locally to:

- every ``Router`` of the deployment (the routed path's replica list);
- the deployment's :class:`ReplicaDirectTable` — the proxy fleet's
  steady-state fast path: a versioned membership + per-replica slot
  table the proxy dispatches through DIRECTLY (proxy→replica, no
  router lock, no per-request ref pruning, no head involvement),
  falling back to the routed path only on saturation, empty
  membership, or replica death.

Cache-invalidation rule (the one that matters for correctness): a
long-poll version bump REPLACES the table's membership atomically
under the table lock — an ``acquire`` that observes the new version
can never return a replica whose removal that version committed. The
raymc ``replica_direct`` scenario proves this (plus exact slot
accounting) over every bounded interleaving of the
``serve.direct.acquire`` / ``serve.direct.update`` /
``serve.direct.release`` seams.

:class:`ReplicaDirectTable` is a pure decision core in the
``tenancy.py`` / ``actor_gate.py`` discipline: locks and counters, no
RPC, no threads — the product wiring (long-poll thread, actor calls)
lives in :class:`DirectDispatcher` and the watch registry around it.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private import perf_stats as _perf_stats
from ray_tpu._private import sanitize_hooks

# Control-plane hops per dispatched request, the trace-plane proof that
# replica-direct steady state skips the router: the routed path crosses
# "router" once per dispatch, the fast path crosses "direct", and a
# direct dispatch that died under the caller and re-dispatched through
# the router crosses "fallback". ray_tpu_serve_hops_total{hop} after
# the runtime-metrics fold.
def hop_counter(hop: str):
    return _perf_stats.counter("serve_hops", {"hop": hop})


class DirectToken:
    """One claimed replica slot. ``release`` / ``invalidate`` consume
    it exactly once (idempotent — a double release must not free
    somebody else's slot)."""

    __slots__ = ("replica", "version", "consumed")

    def __init__(self, replica: Any, version: int):
        self.replica = replica
        self.version = version
        self.consumed = False


class ReplicaDirectTable:
    """Versioned replica membership + per-replica in-flight slots.

    Invariants (raymc ``replica_direct``):

    - an ``acquire`` never returns a replica absent from the CURRENT
      committed membership — once ``update(v)`` removing ``r`` returns,
      no later acquire yields ``r``;
    - per-replica slots never exceed ``cap`` and never go negative —
      releases of tokens for since-removed replicas are dropped, not
      miscounted against the replacement membership.
    """

    def __init__(self, cap: int):
        self._lock = threading.Lock()
        self.cap = max(1, int(cap))
        self.version = -1
        self._members: List[Any] = []
        self._slots: Dict[Any, int] = {}
        self._rr = 0
        # Cache-affinity hints: replica actor name -> prefix-digest doc
        # ({"seed", "block_tokens", "block_bytes", "keys", "model"}),
        # fed by the controller's digests:: long-poll channel. Purely
        # advisory — acquire() without an affinity hint (or with no
        # digests) keeps the round-robin contract the raymc
        # replica_direct scenario proves.
        self._digests: Dict[str, dict] = {}
        # Replicas a CALLER observed dead before long-poll caught up:
        # filtered out of every snapshot until a committed membership
        # no longer contains them (then the tombstone drops — the name
        # could in principle be reused).
        self._dead: set = set()

    def update(self, version: int, replicas) -> bool:
        """Commit a membership snapshot. Stale (<= current) versions
        are ignored — the long-poll channel delivers in order, but a
        racing manual refresh must never regress the table."""
        sanitize_hooks.sched_point("serve.direct.update")
        with self._lock:
            if version <= self.version:
                return False
            self.version = version
            members = [r for r in (replicas or []) if r not in self._dead]
            self._dead = {r for r in self._dead
                          if r in (replicas or [])}
            self._members = members
            # Slot rows of removed replicas drop with the membership:
            # their outstanding tokens release into the void (guarded
            # in release()), never against a replacement's accounting.
            self._slots = {r: self._slots.get(r, 0) for r in members}
            return True

    def set_digests(self, digests: Optional[Dict[str, dict]]) -> None:
        """Replace the affinity-hint table (controller broadcast). A
        malformed snapshot degrades to no hints, never to an error on
        the dispatch path."""
        if not isinstance(digests, dict):
            digests = {}
        with self._lock:
            self._digests = {str(k): v for k, v in digests.items()
                             if isinstance(v, dict)}

    @staticmethod
    def _affinity_order(members, slots, digests, affinity_tokens):
        """Reorder `members` by matched-prefix bytes against each
        replica's exported digest keys (desc), tie-broken by fewest
        held slots. Members without a positive score keep their
        round-robin relative order at the tail. Pure: called on
        SNAPSHOTS, outside the table lock."""
        from ray_tpu._private.kv_cache import chain_keys

        chains: Dict[tuple, list] = {}
        scored = []
        for pos, r in enumerate(members):
            doc = digests.get(str(getattr(r, "_actor_name", "")) or "")
            score = 0
            if doc:
                try:
                    bt = int(doc.get("block_tokens", 0))
                    seed = doc.get("seed", "")
                    keys = doc.get("keys") or ()
                    if bt > 0 and keys:
                        ck = (seed, bt)
                        chain = chains.get(ck)
                        if chain is None:
                            chain = chains[ck] = chain_keys(
                                affinity_tokens, bt, seed)
                        keyset = set(keys)
                        matched = 0
                        for key in chain:
                            if key not in keyset:
                                break
                            matched += 1
                        score = matched * int(doc.get("block_bytes", 1))
                except Exception:
                    score = 0
            scored.append((-score, slots.get(r, 0), pos, r))
        scored.sort(key=lambda t: t[:3])
        return [t[3] for t in scored], bool(scored and -scored[0][0] > 0)

    def acquire(self, extra_load=None,
                affinity_tokens=None) -> Optional[DirectToken]:
        """Claim one slot on a member with headroom (round-robin), or
        None when every member is at cap / membership is empty.

        ``extra_load(replica)`` is the ROUTED path's per-replica
        in-flight count (unpruned, so an overestimate — when in doubt
        the request routes, which is always correct): the two dispatch
        paths share one per-replica concurrency budget from both
        sides. It is called OUTSIDE the table lock; the claim re-checks
        membership under the lock, so a replica removed between the
        snapshot and the claim is skipped — the no-stale-dispatch
        property the raymc scenario proves.

        ``affinity_tokens`` (an LLM request's prompt head) reorders the
        candidates by matched-prefix bytes against each replica's
        exported digests — a prefix-cache hit skips the shared-head
        prefill, which dwarfs any load-skew cost. Capacity still wins:
        a scored replica at cap falls through to the next candidate."""
        with self._lock:
            members = list(self._members)
            start = self._rr
            self._rr += 1
            digests = dict(self._digests) if affinity_tokens else None
            slots_snap = dict(self._slots) if affinity_tokens else None
        # The yield point sits IN the race window: membership snapshot
        # taken, claim not yet committed — the interleaving raymc
        # orders an update's removal into (the under-lock containment
        # re-check below is what keeps the property true).
        sanitize_hooks.sched_point("serve.direct.acquire")
        n = len(members)
        order = [members[(start + i) % n] for i in range(n)]
        affine = False
        if affinity_tokens and digests:
            order, affine = self._affinity_order(
                order, slots_snap, digests, affinity_tokens)
        for idx, replica in enumerate(order):
            ext = extra_load(replica) if extra_load is not None else 0
            with self._lock:
                held = self._slots.get(replica)
                if held is None:
                    continue  # removed since the snapshot: never claim
                if held + ext < self.cap:
                    self._slots[replica] = held + 1
                    if affine:
                        _perf_stats.counter(
                            "serve_affinity_routed",
                            {"placed": "best" if idx == 0
                             else "spill"}).inc()
                    if affinity_tokens:
                        # Hit = the request landed on its best-scored
                        # cache-affine replica; anything else (no
                        # digest overlap, or the best replica was at
                        # cap and the claim spilled) is a miss the
                        # hit-rate panel should see.
                        hit = affine and idx == 0
                        _perf_stats.counter(
                            "serve_affinity_hits" if hit
                            else "serve_affinity_misses").inc()
                    return DirectToken(replica, self.version)
        if affinity_tokens:
            _perf_stats.counter("serve_affinity_misses").inc()
        return None

    def release(self, token: Optional[DirectToken]) -> None:
        if token is None or token.consumed:
            return
        token.consumed = True
        sanitize_hooks.sched_point("serve.direct.release")
        with self._lock:
            held = self._slots.get(token.replica)
            if held is not None and held > 0:
                self._slots[token.replica] = held - 1
            # else: the replica left membership while the token was
            # out — its row is gone and stays gone.

    def invalidate(self, token: Optional[DirectToken]) -> None:
        """A dispatch through ``token`` failed with replica death: drop
        the replica from membership NOW (long-poll will confirm) and
        release the slot."""
        if token is None:
            return
        with self._lock:
            replica = token.replica
            if replica in self._slots:
                self._members = [r for r in self._members
                                 if r is not replica]
                self._slots.pop(replica, None)
            self._dead.add(replica)
        token.consumed = True

    def slots_of(self, replica: Any) -> int:
        """Direct-path in-flight for one replica — the router adds this
        to its own accounting so the per-replica cap spans BOTH
        dispatch paths."""
        with self._lock:
            return self._slots.get(replica, 0)

    def total_in_flight(self) -> int:
        """All direct-path in-flight — folded into the router's
        autoscaling report so a fleet serving entirely via the fast
        path still pressures the controller's queue signal (without
        this the autoscaler reads ~0 and scales a loaded fleet down)."""
        with self._lock:
            return sum(self._slots.values())

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"version": self.version,
                    "members": len(self._members),
                    "in_flight": sum(self._slots.values())}


# -- shared long-poll membership watches -------------------------------------


class _SubEntry:
    """Per-subscriber delivery state: monotonic in seq, so a
    subscribe-time replay racing a live delivery can never regress the
    subscriber to an older snapshot."""

    __slots__ = ("cb", "seq", "lock")

    def __init__(self, cb: Callable):
        self.cb = cb
        self.seq = -1
        self.lock = threading.Lock()

    def deliver(self, seq: int, snapshot) -> None:
        with self.lock:
            if seq <= self.seq:
                return
            self.seq = seq
            try:
                self.cb(seq, snapshot)
            except Exception:
                pass


class _DeploymentWatch:
    """One long-poll subscription per (controller, channel) in this
    process; subscribers (routers, direct tables) get every snapshot —
    and the latest one immediately on subscribe."""

    def __init__(self, key, controller, channel: str):
        from ray_tpu.serve._private.long_poll import LongPollClient

        self._key = key
        self._channel = channel
        self._controller = controller
        self._lock = threading.Lock()
        self._subs: List[_SubEntry] = []
        self._controller_subs: List[Callable] = []
        self._last = None
        self._seq = 0  # local commit counter: the table's version feed
        self._stopped = False  # set by retire; subscribe refuses after
        self._client = LongPollClient(
            controller, channel, self._on_change,
            reresolve=self._reresolve)

    def _reresolve(self):
        from ray_tpu.serve._private.controller import (
            resolve_live_controller,
        )

        handle = resolve_live_controller()
        if handle is not None:
            with self._lock:
                self._controller = handle
                listeners = list(self._controller_subs)
            # Consumers that talk to the controller themselves (router
            # metrics reports) retarget to the replacement.
            for cb in listeners:
                try:
                    cb(handle)
                except Exception:
                    pass
        return handle

    def _on_change(self, snapshot):
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._last = (seq, snapshot)
            subs = list(self._subs)
        for entry in subs:
            entry.deliver(seq, snapshot)

    def subscribe(self, cb: Callable, on_controller: Optional[Callable]
                  = None) -> Optional["_Subscription"]:
        """None when this watch lost a race with its retirement (the
        last unsubscribe stopped the long-poll stream between the
        registry lookup and this call) — the caller creates a fresh
        watch instead of riding a stopped stream forever."""
        entry = _SubEntry(cb)
        with self._lock:
            if self._stopped:
                return None
            self._subs.append(entry)
            if on_controller is not None:
                self._controller_subs.append(on_controller)
            last = self._last
        if last is not None:
            entry.deliver(*last)
        return _Subscription(self, entry, on_controller)

    def _unsubscribe(self, entry, on_controller) -> bool:
        """Returns True when this was the last subscriber (the caller
        retires the watch)."""
        with self._lock:
            if entry in self._subs:
                self._subs.remove(entry)
            if on_controller is not None and \
                    on_controller in self._controller_subs:
                self._controller_subs.remove(on_controller)
            return not self._subs

    def stop(self):
        self._client.stop()


class _Subscription:
    __slots__ = ("_watch", "_entry", "_on_controller", "_done")

    def __init__(self, watch, entry, on_controller):
        self._watch = watch
        self._entry = entry
        self._on_controller = on_controller
        self._done = False

    def unsubscribe(self):
        if self._done:
            return
        self._done = True
        if self._watch._unsubscribe(self._entry, self._on_controller):
            _retire_watch(self._watch)


_WATCH_LOCK = threading.Lock()
_WATCHES: Dict[Any, _DeploymentWatch] = {}


def _controller_key(controller) -> Any:
    aid = getattr(controller, "_actor_id", None)
    return aid.binary() if aid is not None else id(controller)


def watch_channel(controller, channel: str, cb: Callable,
                  on_controller: Optional[Callable] = None
                  ) -> _Subscription:
    """Subscribe ``cb(seq, snapshot)`` to any controller long-poll
    channel, sharing one stream per (controller, channel) in this
    process. The last unsubscribe stops the stream; a subscriber
    racing that retirement retries against a fresh watch (subscribe on
    a stopped watch returns None, never a dead subscription)."""
    key = (_controller_key(controller), channel)
    while True:
        with _WATCH_LOCK:
            watch = _WATCHES.get(key)
            if watch is None:
                watch = _WATCHES[key] = _DeploymentWatch(
                    key, controller, channel)
        sub = watch.subscribe(cb, on_controller)
        if sub is not None:
            return sub
        # Lost the race with _retire_watch: drop the stopped watch
        # from the registry ourselves (the retiring thread may not
        # have reached its delete yet) so the next iteration builds a
        # fresh one instead of spinning on the corpse.
        with _WATCH_LOCK:
            if _WATCHES.get(key) is watch:
                del _WATCHES[key]


def watch_replicas(controller, deployment: str, cb: Callable,
                   on_controller: Optional[Callable] = None
                   ) -> _Subscription:
    """Subscribe ``cb(seq, replicas)`` to the deployment's membership
    channel (see :func:`watch_channel`)."""
    return watch_channel(controller, f"replicas::{deployment}", cb,
                         on_controller)


def _retire_watch(watch: _DeploymentWatch) -> None:
    # Commit the stop under the WATCH lock, re-checking for a
    # subscriber that slipped in after the last unsubscribe: either
    # the late subscriber lands first (subs non-empty — the watch
    # stays live) or the stop commits first (the late subscriber's
    # subscribe() sees _stopped and retries on a fresh watch). No
    # interleaving leaves a subscriber on a stopped stream.
    with watch._lock:
        if watch._subs:
            return
        watch._stopped = True
    with _WATCH_LOCK:
        if _WATCHES.get(watch._key) is watch:
            del _WATCHES[watch._key]
    watch.stop()


def shutdown_all_watches() -> None:
    """Stop every membership stream (serve.shutdown's safety net for
    watches whose subscribers never unsubscribed)."""
    with _WATCH_LOCK:
        watches = list(_WATCHES.values())
        _WATCHES.clear()
    for watch in watches:
        watch.stop()


# -- the dispatcher (product wiring around the table) ------------------------


# Live dispatchers, for serve.shutdown (weak: handles are GC'd freely).
_DISPATCHERS: "weakref.WeakSet[DirectDispatcher]" = weakref.WeakSet()


def shutdown_all_dispatchers() -> None:
    for d in list(_DISPATCHERS):
        try:
            d.shutdown()
        except Exception:
            pass


class DirectDispatcher:
    """Replica-direct dispatch for one deployment: claim a slot in the
    shared table, fire the actor call with the request's ambient
    trace/job context, and hand the caller a token to release (or
    invalidate) on completion. The routed path stays the fallback for
    saturation, cold tables, and replica death."""

    def __init__(self, controller, deployment: str, cap: int):
        self._deployment = deployment
        self.table = ReplicaDirectTable(cap)
        # The routed path's per-replica in-flight probe (set when the
        # deployment's Router exists): both paths see each other's
        # load, so neither can oversubscribe a replica the other
        # saturated.
        self._router_load = None
        self._sub = watch_replicas(controller, deployment,
                                   self.table.update)
        # Cache-affinity hints ride their own channel (hot prefix
        # digests change far more often than membership — versioning
        # them through update() would churn the slot table).
        self._dig_sub = watch_channel(
            controller, f"digests::{deployment}",
            lambda _seq, snap: self.table.set_digests(snap))
        _DISPATCHERS.add(self)

    @staticmethod
    def _affinity_hint(args: tuple, kwargs: dict):
        """An LLM request's prompt head (the affinity key), or None for
        non-LLM payloads. Sniffed, not schema'd: the dispatcher serves
        arbitrary deployments and must never fail on shape."""
        from ray_tpu._private.config import ray_config

        if not ray_config.llm_affinity_routing:
            return None
        payload = args[0] if args else kwargs.get("request")
        if not isinstance(payload, dict):
            return None
        toks = payload.get("prompt_ids")
        if not isinstance(toks, (list, tuple)) or not toks:
            return None
        # The digest match only needs the head; hashing a megaprompt
        # per candidate scoring pass would tax the dispatch path.
        return list(toks[:512])

    def set_router_load(self, fn) -> None:
        self._router_load = fn

    def dispatch(self, method: str, args: tuple, kwargs: dict,
                 trace=None, job=None):
        """(ref, token) on success, (None, None) when the table has no
        free member (caller falls back to the routed path)."""
        from ray_tpu._private import critical_path
        from ray_tpu._private.task_spec import (set_ambient_job_id,
                                                set_ambient_trace_parent)

        t_acquire = time.monotonic()
        token = self.table.acquire(
            extra_load=self._router_load,
            affinity_tokens=self._affinity_hint(args, kwargs))
        if token is None:
            return None, None
        # Stage span: slot claim incl. the affinity-scoring pass (the
        # dispatch RPC below is charged to the proxy's dispatch stage).
        critical_path.record_stage(
            trace[0] if trace else None, "direct.acquire",
            time.monotonic() - t_acquire)
        try:
            prev = set_ambient_trace_parent(trace) \
                if trace is not None else None
            prev_job = set_ambient_job_id(job) if job is not None else None
            try:
                ref = token.replica.handle_request.remote(
                    method, args, kwargs)
            finally:
                if trace is not None:
                    set_ambient_trace_parent(prev)
                if job is not None:
                    set_ambient_job_id(prev_job)
        except BaseException:
            self.table.release(token)
            raise
        hop_counter("direct").inc()
        return ref, token

    def release(self, token) -> None:
        self.table.release(token)

    def invalidate(self, token) -> None:
        """Caller observed the token's replica die: drop it from the
        table ahead of the long-poll confirmation."""
        _perf_stats.counter(
            "serve_direct_invalidations",
            {"deployment": self._deployment}).inc()
        self.table.invalidate(token)

    def shutdown(self) -> None:
        self._sub.unsubscribe()
        self._dig_sub.unsubscribe()
