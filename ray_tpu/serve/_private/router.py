"""Router + ServeHandle: the data plane.

Reference: `serve/_private/router.py:263` (`assign_replica :224` —
round-robin skipping replicas at `max_concurrent_queries`) and
`serve/handle.py`. Replica membership arrives via long-poll; in-flight
refs are tracked per replica so the cap is enforced client-side.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref
from typing import Any, Dict, List

# Every live Router (weakly held: handles are GC'd freely). Routers own
# two daemon threads each (metrics reporter + long-poll listener), and
# ServeHandles are minted ad hoc — by drivers, replicas, deployment
# graphs — with nothing above them tracking lifetime, so
# ``serve.shutdown()`` sweeps this registry to take the threads back
# down (the leak sanitizer caught them outliving every serve test).
_ROUTERS: "weakref.WeakSet[Router]" = weakref.WeakSet()


def shutdown_all_routers() -> None:
    """Stop every live router's reporter/long-poll threads. Called by
    ``serve.shutdown()`` BEFORE the controller is killed: stop flags
    are set here, then the controller's death errors any in-flight
    long-poll listen, so both threads exit promptly instead of timing
    out a 30s poll."""
    for router in list(_ROUTERS):
        try:
            router.shutdown()
        except Exception:
            pass

import ray_tpu
from ray_tpu._private import critical_path
from ray_tpu._private import sanitize_hooks
from ray_tpu._private import tenancy
from ray_tpu._private.config import ray_config
from ray_tpu._private.task_spec import (set_ambient_job_id,
                                        set_ambient_trace_parent)
from ray_tpu.serve._private import membership


class QueueSaturatedError(TimeoutError):
    """No replica slot freed within the queue timeout. A TimeoutError
    subclass for caller compatibility, but distinguishable from a
    TimeoutError raised BY a deployment — the proxy maps only THIS to
    503 load-shedding; application timeouts stay 500s."""


class Router:
    def __init__(self, controller, deployment_name: str,
                 max_concurrent_queries: int = 100, external_load=None):
        self._controller = controller
        self._deployment = deployment_name
        self._max_concurrent = max_concurrent_queries
        self._replicas: List[Any] = []
        self._rr = itertools.count()
        self._in_flight: Dict[Any, List] = {}
        # Slots claimed under the lock but whose dispatch RPC is still
        # being sent OUTSIDE it (see _try_assign): counted against the
        # per-replica cap so concurrent dispatchers can't oversubscribe
        # a replica while a send is in flight.
        self._reserved: Dict[Any, int] = {}
        # Per-replica in-flight the router did NOT dispatch (the
        # replica-direct fast path's slot table): counted against the
        # cap so the routed fallback cannot oversubscribe a replica the
        # direct path already saturated. ``_external_total`` is the
        # table's whole in-flight count, folded into the autoscaling
        # report (direct traffic must pressure the queue signal).
        self._external_load = external_load
        self._external_total = None
        self._lock = threading.Condition()
        # Per-job weighted fair arbitration over contended replica
        # slots (tenancy enforcement): when requests of several jobs
        # wait for a slot, the job with the smallest virtual time
        # dispatches next — a flood job saturates only its weight
        # share. No-op (one lock read) when enforcement is off.
        self._fair = tenancy.FairShare()
        # Shared per-process membership stream: one long-poll client
        # per (controller, deployment) feeds every router AND the
        # replica-direct table — membership changes fan out once.
        self._watch_sub = membership.watch_replicas(
            controller, deployment_name,
            lambda _seq, snapshot: self._update_replicas(snapshot),
            on_controller=self._set_controller)
        self._last_report = 0.0
        self._waiting = 0  # callers blocked on a free replica slot
        # Periodic reporter: long-running requests dispatch once and then
        # produce no assign_request traffic, which would let the metric
        # go stale while replicas are mid-request (the controller reads
        # stale as idle). Reports continue while anything is in flight
        # and send one final 0 when drained.
        self._reporter_stop = threading.Event()
        self._reporter = threading.Thread(
            target=self._report_loop, daemon=True,
            name=f"router-metrics-{deployment_name}")
        self._reporter.start()
        _ROUTERS.add(self)

    def _set_controller(self, handle):
        """Controller replacement found by the shared watch's reresolve:
        swap the metrics-report target so autoscaling signals resume."""
        self._controller = handle

    def set_external_load(self, fn, total=None) -> None:
        """Late cross-wiring (direct dispatcher created after this
        router — e.g. serve_replica_direct flipped on live)."""
        self._external_load = fn
        self._external_total = total

    def _update_replicas(self, replicas):
        with self._lock:
            self._replicas = list(replicas or [])
            for r in self._replicas:
                self._in_flight.setdefault(r, [])
            self._lock.notify_all()

    def discard_replica(self, replica) -> None:
        """A caller observed this replica die (ActorDiedError) before
        the membership broadcast caught up: stop round-robining onto
        it now. The next long-poll snapshot replaces the list
        wholesale either way."""
        with self._lock:
            if replica in self._replicas:
                self._replicas = [r for r in self._replicas
                                  if r is not replica]

    def _prune(self, replica) -> int:
        refs = self._in_flight.get(replica, [])
        if refs:
            _, not_ready = ray_tpu.wait(refs, num_returns=len(refs),
                                        timeout=0)
            self._in_flight[replica] = list(not_ready)
        return len(self._in_flight.get(replica, []))

    def replica_load(self, replica) -> int:
        """Routed-path in-flight for one replica, UNPRUNED (no
        ray_tpu.wait on the direct fast path): an overestimate only
        makes the direct table decline and the request take the routed
        path, which prunes and decides exactly. Stale refs decay within
        a reporter tick (~1s) or the next routed dispatch attempt."""
        with self._lock:
            return len(self._in_flight.get(replica, ())) \
                + self._reserved.get(replica, 0)

    def _try_assign(self, method: str, args: tuple, kwargs: dict,
                    trace=None, job=None):
        """One round-robin dispatch attempt; returns the ref or None if
        every replica is at its in-flight cap. On success the waiting
        count drops under the SAME lock hold as the slot accounting —
        counting a request as both waiting and in-flight would double
        it in the autoscaling signal.

        The dispatch RPC itself runs OUTSIDE the lock (raylint R2: a
        `.remote()` submission can stall on batcher backpressure, and
        the router lock serializes every other dispatcher). The slot is
        claimed under the lock via ``_reserved`` first, so the cap
        stays exact while the send is in flight.

        ``trace`` is the request's (trace_id, parent_span_id): it rides
        the dispatching thread's ambient trace context so the replica's
        actor task — and every task the replica then submits — joins
        the HTTP request's trace. ``job`` rides the ambient job tag the
        same way: the replica call's spec carries it, so one tenant's
        serve traffic stays attributable through the tasks it fans
        into."""
        # WFQ turn gate: under contention only the minimum-virtual-time
        # job may claim the next slot (the fast path with no waiters
        # always passes). Sits BEFORE any slot probing so a flood job's
        # requests cannot race a freed slot away from a higher-weight
        # tenant parked for it.
        if not self._fair.may_dispatch(job or ""):
            return None
        with self._lock:
            replicas = list(self._replicas)
        if not replicas:
            return None
        n = len(replicas)
        start = next(self._rr)
        for i in range(n):
            replica = replicas[(start + i) % n]
            # Direct-path load read OUTSIDE the router lock (the table
            # has its own leaf lock; nesting the two would add a lock
            # order for no benefit — a slightly stale count only shifts
            # which replica this dispatch probes).
            ext = self._external_load(replica) \
                if self._external_load is not None else 0
            with self._lock:
                load = self._prune(replica) \
                    + self._reserved.get(replica, 0) + ext
                if load >= self._max_concurrent:
                    continue
                self._reserved[replica] = \
                    self._reserved.get(replica, 0) + 1
            dispatched = False
            try:
                prev = set_ambient_trace_parent(trace) \
                    if trace is not None else None
                prev_job = set_ambient_job_id(job) \
                    if job is not None else None
                try:
                    ref = replica.handle_request.remote(
                        method, args, kwargs)
                finally:
                    if trace is not None:
                        set_ambient_trace_parent(prev)
                    if job is not None:
                        set_ambient_job_id(prev_job)
                dispatched = True
            finally:
                # Reserved→in-flight handoff under ONE hold: a gap
                # between the decrement and the append would leave the
                # dispatched request counted by neither, letting a
                # concurrent dispatcher oversubscribe the cap. The
                # yield point marks the handoff boundary for the
                # deterministic-schedule harness: raysan's regression
                # fixture parks a dispatcher here and proves a
                # concurrent one still sees the reserved slot.
                sanitize_hooks.sched_point("router.handoff")
                with self._lock:
                    self._reserved[replica] -= 1
                    if dispatched:
                        self._in_flight.setdefault(
                            replica, []).append(ref)
                        self._waiting -= 1
                        total = self._pending_report_locked()
            if dispatched:
                # Advance the job's virtual time: its next contended
                # turn moves back by 1/weight.
                self._fair.charge(job or "")
                # Trace-plane hop accounting: this request paid a
                # router hop (the replica-direct A/B reads the ratio).
                membership.hop_counter("router").inc()
            self._send_report(total)
            return ref
        return None

    def assign_request(self, method: str, args: tuple, kwargs: dict,
                       timeout: float = 30.0, trace=None, job=None):
        t_enter = time.monotonic()
        deadline = t_enter + timeout
        dispatched = False
        with self._lock:
            self._waiting += 1
        # Fair-share wait registration: while parked, this job's
        # virtual time competes for the next freed slot.
        self._fair.enter_wait(job or "")
        try:
            while True:
                ref = self._try_assign(method, args, kwargs, trace, job)
                if ref is not None:
                    dispatched = True
                    critical_path.record_stage(
                        trace[0] if trace else None, "router.assign",
                        time.monotonic() - t_enter)
                    return ref
                if time.monotonic() > deadline:
                    raise QueueSaturatedError(
                        f"no replica available for {self._deployment} "
                        f"within {timeout}s")
                # Saturated: no dispatch happens, but pressure must
                # still reach the autoscaler — waiting requests ARE the
                # scale-up signal (reference: handle queue metrics count
                # queued + ongoing, `_private/autoscaling_metrics.py`).
                with self._lock:
                    total = self._pending_report_locked()
                self._send_report(total)
                time.sleep(0.005)
        finally:
            self._fair.exit_wait(job or "")
            if not dispatched:
                with self._lock:
                    self._waiting -= 1

    def try_assign_request(self, method: str, args: tuple,
                           kwargs: dict, trace=None, job=None):
        """Non-blocking dispatch: the ref if a replica slot is free
        right now, else None. The event-loop proxy's fast path — no
        coroutine, no parking; saturation falls back to
        :meth:`assign_request_async`."""
        t_enter = time.monotonic()
        with self._lock:
            self._waiting += 1
        ref = self._try_assign(method, args, kwargs, trace, job)
        if ref is None:
            with self._lock:
                self._waiting -= 1
        else:
            critical_path.record_stage(
                trace[0] if trace else None, "router.assign",
                time.monotonic() - t_enter)
        return ref

    async def assign_request_async(self, method: str, args: tuple,
                                   kwargs: dict, timeout: float = 30.0,
                                   trace=None, job=None):
        """Event-loop completion path (the asyncio HTTP proxy's bridge):
        identical dispatch and autoscaling accounting to
        :meth:`assign_request`, but saturation parks the coroutine with
        ``await asyncio.sleep`` instead of blocking the loop thread."""
        import asyncio

        t_enter = time.monotonic()
        deadline = t_enter + timeout
        dispatched = False
        with self._lock:  # raylint: disable=R1 -- microsecond critical section guarding state shared with sync dispatch threads; an asyncio.Lock cannot serialize against them
            self._waiting += 1
        self._fair.enter_wait(job or "")
        try:
            while True:
                ref = self._try_assign(method, args, kwargs, trace, job)
                if ref is not None:
                    dispatched = True
                    critical_path.record_stage(
                        trace[0] if trace else None, "router.assign",
                        time.monotonic() - t_enter)
                    return ref
                if time.monotonic() > deadline:
                    raise QueueSaturatedError(
                        f"no replica available for {self._deployment} "
                        f"within {timeout}s")
                with self._lock:  # raylint: disable=R1 -- microsecond critical section guarding state shared with sync dispatch threads; an asyncio.Lock cannot serialize against them
                    total = self._pending_report_locked()
                self._send_report(total)
                await asyncio.sleep(0.002)
        finally:
            self._fair.exit_wait(job or "")
            if not dispatched:
                with self._lock:  # raylint: disable=R1 -- microsecond critical section guarding state shared with sync dispatch threads; an asyncio.Lock cannot serialize against them
                    self._waiting -= 1

    def _pending_report_locked(self):
        """Under the lock: the metric total to ship, or None inside the
        rate-limit window. The RPC itself (`_send_report`) happens with
        the lock RELEASED — a slow/backpressured controller send must
        never stall request dispatch (raylint R2)."""
        now = time.monotonic()
        if now - self._last_report < 0.5:
            return None
        self._last_report = now
        ext = 0
        if self._external_total is not None:
            try:
                ext = int(self._external_total())
            except Exception:
                ext = 0
        return float(sum(len(v) for v in self._in_flight.values())
                     + self._waiting + ext)

    def _send_report(self, total):
        if total is None:
            return
        try:
            self._controller.record_handle_metrics.remote(
                self._deployment, total)
        except Exception:
            pass

    def _report_loop(self):
        was_busy = False
        while not self._reporter_stop.wait(1.0):
            total = None
            ext_busy = False
            if self._external_total is not None:
                try:
                    ext_busy = self._external_total() > 0
                except Exception:
                    ext_busy = False
            with self._lock:
                busy = ext_busy or self._waiting > 0 or any(
                    self._prune(r) for r in list(self._in_flight))
                if busy or was_busy:  # final 0 on the drain edge
                    self._last_report = 0.0  # bypass the rate limit
                    total = self._pending_report_locked()
                was_busy = busy
            self._send_report(total)

    def shutdown(self):
        self._reporter_stop.set()
        self._watch_sub.unsubscribe()
        _ROUTERS.discard(self)


class ServeHandle:
    """Reference: `serve/handle.py` — `handle.remote(...)`,
    `handle.method_name.remote(...)`."""

    def __init__(self, controller, deployment_name: str,
                 max_concurrent_queries: int = 100, _method: str = ""):
        self._controller = controller
        self._deployment = deployment_name
        self._method = _method
        self._router_holder: Dict[str, Router] = {}
        self._max_concurrent = max_concurrent_queries

    def _direct(self):
        """The deployment's replica-direct dispatcher (shared across
        method handles, like the router) — or None while
        ``serve_replica_direct`` is off. Config is read per call so an
        A/B (or an operator) can flip the fast path live; an existing
        dispatcher keeps its membership subscription either way."""
        if not ray_config.serve_replica_direct:
            return None
        d = self._router_holder.get("d")
        if d is None:
            d = membership.DirectDispatcher(
                self._controller, self._deployment, self._max_concurrent)
            self._router_holder["d"] = d
            # A router may already exist (the knob was flipped on
            # LIVE, after routed traffic created one): cross-wire the
            # two NOW — each path must count the other's per-replica
            # load or the shared cap splits into two.
            r = self._router_holder.get("r")
            if r is not None:
                d.set_router_load(r.replica_load)
                r.set_external_load(d.table.slots_of,
                                    total=d.table.total_in_flight)
        return d

    def _router(self) -> Router:
        r = self._router_holder.get("r")
        if r is None:
            # The router counts the direct table's slots against the
            # per-replica cap, so the two dispatch paths share one
            # concurrency budget per replica. Created through the
            # holder so the dispatcher (and its membership
            # subscription) exists whenever the router does.
            d = self._direct()
            r = Router(self._controller, self._deployment,
                       self._max_concurrent,
                       external_load=d.table.slots_of
                       if d is not None else None)
            if d is not None:
                d.set_router_load(r.replica_load)
                r.set_external_load(d.table.slots_of,
                                    total=d.table.total_in_flight)
            self._router_holder["r"] = r
        return r

    def try_direct(self, *args, _trace=None, _job=None, **kwargs):
        """Replica-direct fast path: ``(ref, token)`` dispatched
        straight to a replica with a free slot (no router, no head), or
        ``(None, None)`` — cold table, saturation, or the fast path
        disabled — in which case the caller takes the routed path. The
        caller MUST release (or, on replica death, invalidate) the
        token when the request completes."""
        d = self._direct()
        if d is None:
            return None, None
        return d.dispatch(self._method or "__call__", args, kwargs,
                          trace=_trace, job=_job)

    def direct_release(self, token) -> None:
        d = self._router_holder.get("d")
        if d is not None:
            d.release(token)

    def direct_invalidate(self, token) -> None:
        d = self._router_holder.get("d")
        if d is not None:
            d.invalidate(token)
        # The routed FALLBACK must not round-robin onto the replica
        # this caller just watched die: drop it from the router's
        # list too, ahead of the membership broadcast.
        r = self._router_holder.get("r")
        if r is not None and token is not None:
            r.discard_replica(token.replica)

    def remote(self, *args, _trace=None, _job=None, **kwargs):
        return self._router().assign_request(self._method or "__call__",
                                             args, kwargs, trace=_trace,
                                             job=_job)

    def remote_async(self, *args, _queue_timeout_s: float = 30.0,
                     _trace=None, _job=None, **kwargs):
        """Awaitable dispatch for event-loop callers (the asyncio HTTP
        proxy): resolves to the ObjectRef once a replica slot frees,
        without ever blocking the calling loop. ``_queue_timeout_s``
        bounds the wait for a slot — the proxy maps its expiry to
        ``503 Retry-After`` (load shedding, not an error). ``_trace``
        is the request's (trace_id, parent_span_id); the replica call
        joins that trace. ``_job`` is the request's job/tenant tag —
        the replica call (and tasks it submits) carries it."""
        return self._router().assign_request_async(
            self._method or "__call__", args, kwargs,
            timeout=_queue_timeout_s, trace=_trace, job=_job)

    def try_remote(self, *args, _trace=None, _job=None, **kwargs):
        """Non-blocking dispatch: the ref now, or None when every
        replica is at its cap (caller then awaits
        :meth:`remote_async` or sheds)."""
        return self._router().try_assign_request(
            self._method or "__call__", args, kwargs, trace=_trace,
            job=_job)

    def __getattr__(self, name: str) -> "ServeHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        h = ServeHandle(self._controller, self._deployment,
                        self._max_concurrent, _method=name)
        h._router_holder = self._router_holder  # share router + caps
        return h

    def __reduce__(self):
        return (ServeHandle, (self._controller, self._deployment,
                              self._max_concurrent, self._method))
