"""Router + ServeHandle: the data plane.

Reference: `serve/_private/router.py:263` (`assign_replica :224` —
round-robin skipping replicas at `max_concurrent_queries`) and
`serve/handle.py`. Replica membership arrives via long-poll; in-flight
refs are tracked per replica so the cap is enforced client-side.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve._private.long_poll import LongPollClient


class Router:
    def __init__(self, controller, deployment_name: str,
                 max_concurrent_queries: int = 100):
        self._controller = controller
        self._deployment = deployment_name
        self._max_concurrent = max_concurrent_queries
        self._replicas: List[Any] = []
        self._rr = itertools.count()
        self._in_flight: Dict[Any, List] = {}
        self._lock = threading.Condition()
        self._client = LongPollClient(
            controller, f"replicas::{deployment_name}",
            self._update_replicas)
        self._last_report = 0.0

    def _update_replicas(self, replicas):
        with self._lock:
            self._replicas = list(replicas or [])
            for r in self._replicas:
                self._in_flight.setdefault(r, [])
            self._lock.notify_all()

    def _prune(self, replica) -> int:
        refs = self._in_flight.get(replica, [])
        if refs:
            _, not_ready = ray_tpu.wait(refs, num_returns=len(refs),
                                        timeout=0)
            self._in_flight[replica] = list(not_ready)
        return len(self._in_flight.get(replica, []))

    def assign_request(self, method: str, args: tuple, kwargs: dict,
                       timeout: float = 30.0):
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                replicas = list(self._replicas)
            if replicas:
                n = len(replicas)
                start = next(self._rr)
                for i in range(n):
                    replica = replicas[(start + i) % n]
                    with self._lock:
                        load = self._prune(replica)
                        if load < self._max_concurrent:
                            ref = replica.handle_request.remote(
                                method, args, kwargs)
                            self._in_flight[replica].append(ref)
                            self._maybe_report()
                            return ref
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no replica available for {self._deployment} "
                    f"within {timeout}s")
            time.sleep(0.005)

    def _maybe_report(self):
        now = time.monotonic()
        if now - self._last_report < 0.5:
            return
        self._last_report = now
        total = sum(len(v) for v in self._in_flight.values())
        try:
            self._controller.record_handle_metrics.remote(
                self._deployment, float(total))
        except Exception:
            pass

    def shutdown(self):
        self._client.stop()


class ServeHandle:
    """Reference: `serve/handle.py` — `handle.remote(...)`,
    `handle.method_name.remote(...)`."""

    def __init__(self, controller, deployment_name: str,
                 max_concurrent_queries: int = 100, _method: str = ""):
        self._controller = controller
        self._deployment = deployment_name
        self._method = _method
        self._router_holder: Dict[str, Router] = {}
        self._max_concurrent = max_concurrent_queries

    def _router(self) -> Router:
        r = self._router_holder.get("r")
        if r is None:
            r = Router(self._controller, self._deployment,
                       self._max_concurrent)
            self._router_holder["r"] = r
        return r

    def remote(self, *args, **kwargs):
        return self._router().assign_request(self._method or "__call__",
                                             args, kwargs)

    def __getattr__(self, name: str) -> "ServeHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        h = ServeHandle(self._controller, self._deployment,
                        self._max_concurrent, _method=name)
        h._router_holder = self._router_holder  # share router + caps
        return h

    def __reduce__(self):
        return (ServeHandle, (self._controller, self._deployment,
                              self._max_concurrent, self._method))
