"""Long-poll: the control→data-plane update channel.

Reference: `serve/_private/long_poll.py:185` (LongPollHost) — clients ask
"notify me when key K changes past version V"; the host blocks the call
until the snapshot advances. Routers and proxies learn replica-set and
route-table changes this way instead of polling hot loops.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple


class LongPollHost:
    """Lives inside the controller actor (thread-safe)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._snapshots: Dict[str, Any] = {}
        self._versions: Dict[str, int] = {}

    def notify_changed(self, key: str, snapshot: Any) -> None:
        with self._cond:
            self._snapshots[key] = snapshot
            self._versions[key] = self._versions.get(key, 0) + 1
            self._cond.notify_all()

    def listen(self, key: str, known_version: int = -1,
               timeout: float = 30.0) -> Tuple[int, Any]:
        """Block until version(key) > known_version (or timeout); returns
        (version, snapshot)."""
        with self._cond:
            self._cond.wait_for(
                lambda: self._versions.get(key, 0) > known_version,
                timeout=timeout)
            return (self._versions.get(key, 0),
                    self._snapshots.get(key))


class LongPollClient:
    """Driver/router-side: background thread keeping a local copy fresh."""

    def __init__(self, controller, key: str, callback):
        self._controller = controller
        self._key = key
        self._callback = callback
        self._version = -1
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"longpoll-{key}")
        self._thread.start()

    def _loop(self):
        import ray_tpu
        from ray_tpu.exceptions import ActorDiedError, ActorError

        while not self._stopped.is_set():
            try:
                version, snapshot = ray_tpu.get(
                    self._controller.listen.remote(self._key, self._version),
                    timeout=60)
            except (ActorDiedError, ActorError):
                # Controller is gone (serve.shutdown / crash): this
                # client is permanently orphaned — exit instead of
                # spinning error objects forever.
                return
            except Exception:
                if self._stopped.is_set():
                    return
                # Transient failure: back off — a hot retry loop against
                # a broken controller starves every other thread.
                self._stopped.wait(0.5)
                continue
            if version > self._version:
                self._version = version
                try:
                    self._callback(snapshot)
                except Exception:
                    pass

    def stop(self):
        self._stopped.set()
