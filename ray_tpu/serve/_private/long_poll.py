"""Long-poll: the control→data-plane update channel.

Reference: `serve/_private/long_poll.py:185` (LongPollHost) — clients ask
"notify me when key K changes past version V"; the host blocks the call
until the snapshot advances. Routers and proxies learn replica-set and
route-table changes this way instead of polling hot loops.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Tuple

from ray_tpu._private import sanitize_hooks


class LongPollHost:
    """Lives inside the controller actor (thread-safe)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._snapshots: Dict[str, Any] = {}
        self._versions: Dict[str, int] = {}
        self._poisoned = False

    def notify_changed(self, key: str, snapshot: Any) -> None:
        # Yield point: a membership broadcast racing listener arrivals
        # and a controller kill is the convergence protocol's surface —
        # raymc orders this crossing against parked listens and the
        # injected controller death.
        sanitize_hooks.sched_point("longpoll.notify")
        with self._cond:
            self._snapshots[key] = snapshot
            self._versions[key] = self._versions.get(key, 0) + 1
            self._cond.notify_all()

    def listen(self, key: str, known_version: int = -1,
               timeout: float = 30.0) -> Tuple[int, Any]:
        """Block until version(key) > known_version (or timeout); returns
        (version, snapshot). A poisoned host (see :meth:`shutdown`)
        answers after a token delay instead of blocking."""
        sanitize_hooks.sched_point("longpoll.listen")
        with self._cond:
            self._cond.wait_for(
                lambda: self._poisoned
                or self._versions.get(key, 0) > known_version,
                timeout=timeout)
            if self._poisoned:
                # Not 0: a client that missed its stop signal would
                # otherwise hot-loop listen/return for the rest of the
                # shutdown window.
                self._cond.wait(0.05)
            return (self._versions.get(key, 0),
                    self._snapshots.get(key))

    def shutdown(self) -> None:
        """Poison the host: every parked listener wakes now and future
        listens return immediately. Without this, a killed controller's
        in-flight ``listen`` task pins its executor thread for the full
        30s wait (and the client's ``get`` with it) — the exact leak
        the sanitizer flagged on every serve test teardown."""
        with self._cond:
            self._poisoned = True
            self._cond.notify_all()


class LongPollClient:
    """Driver/router-side: background thread keeping a local copy fresh.

    When the controller dies and a `reresolve` callable is provided, the
    client polls it until a REPLACEMENT controller registers under the
    well-known name, then resumes listening from version -1 (the
    recovered controller re-broadcasts its checkpointed state) — the
    reference's client-side controller-recovery path. Without
    `reresolve` a dead controller permanently orphans the client (the
    serve.shutdown case)."""

    _RERESOLVE_WINDOW_S = 60.0

    def __init__(self, controller, key: str, callback, reresolve=None):
        self._controller = controller
        self._key = key
        self._callback = callback
        self._reresolve = reresolve
        self._version = -1
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"longpoll-{key}")
        self._thread.start()

    def _try_reresolve(self) -> bool:
        """Poll for a LIVE controller (the reresolver pings before
        returning a handle — a replacement, or the same actor restarted
        in place via max_restarts); True to resume listening from
        scratch."""
        import time

        deadline = time.monotonic() + self._RERESOLVE_WINDOW_S
        while not self._stopped.is_set() and time.monotonic() < deadline:
            try:
                new = self._reresolve()
            except Exception:
                new = None
            if new is not None:
                self._controller = new
                self._version = -1
                return True
            self._stopped.wait(0.5)
        return False

    def _loop(self):
        import ray_tpu
        from ray_tpu.exceptions import (ActorDiedError, ActorError,
                                        GetTimeoutError)

        while not self._stopped.is_set():
            # Loop-edge yield point: between two polls is where a
            # controller death lands (the next listen hits a dead
            # actor) — the crossing the checker parks to interleave a
            # kill/restart against an in-flight poll cycle.
            sanitize_hooks.sched_point("longpoll.client.loop")
            try:
                ref = self._controller.listen.remote(
                    self._key, self._version)
                # Bounded get so stop() takes effect within one slice
                # even while the server holds the poll open — an
                # un-interruptible 60s get kept this thread alive long
                # past every teardown.
                while True:
                    if self._stopped.is_set():
                        return
                    try:
                        version, snapshot = ray_tpu.get(ref, timeout=0.5)
                        break
                    except GetTimeoutError:
                        continue
            except (ActorDiedError, ActorError):
                # Controller is gone. With a reresolver, wait for its
                # replacement (serve keeps answering from the last
                # snapshot meanwhile); otherwise exit instead of
                # spinning error objects forever.
                if self._reresolve is not None and self._try_reresolve():
                    continue
                return
            except Exception:
                if self._stopped.is_set():
                    return
                # Transient failure: back off — a hot retry loop against
                # a broken controller starves every other thread.
                self._stopped.wait(0.5)
                continue
            if version > self._version:
                self._version = version
                try:
                    self._callback(snapshot)
                except Exception:
                    pass

    def stop(self):
        self._stopped.set()
