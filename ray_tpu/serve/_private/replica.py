"""Replica: the actor wrapping one copy of a deployment's callable.

Reference: `serve/_private/replica.py:268` (RayServeReplica) — construct
the user class, serve queries, expose reconfigure + health check, report
in-flight load for the router's capacity decisions.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict

import ray_tpu

# Lag-sampler component keys need a per-instance discriminator: two
# replicas of one deployment can share a process, and under a shared
# key the second install's supersede token would silently stop the
# first replica's sampler — leaving exactly one loop unmonitored.
_loop_seq = itertools.count(1)

# Per-replica progress heartbeats (actor name -> monotonic stamp of the
# last COMPLETED request): the controller's hung-replica detector
# distinguishes a SATURATED replica (ping FIFO'd behind a deep mailbox
# but requests completing continuously — must never be struck) from a
# WEDGED one (no completions since the ping was sent). Process-local:
# in cluster mode a remote replica's stamps are invisible and the
# detector conservatively treats "no stamp" as "can't prove progress".
_PROGRESS_LOCK = threading.Lock()
_PROGRESS: Dict[str, float] = {}


def note_progress(name: str) -> None:
    if name:
        with _PROGRESS_LOCK:
            _PROGRESS[name] = time.monotonic()


def last_progress(name: str):
    with _PROGRESS_LOCK:
        return _PROGRESS.get(name)


def clear_progress(name: str) -> None:
    """Reset-capable (a replica leaving membership drops its row)."""
    with _PROGRESS_LOCK:
        _PROGRESS.pop(name, None)


@ray_tpu.remote
class ServeReplica:
    def __init__(self, deployment_name: str, serialized_cls, init_args,
                 init_kwargs, user_config=None, version: str = "",
                 actor_name: str = ""):
        from ray_tpu._private import perf_stats

        self.deployment_name = deployment_name
        self.version = version
        self.actor_name = actor_name  # progress-heartbeat key
        self._lock = threading.Lock()
        self._in_flight = 0
        self._total = 0
        self._t_busy = 0.0
        # Per-deployment execution latency, recorded in the REPLICA's
        # process — on a worker node it rides the metric-snapshot
        # shipping plane to the head's merged /api/metrics.
        self._stat_latency = perf_stats.dist(
            "serve_replica_request_seconds",
            tags={"deployment": deployment_name},
            bounds=perf_stats.SERVE_LATENCY_BOUNDS)
        self._stat_errors = perf_stats.counter(
            "serve_replica_errors", tags={"deployment": deployment_name})
        self._async_loop = None  # lazily-started, shared across requests
        self._loop_lag_component = None
        if isinstance(serialized_cls, type):
            self.callable = serialized_cls(*(init_args or ()),
                                           **(init_kwargs or {}))
        else:
            self.callable = serialized_cls  # plain function deployment
        if user_config is not None:
            self.reconfigure(user_config)

    def reconfigure(self, user_config) -> bool:
        fn = getattr(self.callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)
        return True

    def check_health(self) -> bool:
        fn = getattr(self.callable, "check_health", None)
        if fn is not None:
            fn()
        return True

    def prefix_digests(self):
        """Cache-affinity hints for the controller's digests:: channel:
        LLM deployments answer with their hot prefix-head digests; every
        other deployment answers None (no hints, router stays
        load-based)."""
        fn = getattr(self.callable, "prefix_digests", None)
        if fn is None:
            return None
        try:
            return fn()
        except Exception:
            return None

    def handle_request(self, method: str, args: tuple, kwargs: dict):
        from ray_tpu._private import critical_path

        with self._lock:
            self._in_flight += 1
            self._total += 1
        trace_id = critical_path.ambient_trace_id() \
            if critical_path.enabled() else None
        t0 = time.perf_counter()
        try:
            target = self.callable
            if method and method != "__call__":
                target = getattr(self.callable, method)
            elif not callable(target):
                target = getattr(self.callable, "__call__")
            result = target(*args, **kwargs)
            import inspect

            if inspect.iscoroutine(result):
                # One persistent loop per replica: asyncio.run() per
                # request paid a full loop setup/teardown on the serving
                # hot path, and broke coroutines that share loop-bound
                # state (locks, queues) across requests.
                result = self._run_coroutine(result)
            if inspect.isasyncgen(result):
                return self._start_stream(self._agen_to_gen(result))
            if inspect.isgenerator(result):
                return self._start_stream(result)
            return result
        except BaseException:
            self._stat_errors.inc()
            raise
        finally:
            elapsed = time.perf_counter() - t0
            self._stat_latency.record(elapsed)
            critical_path.record_stage(trace_id, "replica.execute",
                                       elapsed)
            note_progress(self.actor_name)
            with self._lock:
                self._in_flight -= 1
                self._t_busy += elapsed

    def _ensure_loop(self):
        import asyncio

        with self._lock:
            if self._async_loop is None:
                loop = asyncio.new_event_loop()
                threading.Thread(target=loop.run_forever, daemon=True,
                                 name="serve-replica-loop").start()
                self._async_loop = loop
                # Health-plane overload signal: lag on the replica's
                # shared request loop (an async deployment blocking it
                # stalls every other request on this replica). Recorded
                # in THIS process, so on a worker node it ships to the
                # head with the rest of the metric snapshot.
                from ray_tpu._private.health import (
                    install_loop_lag_sampler,
                )

                self._loop_lag_component = (
                    f"replica:{self.deployment_name}"
                    f"#{next(_loop_seq)}")
                install_loop_lag_sampler(
                    loop, self._loop_lag_component)
            return self._async_loop

    def _run_coroutine(self, coro):
        import asyncio

        return asyncio.run_coroutine_threadsafe(
            coro, self._ensure_loop()).result()

    def _agen_to_gen(self, agen):
        """Drive an async-generator deployment result from the stream
        pump thread, one chunk at a time on the replica's loop — async
        deployments stream exactly like sync ones."""
        import asyncio

        loop = self._ensure_loop()
        try:
            while True:
                try:
                    yield asyncio.run_coroutine_threadsafe(
                        agen.__anext__(), loop).result()
                except StopAsyncIteration:
                    return
        finally:
            asyncio.run_coroutine_threadsafe(
                agen.aclose(), loop).result(timeout=5)

    def _start_stream(self, gen):
        """Generator results stream through an actor-backed queue: the
        replica pumps in a background thread (bounded queue =
        backpressure); the consumer — HTTP proxy or Python caller via
        `serve.iter_stream` — pulls until the end marker. This is the
        token-streaming channel (reference: ASGI StreamingResponse
        through `http_proxy.py:425`; the transport differs, the contract
        — incremental chunks over one request — is the same)."""
        from ray_tpu.serve.streaming import STREAM_END_KEY, STREAM_KEY
        from ray_tpu.util.queue import Queue

        queue = Queue(maxsize=64)

        def pump():
            # Finite put timeouts: an abandoned consumer (client gone,
            # queue actor killed by iter_stream's cleanup) must release
            # the pump thread and close the generator, not pin them
            # forever behind a full queue.
            try:
                for item in gen:
                    queue.put(item, timeout=60.0)
                queue.put({STREAM_END_KEY: True}, timeout=60.0)
            except BaseException as e:  # noqa: BLE001 - surfaced to reader
                try:
                    gen.close()
                except Exception:
                    pass
                try:
                    queue.put({STREAM_END_KEY: True, "error": repr(e)},
                              timeout=5.0)
                except Exception:
                    pass

        threading.Thread(target=pump, daemon=True,
                         name="serve-stream-pump").start()
        return {STREAM_KEY: queue}

    def get_metrics(self) -> Dict[str, Any]:
        with self._lock:
            return {"in_flight": self._in_flight, "total": self._total,
                    "busy_s": self._t_busy}

    def prepare_for_shutdown(self) -> bool:
        # Graceful: wait for in-flight to drain (bounded).
        drained = False
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with self._lock:
                if self._in_flight == 0:
                    drained = True
                    break
            time.sleep(0.02)
        # Stop the request loop (kills its lag sampler with it) and
        # retire the sampler's component entry — a retired replica must
        # not keep an idle-~0 lag series alive under its unique key.
        with self._lock:
            loop, comp = self._async_loop, self._loop_lag_component
            self._async_loop = None
            self._loop_lag_component = None
        if loop is not None:
            import asyncio

            # Cancel everything still on the loop (the lag sampler, any
            # straggler requests past the drain deadline) and give the
            # cancellations one pass to unwind BEFORE stopping — a task
            # still pending at loop teardown warns "Task was destroyed
            # but it is pending!" on every replica stop.
            async def _cancel_all_and_stop():
                cur = asyncio.current_task()
                for t in asyncio.all_tasks():
                    if t is not cur:
                        t.cancel()
                await asyncio.sleep(0)
                loop.stop()

            try:
                asyncio.run_coroutine_threadsafe(
                    _cancel_all_and_stop(), loop).result(timeout=2)
            except Exception:
                pass
        if comp is not None:
            from ray_tpu._private.health import (
                remove_loop_lag_component,
            )

            remove_loop_lag_component(comp)
        return drained
