"""Replica: the actor wrapping one copy of a deployment's callable.

Reference: `serve/_private/replica.py:268` (RayServeReplica) — construct
the user class, serve queries, expose reconfigure + health check, report
in-flight load for the router's capacity decisions.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Dict, Optional

import ray_tpu


@ray_tpu.remote
class ServeReplica:
    def __init__(self, deployment_name: str, serialized_cls, init_args,
                 init_kwargs, user_config=None, version: str = ""):
        self.deployment_name = deployment_name
        self.version = version
        self._lock = threading.Lock()
        self._in_flight = 0
        self._total = 0
        self._t_busy = 0.0
        if isinstance(serialized_cls, type):
            self.callable = serialized_cls(*(init_args or ()),
                                           **(init_kwargs or {}))
        else:
            self.callable = serialized_cls  # plain function deployment
        if user_config is not None:
            self.reconfigure(user_config)

    def reconfigure(self, user_config) -> bool:
        fn = getattr(self.callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)
        return True

    def check_health(self) -> bool:
        fn = getattr(self.callable, "check_health", None)
        if fn is not None:
            fn()
        return True

    def handle_request(self, method: str, args: tuple, kwargs: dict):
        with self._lock:
            self._in_flight += 1
            self._total += 1
        t0 = time.perf_counter()
        try:
            target = self.callable
            if method and method != "__call__":
                target = getattr(self.callable, method)
            elif not callable(target):
                target = getattr(self.callable, "__call__")
            result = target(*args, **kwargs)
            import inspect

            if inspect.iscoroutine(result):
                import asyncio

                result = asyncio.run(result)
            return result
        finally:
            with self._lock:
                self._in_flight -= 1
                self._t_busy += time.perf_counter() - t0

    def get_metrics(self) -> Dict[str, Any]:
        with self._lock:
            return {"in_flight": self._in_flight, "total": self._total,
                    "busy_s": self._t_busy}

    def prepare_for_shutdown(self) -> bool:
        # Graceful: wait for in-flight to drain (bounded).
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with self._lock:
                if self._in_flight == 0:
                    return True
            time.sleep(0.02)
        return False
