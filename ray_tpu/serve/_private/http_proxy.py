"""HTTP proxy: route HTTP requests to deployment handles.

Reference: `serve/_private/http_proxy.py:425` (uvicorn + ASGI). Here a
threaded stdlib HTTP server (no external deps in the image) with
longest-prefix routing; JSON bodies are parsed and handed to the
deployment callable, results JSON-encoded. An ASGI front-end can be
swapped in where starlette/uvicorn are available.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import ray_tpu


class _RouteTable:
    def __init__(self):
        self._routes: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def set(self, prefix: str, handle):
        with self._lock:
            self._routes[prefix.rstrip("/") or "/"] = handle

    def remove(self, prefix: str):
        with self._lock:
            self._routes.pop(prefix.rstrip("/") or "/", None)

    def match(self, path: str) -> Tuple[Optional[Any], str]:
        with self._lock:
            routes = dict(self._routes)
        best = None
        best_len = -1
        for prefix, handle in routes.items():
            p = prefix.rstrip("/")
            if (path == p or path.startswith(p + "/") or p == "") and \
                    len(p) > best_len:
                best, best_len = (handle, p), len(p)
        if best is None:
            return None, path
        handle, p = best
        return handle, path[len(p):] or "/"


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.routes = _RouteTable()
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive: without it every request pays a TCP
            # connect plus a fresh handler thread (ThreadingHTTPServer
            # is thread-per-CONNECTION), which capped ingress at a few
            # hundred RPS. Persistent connections amortize both.
            protocol_version = "HTTP/1.1"
            # One segment per response: unbuffered wfile writes (status
            # line, each header, body as separate send()s) interact with
            # Nagle + the peer's 40ms delayed ACK to add ~44ms per
            # keep-alive request. Buffer fully and disable Nagle.
            wbufsize = -1
            disable_nagle_algorithm = True
            # Idle keep-alive connections must not pin a thread forever
            # (thread-per-connection server): reap after 30s quiet.
            timeout = 30

            def log_message(self, *args):  # quiet
                pass

            def _dispatch(self):
                handle, rest = proxy.routes.match(self.path.split("?")[0])
                if handle is None:
                    miss = b'{"error": "no route"}'
                    self.send_response(404)
                    self.send_header("Content-Length", str(len(miss)))
                    self.end_headers()
                    self.wfile.write(miss)
                    return
                if "chunked" in (self.headers.get("Transfer-Encoding")
                                 or "").lower():
                    # Not decoded here; reading Content-Length bytes of
                    # a chunked body would desync the keep-alive stream.
                    err = b'{"error": "chunked bodies not supported"}'
                    self.send_response(501)
                    self.send_header("Content-Length", str(len(err)))
                    self.send_header("Connection", "close")
                    self.close_connection = True
                    self.end_headers()
                    self.wfile.write(err)
                    return
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                payload: Any = None
                if body:
                    try:
                        payload = json.loads(body)
                    except ValueError:
                        payload = body.decode("utf-8", "replace")
                try:
                    if payload is None:
                        ref = handle.remote()
                    else:
                        ref = handle.remote(payload)
                    result = ray_tpu.get(ref, timeout=60)
                    from ray_tpu.serve.streaming import (is_stream,
                                                         iter_stream)

                    if is_stream(result):
                        # Server-sent events, flushed per chunk: tokens
                        # reach the client while the model is still
                        # decoding (reference: ASGI StreamingResponse).
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "text/event-stream")
                        self.send_header("Cache-Control", "no-cache")
                        # SSE has no Content-Length: close when done so
                        # keep-alive clients see the end of the body.
                        self.send_header("Connection", "close")
                        self.close_connection = True
                        self.end_headers()
                        try:
                            for chunk in iter_stream(result):
                                self.wfile.write(
                                    b"data: " + json.dumps(chunk).encode()
                                    + b"\n\n")
                                self.wfile.flush()
                            self.wfile.write(b"data: [DONE]\n\n")
                            self.wfile.flush()
                        except (BrokenPipeError, ConnectionError):
                            pass  # client went away mid-stream
                        except Exception as stream_err:  # noqa: BLE001
                            # Headers already sent: a mid-stream failure
                            # must become an error *event*, never a 500
                            # status line spliced into the SSE body.
                            try:
                                self.wfile.write(
                                    b"data: " + json.dumps(
                                        {"error": str(stream_err)}
                                    ).encode() + b"\n\ndata: [DONE]\n\n")
                                self.wfile.flush()
                            except (BrokenPipeError, ConnectionError):
                                pass
                        return
                    out = json.dumps(result).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(out)))
                    self.end_headers()
                    self.wfile.write(out)
                except Exception as e:  # noqa: BLE001
                    err = json.dumps({"error": str(e)}).encode()
                    self.send_response(500)
                    self.send_header("Content-Length", str(len(err)))
                    self.end_headers()
                    self.wfile.write(err)

            do_GET = _dispatch
            do_POST = _dispatch
            do_PUT = _dispatch
            do_DELETE = _dispatch

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="serve-http-proxy")
        self._thread.start()

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()
