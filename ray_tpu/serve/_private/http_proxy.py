"""HTTP ingress: a single-threaded asyncio event-loop HTTP/1.1 server.

Reference: `serve/_private/http_proxy.py:425` (uvicorn + ASGI). The
previous ingress here was a stdlib ``ThreadingHTTPServer`` — a thread
per *connection*, blocking ``ray_tpu.get`` per request, and streamed
responses forced ``Connection: close`` (SSE has no Content-Length), so
every streaming reply tore down its keep-alive connection. This module
replaces it with an event-loop data plane, uvicorn-style but with no
external deps:

- one ``asyncio.Protocol`` per connection on a single loop thread:
  persistent keep-alive connections, no thread per connection, idle
  connections reaped after ``idle_timeout_s``;
- streaming/SSE responses use **chunked transfer-encoding**, so the
  connection survives the stream and the next request rides the same
  socket;
- **bounded-concurrency backpressure**: at most ``max_in_flight``
  requests are in the router at once; beyond that the proxy sheds load
  with ``503 + Retry-After`` instead of growing threads/queues without
  bound. A router-queue timeout (no replica slot within
  ``queue_timeout_s``) also maps to 503;
- the bridge to the handle/router path is fully async:
  ``ServeHandle.remote_async`` awaits a replica slot and
  ``ObjectRef.as_future`` completes on this loop via one
  ``call_soon_threadsafe`` hop — the loop never blocks in
  ``ray_tpu.get``.

Each response is written as a single ``transport.write`` (plus
TCP_NODELAY) — the buffered-write/Nagle lesson from the threaded
proxy's 40 ms delayed-ACK stall carries over.
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import re
import socket
import threading
import time
import uuid
import weakref
from collections import deque
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private import critical_path
from ray_tpu._private import perf_stats
from ray_tpu._private import tenancy
from ray_tpu.exceptions import ActorDiedError
from ray_tpu.serve._private import membership
from ray_tpu.serve._private.router import QueueSaturatedError
from ray_tpu.serve.streaming import aiter_stream, is_stream

_MAX_HEADER_BYTES = 64 * 1024
_MAX_BODY_BYTES = 32 * 1024 * 1024
_MAX_PIPELINED = 16
# Distinct X-Job-Id values one proxy will account before new tags
# degrade to untagged (metric/event cardinality bound).
_MAX_JOB_TAGS = 512

# Structured access log (one line per request, JSON payload), enabled
# by ray_config.serve_access_log — off by default so the ingress hot
# path stays log-free.
_access_log = logging.getLogger("ray_tpu.serve.access")

# Trace ids (client-supplied or minted) and job/tenant tags: token
# chars only — both are echoed into response headers and logs, so the
# same header-injection sanitizing applies.
_TRACE_ID_OK = re.compile(r"^[0-9A-Za-z_.-]+$").match

# Live proxies in this process, for the runtime-metrics gauges
# (ray_tpu_serve_http_in_flight etc.); weak so shutdown proxies drop.
_PROXIES: "weakref.WeakSet[HTTPProxy]" = weakref.WeakSet()


def aggregate_stats() -> Optional[Dict[str, int]]:
    """Summed ingress counters across every live proxy in this process
    (None when no proxy exists) — consumed by runtime_metrics."""
    proxies = list(_PROXIES)
    if not proxies:
        return None
    out: Dict[str, int] = {}
    for p in proxies:
        for k, v in p.stats().items():
            out[k] = out.get(k, 0) + v
    return out


# The core exporter must not import serve (raylint R3): the ingress
# registers its stats source with runtime_metrics instead, keeping the
# dependency pointing downward. Gauge names are unchanged.
from ray_tpu._private import runtime_metrics as _runtime_metrics  # noqa: E402

_runtime_metrics.register_stats_provider(
    "serve_http_ingress", aggregate_stats, {
        "in_flight": ("ray_tpu_serve_http_in_flight",
                      "Serve ingress: HTTP requests in flight"),
        "open_connections": ("ray_tpu_serve_http_open_connections",
                             "Serve ingress: open ingress connections"),
        "served": ("ray_tpu_serve_http_served",
                   "Serve ingress: requests served (terminal non-shed)"),
        "shed_503": ("ray_tpu_serve_http_shed_503",
                     "Serve ingress: requests shed with 503"),
        "limited_429": ("ray_tpu_serve_http_limited_429",
                        "Serve ingress: requests shed by per-tenant "
                        "rate limits (429)"),
        "denied_401": ("ray_tpu_serve_http_denied_401",
                       "Serve ingress: requests refused by ingress "
                       "auth (401)"),
        "direct_served": ("ray_tpu_serve_http_direct_served",
                          "Serve ingress: requests served via the "
                          "replica-direct fast path"),
        "direct_fallbacks": ("ray_tpu_serve_http_direct_fallbacks",
                             "Serve ingress: direct dispatches that "
                             "fell back to the routed path after a "
                             "replica death"),
    })

_REASONS = {
    200: "OK", 400: "Bad Request", 401: "Unauthorized",
    404: "Not Found", 413: "Payload Too Large",
    429: "Too Many Requests",
    431: "Request Header Fields Too Large", 500: "Internal Server Error",
    501: "Not Implemented", 503: "Service Unavailable",
}


class _RouteTable:
    def __init__(self):
        self._routes: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def set(self, prefix: str, handle):
        with self._lock:
            self._routes[prefix.rstrip("/") or "/"] = handle

    def remove(self, prefix: str):
        with self._lock:
            self._routes.pop(prefix.rstrip("/") or "/", None)

    def match(self, path: str) -> Tuple[Optional[Any], str, str]:
        """(handle, rest_of_path, matched_prefix). The prefix — a
        registered route, bounded cardinality — is what metrics and the
        access log tag requests with, never the raw client path."""
        with self._lock:
            routes = dict(self._routes)
        best = None
        best_len = -1
        for prefix, handle in routes.items():
            p = prefix.rstrip("/")
            if (path == p or path.startswith(p + "/") or p == "") and \
                    len(p) > best_len:
                best, best_len = (handle, p), len(p)
        if best is None:
            return None, path, ""
        handle, p = best
        return handle, path[len(p):] or "/", p or "/"


class _Request:
    __slots__ = ("method", "path", "version", "headers", "body",
                 "keep_alive", "chunked_body", "error")

    def __init__(self):
        self.body = b""
        self.chunked_body = False
        self.error: Optional[Tuple[int, bytes]] = None


class _Conn(asyncio.Protocol):
    """One keep-alive client connection on the proxy's event loop.

    Headers parse with one ``split`` over the header block (no readline
    loop); pipelined requests queue in ``backlog`` and are handled
    strictly in order by a single task, so responses never interleave.
    """

    def __init__(self, proxy: "HTTPProxy"):
        self.proxy = proxy
        self.transport = None
        self.buf = b""
        self.backlog: deque = deque()
        self.task: Optional[asyncio.Task] = None
        self.closing = False
        self.last_activity = time.monotonic()
        self._write_paused = False
        self._read_paused = False
        self._drain_waiter: Optional[asyncio.Future] = None
        self._need: Optional[Tuple[_Request, int]] = None
        self._halt_parse = False  # unparseable framing (chunked body)
        self.http10 = False  # version of the request being handled
        self.last_status = 0  # status of the most recent response
        self.trace_id = ""    # trace id of the request being handled
        self.job_id = ""      # job/tenant tag of the request in flight
        self.serve_path = ""  # dispatch path taken (direct/routed/...)
        self.model = ""       # X-Model tag of the request in flight
        self.ttft_s = None    # first-token latency, once observed
        self.t_start = 0.0    # arrival stamp of the request in flight

    # -- lifecycle -------------------------------------------------------

    def connection_made(self, transport):
        self.transport = transport
        sock = transport.get_extra_info("socket")
        if sock is not None:
            try:
                sock.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
            except OSError:
                pass
        self.proxy._conns.add(self)

    def connection_lost(self, exc):
        self.closing = True
        self.proxy._conns.discard(self)
        w = self._drain_waiter
        if w is not None and not w.done():
            w.set_result(None)

    # -- outgoing flow control (slow client) -----------------------------

    def pause_writing(self):
        self._write_paused = True

    def resume_writing(self):
        self._write_paused = False
        w = self._drain_waiter
        if w is not None and not w.done():
            w.set_result(None)

    async def drain(self):
        """Park the writer until the transport buffer drains — a slow
        streaming client backpressures its own stream pump instead of
        buffering the whole response in proxy memory."""
        if self._write_paused and not self.closing:
            self._drain_waiter = self.proxy._loop.create_future()
            try:
                await self._drain_waiter
            finally:
                self._drain_waiter = None

    # -- incoming --------------------------------------------------------

    def data_received(self, data: bytes):
        self.last_activity = time.monotonic()
        self.buf += data
        self._parse()
        if self.backlog and self.task is None and not self.closing:
            self.task = self.proxy._loop.create_task(self._run())
        # Inbound flood guard: a client pipelining faster than the
        # handlers drain must not buffer unboundedly.
        if (len(self.backlog) > _MAX_PIPELINED
                and not self._read_paused):
            self._read_paused = True
            self.transport.pause_reading()

    def _fail_parse(self, status: int, body: bytes):
        """Queue a framing-error pseudo-request (responses must stay in
        order behind any pipelined predecessors) and stop parsing — the
        byte stream is no longer trustworthy, so the handler closes."""
        req = _Request()
        req.method, req.path, req.version = "GET", "/", "HTTP/1.1"
        req.headers = {}
        req.keep_alive = False
        req.error = (status, body)
        self.backlog.append(req)
        self._halt_parse = True

    def _parse(self):
        while not self._halt_parse:
            if self._need is not None:
                req, length = self._need
                if len(self.buf) < length:
                    return
                req.body = self.buf[:length]
                self.buf = self.buf[length:]
                self._need = None
                self.backlog.append(req)
                continue
            end = self.buf.find(b"\r\n\r\n")
            if end < 0:
                if len(self.buf) > _MAX_HEADER_BYTES:
                    self._fail_parse(431, b'{"error": "headers too '
                                     b'large"}')
                return
            head, self.buf = self.buf[:end], self.buf[end + 4:]
            lines = head.split(b"\r\n")
            req = _Request()
            try:
                req.method, req.path, version = \
                    lines[0].decode("latin-1").split(" ", 2)
                req.version = version.strip()
            except ValueError:
                self._fail_parse(400, b'{"error": "bad request"}')
                return
            headers: Dict[str, str] = {}
            cl_values = set()
            for ln in lines[1:]:
                k, _, v = ln.partition(b":")
                key = k.strip().lower().decode("latin-1")
                headers[key] = v.strip().decode("latin-1")
                if key == "content-length":
                    cl_values.add(headers[key])
            req.headers = headers
            if len(cl_values) > 1:
                # Conflicting duplicate content-lengths: last-wins here
                # vs first-wins at a front proxy is exactly the framing
                # disagreement smuggling exploits (RFC 9110 §8.6 allows
                # duplicates only when identical): hard 400.
                self._fail_parse(400, b'{"error": "conflicting '
                                 b'content-length"}')
                return
            conn_hdr = headers.get("connection", "").lower()
            if req.version == "HTTP/1.0":
                req.keep_alive = "keep-alive" in conn_hdr
            else:
                req.keep_alive = "close" not in conn_hdr
            if "chunked" in headers.get("transfer-encoding", "").lower():
                # Not decoded: bytes after the header block can't be
                # framed, so stop parsing — the handler replies 501 and
                # closes.
                req.chunked_body = True
                self.backlog.append(req)
                self._halt_parse = True
                return
            cl = headers.get("content-length", "")
            if cl:
                # RFC 9110: the value is DIGITs only. Bare int() is
                # laxer ("+5", " 5 ", "1_0", non-ASCII decimal digits)
                # and any laxity mismatch with a stricter front proxy
                # is a smuggling vector, so validate before parsing.
                length = int(cl) if cl.isascii() and cl.isdigit() \
                    else -1
            else:
                length = 0
            if length < 0:
                # A negative length would make the body slice swallow
                # pipelined successors (request smuggling): hard 400.
                self._fail_parse(400, b'{"error": "bad content-'
                                 b'length"}')
                return
            if length > _MAX_BODY_BYTES:
                # Bound what one request can make the loop buffer —
                # max_in_flight can't engage before parsing completes.
                self._fail_parse(413, b'{"error": "body too large"}')
                return
            if length:
                self._need = (req, length)
            else:
                self.backlog.append(req)

    async def _run(self):
        try:
            while self.backlog and not self.closing:
                req = self.backlog.popleft()
                if (self._read_paused
                        and len(self.backlog) <= _MAX_PIPELINED // 2):
                    self._read_paused = False
                    self.transport.resume_reading()
                self.http10 = req.version == "HTTP/1.0"
                await self.proxy._handle(self, req)
                self.last_activity = time.monotonic()
        finally:
            # No await between the loop's empty-backlog check and this
            # reset (single loop thread), so no request can slip in
            # unhandled.
            self.task = None

    # -- outgoing --------------------------------------------------------

    def send_response(self, status: int, body: bytes, *,
                      keep: bool = True, retry_after=False,
                      content_type: str = "application/json"):
        # ``retry_after``: falsy = no header; True = 1s; a number =
        # that many seconds (rounded up — the rate limiter's computed
        # token-accrual time must reach the wire, or compliant clients
        # retry far too fast).
        self.last_status = status
        if self.closing:
            return
        if status == 200 and keep and not self.http10 \
                and content_type == "application/json":
            # The hot path (every successful unary JSON reply): one
            # bytes concatenation, no per-header string formatting.
            trace_hdr = (b"X-Trace-Id: " + self.trace_id.encode()
                         + b"\r\n") if self.trace_id else b""
            if self.job_id:
                trace_hdr += (b"X-Job-Id: " + self.job_id.encode()
                              + b"\r\n")
            if self.serve_path:
                # Per-request dispatch-path proof (direct|routed|
                # fallback): the replica-direct benches and the chaos
                # test read it instead of trusting aggregate counters.
                trace_hdr += (b"X-Serve-Path: "
                              + self.serve_path.encode() + b"\r\n")
            self.transport.write(
                b"HTTP/1.1 200 OK\r\nContent-Type: application/json"
                b"\r\n" + trace_hdr
                + b"Content-Length: " + str(len(body)).encode()
                + b"\r\n\r\n" + body)
            return
        parts = [
            f"HTTP/1.1 {status} {_REASONS.get(status, 'Unknown')}",
            f"Content-Type: {content_type}",
            f"Content-Length: {len(body)}",
        ]
        if self.trace_id:
            parts.append(f"X-Trace-Id: {self.trace_id}")
        if self.job_id:
            parts.append(f"X-Job-Id: {self.job_id}")
        if self.serve_path:
            parts.append(f"X-Serve-Path: {self.serve_path}")
        if retry_after:
            seconds = 1 if retry_after is True else \
                max(1, math.ceil(float(retry_after)))
            parts.append(f"Retry-After: {seconds}")
        if not keep:
            parts.append("Connection: close")
        elif self.http10:
            # HTTP/1.0 defaults to close: persistence must be granted
            # explicitly or the client drops the socket while the
            # server-side connection lingers until the idle reaper.
            parts.append("Connection: keep-alive")
        self.transport.write(
            ("\r\n".join(parts) + "\r\n\r\n").encode("latin-1") + body)
        if not keep:
            self.closing = True
            self.transport.close()

    def send_header_block(self, status: int, headers):
        self.last_status = status
        if self.closing:
            return
        parts = [f"HTTP/1.1 {status} {_REASONS.get(status, 'Unknown')}"]
        parts += [f"{k}: {v}" for k, v in headers]
        if self.trace_id:
            parts.append(f"X-Trace-Id: {self.trace_id}")
        if self.job_id:
            parts.append(f"X-Job-Id: {self.job_id}")
        self.transport.write(
            ("\r\n".join(parts) + "\r\n\r\n").encode("latin-1"))

    def write_body(self, data: bytes, chunked: bool):
        if self.closing:
            return
        if chunked:
            self.transport.write(b"%x\r\n" % len(data) + data + b"\r\n")
        else:
            self.transport.write(data)


class HTTPProxy:
    """The per-process ingress: an event-loop HTTP/1.1 server routing to
    deployment handles. API-compatible with the threaded predecessor
    (``routes`` / ``host`` / ``port`` / ``shutdown``)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8000, *,
                 max_in_flight: int = 256, queue_timeout_s: float = 15.0,
                 idle_timeout_s: float = 30.0,
                 result_timeout_s: float = 60.0):
        self.routes = _RouteTable()
        self.max_in_flight = max_in_flight
        self.queue_timeout_s = queue_timeout_s
        self.idle_timeout_s = idle_timeout_s
        self.result_timeout_s = result_timeout_s
        self._in_flight = 0
        self._served = 0
        self._shed = 0
        self._limited = 0
        self._denied = 0
        self._direct_served = 0
        self._fallbacks = 0
        # Per-tenant ingress token buckets (tenancy enforcement): work
        # a job pushes past its rate is shed with 429 + Retry-After
        # HERE, before any router/replica resource is touched.
        self._limiter = tenancy.IngressLimiter()
        # Priority-class shedding (X-Priority): lowest class sheds
        # first as in-flight load rises, plus optional per-class rate
        # buckets — all decided by the pure gate in tenancy.py.
        self._priority = tenancy.PriorityGate()
        self._conns: set = set()
        # Distinct job tags this proxy has accounted. X-Job-Id is
        # client-controlled: without a cap, a client cycling random
        # tokens mints one permanent (route, job) counter series — and
        # one job-tagged task-event key head-side — per value. Real
        # tenant counts are far below this; overflow tags degrade to
        # untagged rather than growing the registry.
        self._job_tags_seen: set = set()
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._loop_main,
                                        daemon=True,
                                        name="serve-http-proxy")
        self._thread.start()
        self._started.wait(10)
        fut = asyncio.run_coroutine_threadsafe(
            self._start_server(host, port), self._loop)
        try:
            self.host, self.port = fut.result(timeout=30)
        except BaseException:
            # Bind failure (port in use, bad host): don't leak the loop
            # thread behind the raised error.
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=5)
            raise
        _PROXIES.add(self)  # runtime-metrics gauges read live proxies

    def _loop_main(self):
        asyncio.set_event_loop(self._loop)
        self._loop.call_soon(self._started.set)
        self._loop.run_forever()
        pending = asyncio.all_tasks(self._loop)
        for t in pending:
            t.cancel()
        if pending:
            self._loop.run_until_complete(
                asyncio.gather(*pending, return_exceptions=True))
        self._loop.close()

    async def _start_server(self, host: str, port: int):
        self._server = await self._loop.create_server(
            lambda: _Conn(self), host, port)
        self._reaper = self._loop.create_task(self._reap_idle())
        # Overload signal for /api/healthz: how late timed callbacks
        # fire on THIS loop — the single-threaded ingress's canonical
        # saturation measure (the sampler task dies with the loop).
        from ray_tpu._private.health import install_loop_lag_sampler

        install_loop_lag_sampler(self._loop, "http_proxy")
        return self._server.sockets[0].getsockname()[:2]

    async def _reap_idle(self):
        """Keep-alive connections must not pin resources forever: close
        any connection idle (no request in progress) past the timeout."""
        while True:
            await asyncio.sleep(min(5.0, self.idle_timeout_s / 2))
            now = time.monotonic()
            for conn in list(self._conns):
                if (conn.task is None and not conn.backlog
                        and not conn.closing
                        and now - conn.last_activity
                        > self.idle_timeout_s):
                    conn.closing = True
                    conn.transport.close()

    # -- request handling ------------------------------------------------

    async def _handle(self, conn: _Conn, req: _Request):
        """Per-request envelope: assign/propagate the trace id, time
        the request, record per-route/status latency, and emit the
        access-log line (when enabled). The response logic itself lives
        in :meth:`_respond`."""
        from ray_tpu._private.config import ray_config

        t0 = time.monotonic()
        # Honor a caller-supplied trace id so an upstream LB or client
        # can stitch the request into ITS trace; mint one otherwise.
        # STRICTLY sanitized before use: the value is echoed into
        # response headers and logs, and the request parser only splits
        # on \r\n — a bare LF smuggled inside the value would otherwise
        # become response-header injection.
        supplied = (req.headers.get("x-trace-id", "")
                    if getattr(req, "headers", None) else "")
        # Reject (don't mutate): an over-length or non-token value gets
        # a fresh id — echoing a truncated id would silently break the
        # caller's correlation.
        trace_id = supplied if supplied and len(supplied) <= 64 \
            and _TRACE_ID_OK(supplied) else uuid.uuid4().hex
        # Job/tenant tag (X-Job-Id): same sanitizing as the trace id
        # (echoed into headers/logs), but never minted — an untagged
        # request falls through to the proxy process's ambient/default
        # tag, and a malformed value is dropped rather than replaced.
        raw_job = (req.headers.get("x-job-id", "")
                   if getattr(req, "headers", None) else "")
        job_id = raw_job if raw_job and len(raw_job) <= 64 \
            and _TRACE_ID_OK(raw_job) else ""
        if job_id and job_id not in self._job_tags_seen:
            if len(self._job_tags_seen) >= _MAX_JOB_TAGS:
                job_id = ""  # cardinality guard: overflow -> untagged
            else:
                self._job_tags_seen.add(job_id)
        # Model tag (X-Model): selects the weight variant on a
        # multi-model LLM deployment. Same sanitizer as the trace id
        # (echoed into logs and used as a metric tag); malformed values
        # drop to the deployment's default model.
        raw_model = (req.headers.get("x-model", "")
                     if getattr(req, "headers", None) else "")
        model = raw_model if raw_model and len(raw_model) <= 64 \
            and _TRACE_ID_OK(raw_model) else ""
        conn.trace_id = trace_id
        conn.job_id = job_id
        conn.last_status = 0
        conn.serve_path = ""
        conn.model = model
        conn.ttft_s = None
        conn.t_start = t0
        route = ""
        try:
            route = await self._respond(conn, req, trace_id, job_id,
                                        model=model)
        finally:
            latency = time.monotonic() - t0
            ttft_s = conn.ttft_s
            conn.trace_id = ""
            conn.job_id = ""
            conn.serve_path = ""
            conn.model = ""
            conn.ttft_s = None
            status = str(conn.last_status or 0)
            perf_stats.dist(
                "serve_request_seconds",
                tags={"route": route or "(unmatched)",
                      "status": status},
                bounds=perf_stats.SERVE_LATENCY_BOUNDS).record(latency)
            # Close the critical-path accumulator: attribute this
            # request's wall time to its recorded stage spans (the
            # remainder folds as "unattributed") and retain the
            # waterfall for /api/slow_requests.
            critical_path.finish_request(
                trace_id, route or "(unmatched)", status, latency)
            # Per-(job, route) request accounting — the serve half of
            # state.job_summary() and the ray_tpu_serve_requests_total
            # job-tagged series. Route prefixes bound the cardinality;
            # jobs are real tenants, also bounded.
            perf_stats.counter(
                "serve_requests",
                tags={"route": route or "(unmatched)",
                      "job": job_id}).inc()
            if ray_config.serve_access_log:
                try:
                    line = {
                        "method": getattr(req, "method", ""),
                        "route": route or "(unmatched)",
                        "path": getattr(req, "path", ""),
                        "status": conn.last_status or 0,
                        "latency_ms": round(latency * 1e3, 3),
                        "trace_id": trace_id,
                        "job_id": job_id,
                    }
                    if model:
                        line["model"] = model
                    if ttft_s is not None:
                        line["ttft_ms"] = round(ttft_s * 1e3, 3)
                    _access_log.info(json.dumps(line))
                except Exception:
                    pass  # the access log must never break serving

    async def _respond(self, conn: _Conn, req: _Request,
                       trace_id: str, job_id: str = "",
                       model: str = "") -> str:
        """Handle one parsed request; returns the matched route prefix
        (for metrics/logging)."""
        if req.error is not None:
            status, body = req.error
            conn.send_response(status, body, keep=False)
            return ""
        if req.chunked_body:
            conn.send_response(
                501, b'{"error": "chunked bodies not supported"}',
                keep=False)
            return ""
        # Ingress auth (optional shared secret), BEFORE route matching:
        # refused requests never touch the route table (no 404-based
        # route enumeration), the router, a replica slot, or the rate
        # limiter's token accounting.
        from ray_tpu._private.config import ray_config

        token = ray_config.ingress_auth_token
        if token:
            import hmac

            # Constant-time comparisons over BYTES: a shared-secret
            # check must not leak matching-prefix length through
            # response timing, and compare_digest refuses non-ASCII
            # str (latin-1-decoded headers can carry any byte).
            expect = f"Bearer {token}".encode("latin-1", "replace")
            supplied = req.headers.get(
                "authorization", "").encode("latin-1", "replace")
            alt = req.headers.get(
                "x-auth-token", "").encode("latin-1", "replace")
            token_b = token.encode("latin-1", "replace")
            if not hmac.compare_digest(supplied, expect) \
                    and not hmac.compare_digest(alt, token_b):
                self._denied += 1
                conn.send_response(
                    401, b'{"error": "missing or invalid ingress '
                    b'credentials"}', keep=req.keep_alive)
                return ""
        handle, _rest, route = self.routes.match(
            req.path.split("?", 1)[0])
        if handle is None:
            conn.send_response(404, b'{"error": "no route"}',
                               keep=req.keep_alive)
            return ""
        # Per-tenant token bucket: shed a job over its ingress rate
        # with 429 + Retry-After BEFORE work enters the router (rides
        # the same early-exit path as the 503 backpressure shed).
        retry_in = self._limiter.try_admit(job_id)
        if retry_in is not None:
            self._limited += 1
            conn.send_response(
                429, json.dumps({
                    "error": f"job {job_id or '(untagged)'} is over "
                             f"its ingress rate limit"}).encode(),
                keep=req.keep_alive, retry_after=retry_in)
            return route
        # Priority-class admission (X-Priority: high|normal|low):
        # below the hard cap, the lowest class sheds first as load
        # rises (layered fractions) and per-class rate buckets apply.
        cls = tenancy.parse_priority(req.headers.get("x-priority", ""))
        retry_in = self._priority.try_admit(cls, self._in_flight,
                                            self.max_in_flight)
        if retry_in is not None:
            self._record_shed(conn, req, route, job_id, cls,
                              retry_after=retry_in)
            return route
        if self._in_flight >= self.max_in_flight:
            # Load shed: a bounded in-flight cap with an explicit 503
            # instead of the threaded server's unbounded thread growth.
            self._record_shed(conn, req, route, job_id, cls,
                              retry_after=True)
            return route
        payload: Any = None
        if req.body:
            try:
                payload = json.loads(req.body)
            except ValueError:
                payload = req.body.decode("utf-8", "replace")
        if isinstance(payload, dict):
            # Header tags ride INSIDE the payload for deployments that
            # understand them (multi-model routing, tenant charging,
            # priority at the engine's slot shed point). Body values
            # win — headers only fill gaps.
            if model and not payload.get("model"):
                payload["model"] = model
            if job_id and not payload.get("job"):
                payload["job"] = job_id
            if req.headers.get("x-priority") and "priority" not in payload:
                payload["priority"] = cls
        self._in_flight += 1
        token = None
        try:
            args = () if payload is None else (payload,)
            # The request is the trace ROOT: the replica call's parent
            # span is the request itself, so proxy→router→replica→tasks
            # all share one trace id. The job tag rides the same
            # dispatch (None = untagged: the replica call inherits the
            # proxy's ambient/default tag instead).
            trace = (trace_id, trace_id)
            job = job_id or None
            result = None
            direct_failed = False
            for attempt in (0, 1, 2):
                # Stage boundary: accept→dispatch covers slot claim /
                # router queueing, dispatch→result the replica's work.
                t_dispatch = time.monotonic()
                # Replica-direct fast path: claim a slot in the
                # long-poll-fed table and dispatch proxy→replica —
                # no router lock, no per-request ref pruning, no
                # report RPC. Falls back to the routed path on cold
                # table / saturation / the knob being off.
                ref = None
                if attempt == 0:
                    ref, token = handle.try_direct(
                        *args, _trace=trace, _job=job)
                if ref is not None:
                    conn.serve_path = "direct"
                else:
                    # "fallback" means a DIRECT dispatch died and the
                    # request rerouted — a routed retry after a routed
                    # death stays "routed" (mislabeling it would skew
                    # the exact A/B ratio the hop counters prove).
                    conn.serve_path = "fallback" if direct_failed \
                        else "routed"
                    # Routed: a free replica slot dispatches
                    # synchronously (no coroutine machinery); only
                    # saturation parks on the async queue-wait.
                    ref = handle.try_remote(*args, _trace=trace,
                                            _job=job)
                    if ref is None:
                        ref = await handle.remote_async(
                            *args,
                            _queue_timeout_s=self.queue_timeout_s,
                            _trace=trace, _job=job)
                t_wait = time.monotonic()
                critical_path.record_stage(
                    trace_id, "proxy.dispatch", t_wait - t_dispatch,
                    route=route)
                fut = ref.as_future(self._loop)
                try:
                    # Bounded replica execution (the threaded proxy's
                    # get(timeout=60) contract): a hung deployment
                    # becomes a 500, not a request pinning its
                    # in-flight slot — and the proxy — forever.
                    result = await asyncio.wait_for(
                        fut, self.result_timeout_s)
                except asyncio.TimeoutError:
                    if not fut.cancelled():
                        # The DEPLOYMENT raised a TimeoutError (3.11+:
                        # asyncio.TimeoutError is builtin
                        # TimeoutError); wait_for only cancels the
                        # future when IT timed out. Application
                        # failure -> generic 500 below.
                        raise
                    conn.send_response(
                        500, json.dumps({
                            "error": f"no result within "
                                     f"{self.result_timeout_s}s"
                        }).encode(), keep=req.keep_alive)
                    self._served += 1
                    return route
                except ActorDiedError:
                    if attempt < 2:
                        # The dispatched replica died with the call
                        # never executed (an ActorDiedError is only
                        # ever stored for calls drained UNEXECUTED
                        # from the mailbox — an executing call runs to
                        # completion — so a re-dispatch cannot
                        # double-execute): drop the replica from the
                        # direct table AND the router's list ahead of
                        # long-poll, then retry through the routed
                        # path. One extra bounded retry covers the
                        # window where the router's own snapshot still
                        # carried a second dying replica.
                        if token is not None:
                            handle.direct_invalidate(token)
                            token = None
                            direct_failed = True
                            # The fallback event IS the direct
                            # dispatch dying — counted here, once.
                            membership.hop_counter("fallback").inc()
                            self._fallbacks += 1
                        continue
                    raise
                # The dispatch→result window is deliberately NOT
                # recorded as a stage: downstream spans (replica
                # execute, LLM prefill/decode) explain it, and a
                # wrapper stage would out-rank every stage nested
                # inside it in the dominant-stage ranking. Whatever
                # downstream doesn't explain folds as "unattributed".
                break
            if token is not None:
                self._direct_served += 1
            if is_stream(result):
                await self._stream_response(conn, req, result,
                                            route=route, model=model)
            else:
                # Non-stream LLM responses carry their engine-measured
                # TTFT; fold it into the same series the SSE path feeds.
                if isinstance(result, dict) and \
                        isinstance(result.get("ttft_s"), float):
                    conn.ttft_s = result["ttft_s"]
                    self._record_ttft(conn.ttft_s, route, model)
                conn.send_response(200, json.dumps(result).encode(),
                                   keep=req.keep_alive)
            self._served += 1
        except QueueSaturatedError as e:
            # Router queue saturated: no replica slot within the queue
            # timeout. Shed with Retry-After, same as the in-flight
            # cap. A TimeoutError raised BY the deployment does NOT
            # land here — that's an application failure (500 below).
            self._shed += 1
            conn.send_response(503,
                               json.dumps({"error": str(e)}).encode(),
                               keep=req.keep_alive, retry_after=True)
        except Exception as e:  # noqa: BLE001
            conn.send_response(500,
                               json.dumps({"error": str(e)}).encode(),
                               keep=req.keep_alive)
            self._served += 1
        finally:
            self._in_flight -= 1
            if token is not None:
                # Slot release is the completion edge of the direct
                # path (streams included: the stream handle resolved).
                handle.direct_release(token)
        return route

    def _record_shed(self, conn: _Conn, req: _Request, route: str,
                     job_id: str, cls: int, retry_after) -> None:
        """One load-shed 503: send the response AND account the shed at
        the shed point — ``serve_requests_shed{route,job,class}`` plus
        the ``serve_request_seconds{route,status="503"}`` /
        job-tagged request records the enclosing ``_handle`` writes —
        so per-job accounting and the (status-aware) SLO burn see
        shedding the moment it happens, not only when saturation
        reaches the router."""
        self._shed += 1
        perf_stats.counter(
            "serve_requests_shed",
            tags={"route": route or "(unmatched)", "job": job_id,
                  "class": tenancy.PRIORITY_CLASSES[
                      min(cls, len(tenancy.PRIORITY_CLASSES) - 1)]}).inc()
        conn.send_response(503, b'{"error": "server overloaded"}',
                           keep=req.keep_alive, retry_after=retry_after)

    @staticmethod
    def _record_ttft(ttft_s: float, route: str, model: str) -> None:
        """ray_tpu_serve_ttft_seconds{route,model} — the LLM serving
        SLO number: request arrival at the proxy to the first token on
        the wire (SSE) or the engine's first-token stamp (unary)."""
        perf_stats.dist(
            "serve_ttft_seconds",
            tags={"route": route or "(unmatched)",
                  "model": model or "(default)"},
            bounds=perf_stats.SERVE_LATENCY_BOUNDS).record(ttft_s)

    async def _stream_response(self, conn: _Conn, req: _Request, result,
                               route: str = "", model: str = ""):
        """Server-sent events with chunked transfer-encoding: the client
        sees each chunk as produced AND the connection stays usable for
        the next request (the threaded proxy had to Connection: close
        here, killing keep-alive for every streamed reply). HTTP/1.0
        clients can't parse chunked framing, so they fall back to a
        close-delimited body."""
        chunked = req.version != "HTTP/1.0"
        keep = req.keep_alive and chunked
        headers = [("Content-Type", "text/event-stream"),
                   ("Cache-Control", "no-cache")]
        if chunked:
            headers.append(("Transfer-Encoding", "chunked"))
        if not keep:
            headers.append(("Connection", "close"))
        conn.send_header_block(200, headers)
        try:
            async for chunk in aiter_stream(result):
                if conn.ttft_s is None:
                    # First token on the wire: the streaming TTFT stamp.
                    conn.ttft_s = time.monotonic() - conn.t_start
                    self._record_ttft(conn.ttft_s, route, model)
                conn.write_body(
                    b"data: " + json.dumps(chunk).encode() + b"\n\n",
                    chunked)
                await conn.drain()
                if conn.closing:  # client went away mid-stream
                    return
            conn.write_body(b"data: [DONE]\n\n", chunked)
        except Exception as stream_err:  # noqa: BLE001
            # Headers already sent: a mid-stream failure must become an
            # error *event*, never a 500 status line spliced into the
            # SSE body.
            conn.write_body(
                b"data: " + json.dumps(
                    {"error": str(stream_err)}).encode()
                + b"\n\ndata: [DONE]\n\n", chunked)
        if conn.closing:
            return
        if chunked:
            conn.transport.write(b"0\r\n\r\n")
        if not keep:
            conn.closing = True
            conn.transport.close()

    # -- observability / lifecycle --------------------------------------

    def stats(self) -> Dict[str, int]:
        """Ingress counters. ``served`` counts requests that reached a
        handler and got a terminal non-shed response (2xx/5xx);
        ``shed_503`` counts load-shed requests (in-flight cap or router
        queue timeout) — the two are disjoint."""
        return {"in_flight": self._in_flight, "served": self._served,
                "shed_503": self._shed, "limited_429": self._limited,
                "denied_401": self._denied,
                "direct_served": self._direct_served,
                "direct_fallbacks": self._fallbacks,
                "open_connections": len(self._conns)}

    def shutdown(self):
        _PROXIES.discard(self)
        if self._loop.is_closed():
            return

        def _stop():
            for conn in list(self._conns):
                try:
                    conn.closing = True
                    conn.transport.close()
                except Exception:
                    pass
            self._reaper.cancel()
            self._server.close()
            self._loop.stop()

        try:
            self._loop.call_soon_threadsafe(_stop)
        except RuntimeError:
            return
        self._thread.join(timeout=10)
